"""System benchmark: the BASELINE.json workloads through the REAL stack.

Every config drives Field.import_bits/import_values -> Executor +
MeshPlanner (and one config through the HTTP server) — not a raw kernel.
Reference analog: end-to-end PQL QPS via api.Query (api.go:135) over
executor.go's mapReduce.

Configs (BASELINE.json):
  1. star-trace     Count(Intersect(Row,Row)) over a 1B-col set index —
                    THE headline metric; pipelined QPS via a thread pool
                    + sequential p50 latency. Also measured through HTTP.
  2. topn           TopN over a 1M-row x 10M-col field (ranked-cache
                    analog: generation-cached exact counts) + a filtered
                    TopN (streamed device counts).
  3. bsi            Sum / Min / Range-filtered Count on an int field
                    (100M cols) through the planner's stacked BSI folds.
  4. time-quantum   Row(f, from, to) + Count over YMDH views.
  5. cluster        4-node in-process cluster (PQL-serialized node
                    boundary): GroupBy + Count over a sharded index.
  8. overload       3-node replicated cluster at 4x admission
                    oversubscription with one slow (gray) peer: admitted
                    p50/p99, shed rate, hedge fire/win rate, and breaker
                    transitions — the overload-resilience layer under
                    its design load.

CPU baseline: the reference publishes no absolute numbers and this image
has no Go toolchain, so the baseline is measured here as the strongest
honest stand-in for roaring's intersectionCountBitmapBitmap
(roaring.go:3121): the native C++ fused popcount(a & b) kernel
(-O3 -march=native POPCNT), run single-threaded AND with one thread per
core over per-shard blocks (the goroutine worker-pool analog,
executor.go:2561). vs_baseline uses the THREADED number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Env knobs: BENCH_COLS (default 1e9), BENCH_QUERIES, BENCH_CONFIGS
(comma list / "all"), BENCH_THREADS.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

N_COLS = int(os.environ.get("BENCH_COLS", 1_000_000_000))
N_QUERIES = int(os.environ.get("BENCH_QUERIES", 256))
N_LAT = int(os.environ.get("BENCH_LAT_QUERIES", 30))
THREADS = int(os.environ.get("BENCH_THREADS", 32))
CONFIGS = os.environ.get("BENCH_CONFIGS", "all")
DENSITY = float(os.environ.get("BENCH_DENSITY", 0.05))


#: quarter-octave log buckets (1e-5 s .. ~20 s): fine enough that the
#: interpolated quantile sits within a few percent of the nearest-rank
#: value the old private lists produced, while staying O(buckets) no
#: matter how many samples a bench takes.
_BENCH_BOUNDS = tuple(1e-5 * (2 ** (i / 4)) for i in range(84))


def _hist():
    """A fresh latency histogram (seconds). Benches accumulate into
    these instead of private lists — same bounded LogHistogram the
    server's stats registry uses."""
    from pilosa_tpu.obs.histogram import LogHistogram
    return LogHistogram(_BENCH_BOUNDS)


def _p99(lat_s):
    """p99 in ms from a LogHistogram of second-latencies (an iterable
    of seconds is folded into one first)."""
    h = lat_s if hasattr(lat_s, "quantile") else _observed(lat_s)
    return h.quantile(0.99) * 1e3


def _p50(h):
    """p50 in ms from a LogHistogram of second-latencies."""
    return h.quantile(0.50) * 1e3


def _observed(lat_s):
    h = _hist()
    for v in lat_s:
        h.observe(v)
    return h


def _timer(fn, n, threads=1):
    """(qps, p50_ms, p99_ms) over n calls; threads>1 = pipelined
    throughput. Tail latency comes from the sequential sample (the
    threaded phase measures occupancy, not per-call service time)."""
    h = _hist()
    for _ in range(min(n, N_LAT)):
        t0 = time.perf_counter()
        fn()
        h.observe(time.perf_counter() - t0)
    p50 = _p50(h)
    p99 = _p99(h)
    if threads <= 1:
        qps = 1e3 / p50 if p50 else float("inf")
        return qps, p50, p99
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        list(pool.map(lambda _: fn(), range(n)))
    dt = time.perf_counter() - t0
    return n / dt, p50, p99


def _rand_positions(rng, n_bits, n_cols):
    return rng.integers(0, n_cols, n_bits, dtype=np.uint64)


# ---------------------------------------------------------------------------
# config 1: star-trace headline — 1B cols through Executor + MeshPlanner
# ---------------------------------------------------------------------------


def bench_star_trace(extra):
    import jax

    from pilosa_tpu import native
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    n_shards = (N_COLS + SHARD_WIDTH - 1) // SHARD_WIDTH
    n_bits = int(N_COLS * DENSITY)
    rng = np.random.default_rng(7)

    # Persistent compile cache ON for the whole bench so the
    # second-boot series below measures disk-cache reloads, the same
    # thing a restarted node pays. Enabled before the first compile so
    # every program of boot 1 gets persisted.
    import tempfile

    from pilosa_tpu.parallel import compile_cache
    cc_dir = (os.environ.get("PILOSA_TPU_BENCH_COMPILE_CACHE")
              or tempfile.mkdtemp(prefix="pilosa-compile-cache-"))
    extra["compile_cache_enabled"] = compile_cache.enable(cc_dir)

    h = Holder()
    idx = h.create_index("bench")
    f = idx.create_field("f")
    g = idx.create_field("g")

    # Timed window covers import_bits only (generating 800 MB of random
    # positions is setup, not import). Row ids are broadcast views and
    # each position array is dropped after its import: resident-set
    # bloat makes every fresh page fault dramatically slower on this
    # virtualized host, which is allocator noise, not import cost.
    row1 = np.broadcast_to(np.uint64(1), n_bits)
    row2 = np.broadcast_to(np.uint64(2), n_bits)
    fpos = _rand_positions(rng, n_bits, N_COLS)
    t0 = time.perf_counter()
    f.import_bits(row1, fpos)
    import_s = time.perf_counter() - t0
    gpos = _rand_positions(rng, n_bits, N_COLS)
    t0 = time.perf_counter()
    g.import_bits(row2, gpos)
    import_s += time.perf_counter() - t0
    del gpos
    # Median of 3 like the BSI metrics: identical imports on this
    # shared vCPU swing 2x with scheduler luck, and a single-shot
    # number inherits whatever minute the host was having (observed
    # 57-122 Mbit/s for the same code). Extra trials land in throwaway
    # fields re-importing fpos; the f/g fields above stay for the
    # query benchmarks.
    rates = [2 * n_bits / import_s / 1e6]
    for t in range(2):
        ft = idx.create_field(f"imp{t}")
        t0 = time.perf_counter()
        ft.import_bits(row1, fpos)
        rates.append(n_bits / (time.perf_counter() - t0) / 1e6)
        idx.delete_field(f"imp{t}")
    del fpos
    extra["import_mbits_per_s"] = round(statistics.median(rates), 1)

    # ---- CPU baselines over the same dense blocks ----
    blocks_f = [h.fragment("bench", "f", "standard", s) for s in range(n_shards)]
    blocks_g = [h.fragment("bench", "g", "standard", s) for s in range(n_shards)]
    words_f = [fr.row_words(1) for fr in blocks_f]
    words_g = [fr.row_words(2) for fr in blocks_g]

    def cpu_shard(s):
        return native.intersection_count_words(words_f[s], words_g[s])

    t0 = time.perf_counter()
    expected = sum(cpu_shard(s) for s in range(n_shards))
    cpu1_dt = time.perf_counter() - t0
    n_cpu = os.cpu_count() or 1
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=n_cpu) as pool:
        got = sum(pool.map(cpu_shard, range(n_shards)))
    cpu_mt_dt = time.perf_counter() - t0
    assert got == expected
    cpu_qps = 1.0 / cpu_mt_dt
    extra["cpu_1thread_qps"] = round(1.0 / cpu1_dt, 2)
    extra["cpu_threaded_qps"] = round(cpu_qps, 2)
    extra["cpu_threads"] = n_cpu
    # Falsifiability (VERDICT r4 weak #5): this rig's CPU is a single
    # shared vCPU, so vs_baseline is honest for THIS host but is NOT
    # "10x a many-core server running the Go reference". The
    # load-bearing comparisons are the paired same-run ratios below
    # (executor_vs_kernel_delivered, pallas_vs_xla).
    extra["cpu_note"] = (
        f"baseline = native C++ popcount kernel on this rig's "
        f"{n_cpu}-thread shared vCPU; not a many-core reference host")

    # ---- device link characterization ----
    # On this deployment the TPU sits behind a tunnel: ONE synchronous
    # device->host pull costs ~100ms of link latency no matter how small
    # the array. Every metric below that needs a device sync is bounded
    # by this floor; the system answers are (a) the TransferBatcher --
    # concurrent queries share one stacked transfer per wave -- and (b)
    # the epoch-invalidated result cache for repeated reads.
    import jax.numpy as jnp

    _tiny = jax.device_put(np.arange(8, dtype=np.int32))
    _sumf = jax.jit(lambda v: jnp.sum(v))
    int(_sumf(_tiny))
    floors = []
    for _ in range(3):
        t0 = time.perf_counter()
        int(_sumf(_tiny))
        floors.append(time.perf_counter() - t0)
    extra["device_sync_floor_ms"] = round(
        statistics.median(floors) * 1e3, 2)

    # ---- executor + planner path ----
    shards = list(range(n_shards))
    planner = MeshPlanner(h, make_mesh())
    ex = Executor(h, planner=planner)
    q = "Count(Intersect(Row(f=1), Row(g=2)))"

    (got,) = ex.execute("bench", q, shards=shards)
    assert got == expected, (got, expected)

    # Pipelined throughput through the FULL stack (parse, cache check,
    # translate, planner, batcher), result cache bypassed so every query
    # runs its device program and delivers its count to the host.
    # Measured in blocks INTERLEAVED with the delivered-kernel baseline
    # below: the tunnel's throughput drifts 2-4x minute to minute, so
    # sequential measurement makes the executor/kernel ratio an artifact
    # of WHEN each side ran, not of host overhead (r3's shipped 0.31x
    # "gap" was exactly this).
    ex.execute("bench", q, shards=shards, cache=False)  # warm async path

    def run_executor_block(n):
        t0 = time.perf_counter()
        futs = [ex.execute_async("bench", q, shards=shards, cache=False)
                for _ in range(n)]
        results = [f.result() for f in futs]
        dt = time.perf_counter() - t0
        assert all(r == [expected] for r in results)
        return n / dt

    # Sequential latency: cold (one full device round-trip per query,
    # floor-bound by the link) and cached (the system behavior for any
    # repeated read until the next write).
    h = _hist()
    for _ in range(min(N_LAT, 15)):
        t0 = time.perf_counter()
        ex.execute("bench", q, shards=shards, cache=False)
        h.observe(time.perf_counter() - t0)
    extra["executor_count_intersect_cold_p50_ms"] = round(_p50(h), 2)
    h = _hist()
    for _ in range(N_LAT):
        t0 = time.perf_counter()
        ex.execute("bench", q, shards=shards)
        h.observe(time.perf_counter() - t0)
    p50 = _p50(h)
    extra["executor_count_intersect_p50_ms"] = round(p50, 3)
    extra["cols"] = n_shards * SHARD_WIDTH

    # Raw-kernel continuity number (r1's measure): pipelined, no executor.
    a = planner._stack_rows(idx, "f", "standard", 1, tuple(shards))
    b = planner._stack_rows(idx, "g", "standard", 2, tuple(shards))

    import jax.numpy as jnp

    @jax.jit
    def kernel(x, y):
        return jnp.sum(
            jax.lax.population_count(jnp.bitwise_and(x, y)).astype(jnp.int32),
            axis=-1)

    jax.block_until_ready(kernel(a, b))
    t0 = time.perf_counter()
    outs = [kernel(a, b) for _ in range(N_QUERIES)]
    jax.block_until_ready(outs)
    extra["raw_kernel_qps"] = round(N_QUERIES / (time.perf_counter() - t0), 1)

    # Shared delivered-rate plumbing for the Pallas A/B and the
    # kernel-delivered baseline below.
    from pilosa_tpu.parallel.batcher import TransferBatcher

    bt = TransferBatcher()
    post = lambda host: int(host.astype(np.int64).sum())  # noqa: E731
    bt.submit(kernel(a, b), post).result()  # warm the batcher's
    # resolver thread + first host-pull path BEFORE any measured block
    # (a cold first block would bias whichever side runs first).

    # ---- Pallas-vs-XLA A/B on chip (VERDICT r4 weak #8) ----
    # The kernel layer's own contribution, measured: the SAME fused
    # popcount(a & b) through the Pallas grid kernel and through plain
    # XLA, as counts DELIVERED to the host through the shared batcher
    # above, fresh jit wrappers per side so neither inherits the
    # other's trace. Runs only where the Pallas path is real (TPU
    # backend); CPU interpret mode would measure the interpreter, not
    # the kernel.
    from pilosa_tpu.ops import pallas_kernels as pk
    if pk._DISABLED:
        # Operator forced the XLA path (PILOSA_TPU_NO_PALLAS=1, the
        # documented escape hatch for a broken Pallas build); never
        # override that — record why the A/B is absent instead.
        extra["pallas_ab_note"] = "skipped: PILOSA_TPU_NO_PALLAS=1"
    elif pk._HAVE_PALLAS and jax.default_backend() == "tpu":
        # _DISABLED is read at TRACE time: compile each side once under
        # its own setting (fresh lambdas = separate jit caches), restore
        # the flag, then alternate measurement blocks with the prebuilt
        # executables.
        old = pk._DISABLED
        try:
            pk._DISABLED = False
            pallas_fn = jax.jit(lambda x, y: pk.pair_count(x, y, "and"))
            ref = jax.block_until_ready(pallas_fn(a, b))
            assert int(np.asarray(ref).astype(np.int64).sum()) == expected
            pk._DISABLED = True
            xla_fn = jax.jit(lambda x, y: pk.pair_count(x, y, "and"))
            ref = jax.block_until_ready(xla_fn(a, b))
            assert int(np.asarray(ref).astype(np.int64).sum()) == expected
        finally:
            pk._DISABLED = old

        # DELIVERED rate through the shared batcher below (the same
        # plumbing the kernel-delivered baseline and the executor use):
        # the enqueue+block form drifts wildly with link weather
        # (recorded 1.43x and 0.53x for identical code on this rig);
        # counts-on-host is the stable, falsifiable comparison and
        # matches how the kernel is consumed in production.
        def rate(fn) -> float:
            t0 = time.perf_counter()
            futs = [bt.submit(fn(a, b), post)
                    for _ in range(N_QUERIES)]
            vals = [f.result() for f in futs]
            assert vals[0] == expected
            return N_QUERIES / (time.perf_counter() - t0)

        # Alternate sides so link weather cancels in the ratio.
        ps, xs = [], []
        for i in range(4):
            if i % 2:
                xs.append(rate(xla_fn))
                ps.append(rate(pallas_fn))
            else:
                ps.append(rate(pallas_fn))
                xs.append(rate(xla_fn))
        # The RATIO is the load-bearing number — paired blocks ride the
        # same link weather, so drift cancels.
        extra["pallas_pair_count_delivered_qps"] = round(
            statistics.median(ps), 1)
        extra["xla_pair_count_delivered_qps"] = round(
            statistics.median(xs), 1)
        extra["pallas_vs_xla"] = round(
            statistics.median(ps) / statistics.median(xs), 3)

    # raw_kernel_qps (enqueue-only, above the A/B) is NOT a query rate:
    # nothing forces each call's result off the device, and the tunnel
    # pipelines/elides, so its absolute value drifts run to run. The
    # honest kernel ceiling is "counts delivered to the host" through
    # the same batcher the executor uses — bare kernel + transfer, zero
    # executor logic — which the Pallas A/B above also measures through
    # (the batcher was warmed before the first measured block).

    def run_kernel_block(n):
        t0 = time.perf_counter()
        futs = [bt.submit(kernel(a, b), post) for _ in range(n)]
        vals = [f.result() for f in futs]
        dt = time.perf_counter() - t0
        assert vals[0] == expected
        return n / dt

    # Paired A/B blocks: executor and bare-kernel alternate through the
    # same link weather. The executor/kernel comparison is the MEDIAN OF
    # PER-PAIR RATIOS — adjacent blocks see near-identical link state,
    # so each ratio cancels the drift that a ratio-of-medians (or r3's
    # fully sequential measurement, which shipped a phantom 0.31x "gap")
    # soaks up. Within-pair order alternates to kill the residual bias.
    # Full-size blocks: throughput scales with in-flight depth on this
    # link (64-query bursts deliver ~½ of 256-query bursts — the wave
    # pipeline amortizes the round-trip over everything in flight), so
    # undersized blocks would understate both sides.
    ex_qps, kern_qps, ratios = [], [], []
    block = N_QUERIES
    for i in range(8):
        if i % 2:
            k = run_kernel_block(block)
            e = run_executor_block(block)
        else:
            e = run_executor_block(block)
            k = run_kernel_block(block)
        ex_qps.append(e)
        kern_qps.append(k)
        ratios.append(e / k)
    qps = statistics.median(ex_qps)
    extra["executor_count_intersect_qps"] = round(qps, 1)
    extra["kernel_delivered_qps"] = round(statistics.median(kern_qps), 1)
    extra["executor_vs_kernel_delivered"] = round(
        statistics.median(ratios), 3)

    # ---- second boot (executor path): persistent compile cache ----
    # clear_caches() drops every in-memory executable — exactly what a
    # process restart loses — while the on-disk cache survives; a fresh
    # planner then re-traces the same kernels and loads them from disk
    # instead of recompiling. The hit counter (not wall clock) is the
    # proof the reload actually happened.
    cc_before = compile_cache.stats()
    jax.clear_caches()
    planner2 = MeshPlanner(h, make_mesh())
    ex2 = Executor(h, planner=planner2)
    t0 = time.perf_counter()
    (got2,) = ex2.execute("bench", q, shards=shards, cache=False)
    extra["executor_count_intersect_second_boot_first_ms"] = round(
        (time.perf_counter() - t0) * 1e3, 2)
    assert got2 == expected, (got2, expected)
    h = _hist()
    for _ in range(min(N_LAT, 15)):
        t0 = time.perf_counter()
        ex2.execute("bench", q, shards=shards, cache=False)
        h.observe(time.perf_counter() - t0)
    p50_2boot = _p50(h)
    extra["executor_count_intersect_second_boot_cold_p50_ms"] = round(
        p50_2boot, 2)
    cc_after = compile_cache.stats()
    extra["executor_compile_cache_hits"] = (
        cc_after["hits"] - cc_before["hits"])
    extra["executor_cold_vs_warm_ratio"] = round(
        p50_2boot / max(p50, 1e-3), 2)
    planner2.close()

    # ---- one pass through HTTP (config-1 surface parity) ----
    # The HTTP bench spawns child server processes and times their first
    # queries; the 1B-col star working set still held here (host row
    # words, device leaf stacks, planner HBM cache) is enough memory/CPU
    # pressure to distort the children's compile+serve timings. Drop it
    # before spawning.
    bt.close()
    del run_kernel_block, run_executor_block, post, kernel
    del a, b, bt, ex, planner, ex2, planner2
    del words_f, words_g, blocks_f, blocks_g, f, g, idx, h
    import gc
    gc.collect()
    try:
        _bench_http(extra, expected)
    except Exception as e:  # pragma: no cover - diagnostics only
        extra["http_error"] = repr(e)
    return qps, cpu_qps


def _bench_http(extra, expected):
    """Small-scale Count through the real HTTP server (32M cols)."""
    import socket
    import subprocess
    import tempfile
    import urllib.request

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    d = tempfile.mkdtemp()
    # First boot: warmup OFF so the first query measures today's cold
    # path (XLA compile + link through the full REST stack). A second
    # boot below, warmup ON over the same data dir, measures what the
    # warmed first query costs — the QoS warmup service's whole point.
    env = dict(os.environ)
    env["PILOSA_TPU_QOS_WARMUP"] = ""

    def spawn(e):
        return subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "--bind", f"127.0.0.1:{port}", "--data-dir", d],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=e)

    proc = spawn(env)
    base = f"http://127.0.0.1:{port}"

    def post(path, body=""):
        r = urllib.request.Request(base + path, data=body.encode(),
                                   method="POST")
        return json.loads(urllib.request.urlopen(r, timeout=60).read()
                          or b"{}")

    def get(path):
        return json.loads(
            urllib.request.urlopen(base + path, timeout=10).read() or b"{}")

    def wait_up():
        for _ in range(200):
            try:
                urllib.request.urlopen(base + "/status", timeout=1)
                return
            except Exception:
                time.sleep(0.25)

    try:
        wait_up()
        post("/index/b")
        post("/index/b/field/f")
        post("/index/b/field/g")
        from pilosa_tpu.config import SHARD_WIDTH
        cols = 32 * SHARD_WIDTH
        n_bits = cols // 20
        rng = np.random.default_rng(11)
        for fld, rid in (("f", 1), ("g", 2)):
            body = json.dumps({
                "rowIDs": [rid] * n_bits,
                "columnIDs": rng.integers(0, cols, n_bits).tolist()})
            post(f"/index/b/field/{fld}/import", body)
        q = "Count(Intersect(Row(f=1), Row(g=2)))"

        # Persistent (keep-alive) connections, one per worker thread —
        # the server speaks HTTP/1.1; paying a TCP handshake per query
        # would measure the client, not the server.
        import http.client
        import threading as _threading
        tls = _threading.local()
        host, p = base.replace("http://", "").split(":")

        def connect():
            conn = tls.conn = http.client.HTTPConnection(host, int(p),
                                                         timeout=60)
            conn.connect()
            # Nagle + delayed-ACK adds ~40ms to every small POST
            # (headers and body go in separate writes).
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
            return conn

        def make_runner(path):
            def run():
                conn = getattr(tls, "conn", None)
                if conn is None:
                    conn = connect()
                try:
                    conn.request("POST", path, q.encode())
                    resp = conn.getresponse()
                    return json.loads(resp.read())
                except (http.client.HTTPException, OSError):
                    tls.conn = None
                    raise
            return run

        run = make_runner("/index/b/query")

        # First-query cost through a PRE-CONNECTED socket: today this
        # pays the cold XLA compile + leaf-stack upload; the warmed
        # restart below measures the same window with the compile
        # already done. Handshake stays outside both timed windows.
        connect()
        t0 = time.perf_counter()
        warm = run()
        extra["http_count_first_cold_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        # r2 silently counted an EMPTY index here (wrong wire field
        # names); never trust an unasserted benchmark query.
        assert warm["results"][0] > 0, warm
        qps, p50, p99 = _timer(run, 256, threads=8)
        extra["http_count_qps_32m"] = round(qps, 1)
        extra["http_count_p50_ms_32m"] = round(p50, 3)
        extra["http_count_p99_ms_32m"] = round(p99, 3)

        # Cold REST path (VERDICT r4 #10): cache bypassed server-side,
        # so every request runs its device program through the full
        # stack — what a real FIRST query costs end to end.
        run_cold = make_runner("/index/b/query?noCache=true")
        assert run_cold() == warm
        _, p50c, p99c = _timer(run_cold, 12)
        extra["http_count_cold_p50_ms"] = round(p50c, 3)
        extra["http_count_cold_p99_ms"] = round(p99c, 3)

        # QoS shed/deadline counters from the steady-state run (expected
        # 0 with the default generous bounds — nonzero means the gate
        # bit during the bench and the numbers above include queueing).
        dv = get("/debug/vars")
        counters = dv.get("counters", {})
        extra["http_qos_sheds"] = sum(
            v for k, v in counters.items() if k.startswith("qos.shed"))
        extra["http_qos_deadline_misses"] = sum(
            v for k, v in counters.items()
            if k.startswith("qos.deadlineMiss"))

        # ---- warmed restart: same data dir, kernel warmup ON ----
        proc.terminate()
        proc.wait(timeout=15)
        env2 = dict(os.environ)
        env2["PILOSA_TPU_QOS_WARMUP"] = "count"
        proc = spawn(env2)
        wait_up()
        # Warmup runs in the background; wait for it to finish so the
        # first query below measures the warmed path, not a race.
        for _ in range(240):
            counters = get("/debug/vars").get("counters", {})
            if counters.get("qos.warmupRuns", 0) >= 1:
                break
            time.sleep(0.25)
        tls.conn = None  # old keep-alive socket died with the old server
        connect()
        t0 = time.perf_counter()
        first = run()
        extra["http_count_first_warm_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        assert first == warm, (first, warm)
        cold_ms = extra["http_count_first_cold_ms"]
        extra["http_warmup_speedup"] = round(
            cold_ms / max(extra["http_count_first_warm_ms"], 1e-3), 1)

        # ---- second-boot cold series + compile-cache accounting ----
        # The restarted server reused the same data dir, so its planner
        # (and warmup replay) read the persistent compile cache written
        # by boot 1; the hit counters are the deterministic proof, the
        # cold p50 is what the reload is worth on this link.
        counters = get("/debug/vars").get("counters", {})
        extra["compile_cache_hits"] = int(
            counters.get("compileCache.hits", 0))
        extra["compile_cache_requests"] = int(
            counters.get("compileCache.requests", 0))
        extra["warmup_cache_hits"] = int(
            counters.get("qos.warmupCacheHits", 0))
        run_cold2 = make_runner("/index/b/query?noCache=true")
        assert run_cold2() == warm
        _, p50c2, p99c2 = _timer(run_cold2, 12)
        extra["http_count_second_boot_cold_p50_ms"] = round(p50c2, 3)
        extra["http_count_second_boot_cold_p99_ms"] = round(p99c2, 3)
        extra["cold_vs_warm_ratio"] = round(
            p50c2 / max(extra["http_count_p50_ms_32m"], 1e-3), 2)
    finally:
        proc.terminate()
        proc.wait(timeout=15)


# ---------------------------------------------------------------------------
# config 2: TopN 1M rows x 10M cols
# ---------------------------------------------------------------------------


def bench_oversubscribed(extra):
    """QPS when the leaf working set EXCEEDS the planner's HBM stack
    budget (VERDICT r4 #3): the same query mix runs once fully resident
    and once with a budget holding half the leaves, so every sweep
    evicts and re-uploads under LRU churn — the two-tier hot-dense /
    cold-host story's cost, measured. Reference role: roaring mmap
    paging (roaring/roaring.go:1437 RemapRoaringStorage).

    Swept at 1x/2x/4x working-set-to-budget ratios, A/B'd dense vs the
    container-classed packed residency (exec/residency) with pipelined
    prefetch — the headline `oversubscribed_vs_resident@2x` is the
    packed leg; the `_dense@` keys keep the old cliff visible."""
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.exec import residency as _residency
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    n_shards, n_rows = 64, 16
    total = n_shards * SHARD_WIDTH
    rng = np.random.default_rng(11)
    h = Holder()
    idx = h.create_index("over")
    f = idx.create_field("f")
    for r in range(n_rows):
        cols = rng.integers(0, total, 20_000)
        f.import_bits(np.full(len(cols), r, dtype=np.uint64), cols)
    shards = list(range(n_shards))
    mesh = make_mesh()
    s_pad = ((n_shards + len(mesh.devices.reshape(-1)) - 1)
             // len(mesh.devices.reshape(-1))) * len(mesh.devices.reshape(-1))
    stack_bytes = _residency.dense_nbytes(s_pad)
    extra["oversub_stack_mb"] = round(stack_bytes / 1e6, 1)
    extra["oversub_working_set_mb"] = round(n_rows * stack_bytes / 1e6, 1)

    oracle = {}
    scalar = Executor(h)
    for r in range(n_rows):
        (oracle[r],) = scalar.execute("over", f"Count(Row(f={r}))",
                                      shards=shards)

    def sweep_qps(budget_bytes, sweeps, packed):
        os.environ["PILOSA_TPU_RESIDENCY_PACKED"] = packed
        planner = MeshPlanner(h, mesh, max_cache_bytes=budget_bytes)
        ex = Executor(h, planner=planner, result_cache=False)
        for r in range(n_rows):  # warm compile + (maybe) cache
            (got,) = ex.execute("over", f"Count(Row(f={r}))", shards=shards)
            assert got == oracle[r], (r, got, oracle[r])
        t0 = time.perf_counter()
        n = 0
        for _ in range(sweeps):
            futs = [ex.execute_async("over", f"Count(Row(f={r}))",
                                     shards=shards)
                    for r in range(n_rows)]
            for r, fut in enumerate(futs):
                assert fut.result() == [oracle[r]]
            n += n_rows
        dt = time.perf_counter() - t0
        st = planner.cache_stats()
        pf = planner.prefetcher.debug()
        planner.close()
        return n / dt, st, pf

    saved_mode = os.environ.get("PILOSA_TPU_RESIDENCY_PACKED")
    try:
        # Fully-resident dense baseline: the denominator for every ratio.
        resident_qps, _, _ = sweep_qps(2 * n_rows * stack_bytes, sweeps=3,
                                       packed="off")
        extra["resident_count_qps"] = round(resident_qps, 1)

        ws_bytes = n_rows * stack_bytes
        for x in (1, 2, 4):  # working set = x * device budget
            dense_qps, st_d, pf_d = sweep_qps(ws_bytes // x, sweeps=3,
                                              packed="off")
            packed_qps, st_p, pf_p = sweep_qps(ws_bytes // x, sweeps=3,
                                               packed="auto")
            extra[f"oversubscribed_vs_resident_dense@{x}x"] = round(
                dense_qps / resident_qps, 3)
            extra[f"oversubscribed_vs_resident@{x}x"] = round(
                packed_qps / resident_qps, 3)
            if x != 2:
                continue
            # the 2x point is the historical BENCH_r05 regime: keep the
            # legacy key (now the packed+prefetch leg) and prove the
            # dense leg really churned.
            extra["oversubscribed_vs_resident"] = (
                extra["oversubscribed_vs_resident@2x"])
            extra["oversubscribed_count_qps"] = round(dense_qps, 1)
            assert st_d["bytes"] <= st_d["budget_bytes"]
            assert st_d["entries"] <= n_rows // 2
            assert st_d["evictions"] > 0  # the metric really measured churn
            extra["oversub_evictions"] = st_d["evictions"]
            # the pipelined miss path: dense churn leg's misses are all
            # absorbed by inflight prefetch uploads.
            extra["oversub_prefetch_hits"] = pf_d["hits"]
            extra["oversub_prefetch_sync_misses"] = pf_d["sync_misses"]
            extra["oversub_prefetch_overlap_ms"] = round(
                pf_d["overlap_ms"], 1)
            # density of what a device-GB holds, per representation
            # class: SET columns of this working set per resident GB
            # (padding included) — the packed/dense ratio is the
            # compression the class taxonomy buys at this sparsity.
            extra["resident_columns_per_gb_dense"] = int(
                sum(oracle.values()) / (n_rows * stack_bytes) * 1e9)
            packed_bytes = st_p["class_bytes"][_residency.PACKED]
            if packed_bytes:
                extra["resident_columns_per_gb_packed"] = int(
                    sum(oracle.values()) / packed_bytes * 1e9)
    finally:
        if saved_mode is None:
            os.environ.pop("PILOSA_TPU_RESIDENCY_PACKED", None)
        else:
            os.environ["PILOSA_TPU_RESIDENCY_PACKED"] = saved_mode

    # ---- tail latency + QoS under the same churn regime ----
    # Individually-timed sync queries through a tight admission gate
    # while a batch-class flood oversubscribes it: what an admitted
    # interactive query's p50/p99 looks like when the node is saturated
    # and the queue bound is doing its job (sheds + deadline misses
    # recorded rather than unbounded queueing).
    from pilosa_tpu.qos import (AdmissionController, Deadline,
                                DeadlineExceededError, QueryShedError,
                                reset_current_deadline,
                                set_current_deadline)
    planner = MeshPlanner(h, mesh, max_cache_bytes=(n_rows // 2) * stack_bytes)
    ex = Executor(h, planner=planner, result_cache=False)
    for r in range(n_rows):  # warm compiles
        ex.execute("over", f"Count(Row(f={r}))", shards=shards)
    ctl = AdmissionController(max_concurrent=2, max_queue=4)
    sheds = misses = 0
    lat = _hist()

    def one_query(r, qos_class, deadline_s):
        nonlocal sheds, misses
        tok = set_current_deadline(Deadline(timeout=deadline_s))
        t0 = time.perf_counter()
        try:
            with ctl.admit(qos_class):
                ex.execute("over", f"Count(Row(f={r}))", shards=shards)
            return time.perf_counter() - t0
        except QueryShedError:
            sheds += 1
        except DeadlineExceededError:
            misses += 1
        finally:
            reset_current_deadline(tok)
        return None

    with ThreadPoolExecutor(max_workers=16) as pool:
        futs = []
        for i in range(n_rows * 4):
            if i % 2:  # batch flood with a tight deadline
                futs.append(pool.submit(one_query, i % n_rows, "batch", 0.5))
            else:      # the interactive stream we're protecting
                futs.append(pool.submit(one_query, i % n_rows,
                                        "interactive", 10.0))
        for i, fut in enumerate(futs):
            dt = fut.result()
            if dt is not None and i % 2 == 0:
                lat.observe(dt)
    planner.close()
    extra["oversub_qos_sheds"] = sheds
    extra["oversub_qos_deadline_misses"] = misses
    if lat.count:
        extra["oversub_admitted_p50_ms"] = round(_p50(lat), 3)
        extra["oversub_admitted_p99_ms"] = round(_p99(lat), 3)
    snap = ctl.snapshot()
    assert snap["shed"] == sheds and snap["deadlineMiss"] == misses


def bench_topn(extra):
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    n_rows = 1_000_000
    cols = 10_000_000
    n_bits = 5_000_000
    rng = np.random.default_rng(13)

    h = Holder()
    idx = h.create_index("topn")
    f = idx.create_field("f")
    g = idx.create_field("g")
    # Zipf-ish row popularity so TopN has real structure.
    rows = (np.abs(rng.standard_cauchy(n_bits)) * 1000).astype(np.uint64) % n_rows
    f.import_bits(rows, _rand_positions(rng, n_bits, cols))
    g.import_bits(np.zeros(200_000, dtype=np.uint64),
                  _rand_positions(rng, 200_000, cols))

    ex = Executor(h, planner=MeshPlanner(h, make_mesh()))
    (warm,) = ex.execute("topn", "TopN(f, n=10)")
    assert len(warm) == 10

    qps, p50, _ = _timer(lambda: ex.execute("topn", "TopN(f, n=10)"), N_LAT)
    extra["topn_1m_rows_p50_ms"] = round(p50, 3)
    extra["topn_1m_rows_qps"] = round(qps, 1)
    _, p50c, _ = _timer(lambda: ex.execute("topn", "TopN(f, n=10)",
                                        cache=False), N_LAT)
    extra["topn_1m_rows_cold_p50_ms"] = round(p50c, 3)

    # Filtered TopN at 20k rows: the streamed exact device path.
    f2 = idx.create_field("f2")
    rows2 = rng.integers(0, 20_000, 400_000).astype(np.uint64)
    f2.import_bits(rows2, _rand_positions(rng, 400_000, cols))
    ex.execute("topn", "TopN(f2, Row(g=0), n=10)")  # warm
    _, p50f, _ = _timer(lambda: ex.execute("topn", "TopN(f2, Row(g=0), n=10)"),
                     max(5, N_LAT // 3))
    extra["topn_filtered_20k_rows_p50_ms"] = round(p50f, 3)
    _, p50fc, _ = _timer(lambda: ex.execute("topn", "TopN(f2, Row(g=0), n=10)",
                                         cache=False), max(5, N_LAT // 3))
    extra["topn_filtered_20k_rows_cold_p50_ms"] = round(p50fc, 3)


# ---------------------------------------------------------------------------
# config 3: BSI Sum / Min / Range
# ---------------------------------------------------------------------------


def bench_bsi(extra):
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder, FieldOptions
    from pilosa_tpu.core.field import FIELD_TYPE_INT
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    cols = 100_000_000
    n_vals = 2_000_000
    rng = np.random.default_rng(17)

    h = Holder()
    idx = h.create_index("bsi")
    v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT,
                                           min=-100_000, max=100_000))
    f = idx.create_field("f")
    # Timed window covers import_values only: the random-sample setup
    # (an 800MB permutation for choice-without-replacement) is test-data
    # generation, not import work.
    vc = rng.choice(cols, n_vals, replace=False).astype(np.uint64)
    vv = rng.integers(-100_000, 100_000, n_vals)
    # The FIRST import after boot additionally pays the pool's growth
    # past its boot reserve (fresh mmap + first-touch faults for the
    # 229MB plane buffer + staging) — a once-per-server-lifetime cost,
    # recorded separately so it stays visible. The headline metric is
    # the steady-state rate a warm server imports at: median of 3
    # post-warm-up trials, fresh field each (plane-buffer creation and
    # zeroing stay IN the metric; only the one-time page faulting is
    # out). The first trial's field is kept — the queries below run
    # against it.
    t0 = time.perf_counter()
    v.import_values(vc, vv)
    extra["bsi_import_first_boot_mvals_per_s"] = round(
        n_vals / (time.perf_counter() - t0) / 1e6, 2)
    rates2m = []
    for t in range(3):
        vt = idx.create_field(f"v2m{t}", FieldOptions(type=FIELD_TYPE_INT,
                                                      min=-100_000,
                                                      max=100_000))
        t0 = time.perf_counter()
        vt.import_values(vc, vv)
        rates2m.append(n_vals / (time.perf_counter() - t0) / 1e6)
        idx.delete_field(f"v2m{t}")
    extra["bsi_import_mvals_per_s"] = round(statistics.median(rates2m), 2)
    # Amortized rate at bulk-load batch size: the 2M-value batch above
    # is dominated by the one-time dense plane-buffer creation (see
    # PROFILE_import.md); 8M values over the same columns shows the
    # steady-state import rate. A STEADY-STATE metric gets the median
    # of 3 trials — single-shot numbers on this shared vCPU swing 2x
    # with scheduler/fault luck (same import: 6.6 then 13.3 Mvals/s).
    vc8 = rng.integers(0, cols, 8_000_000, dtype=np.uint64)
    vv8 = rng.integers(-100_000, 100_000, 8_000_000)
    rates = []
    for t in range(3):
        v8 = idx.create_field("v8", FieldOptions(type=FIELD_TYPE_INT,
                                                 min=-100_000, max=100_000))
        t0 = time.perf_counter()
        v8.import_values(vc8, vv8)
        rates.append(8_000_000 / (time.perf_counter() - t0) / 1e6)
        idx.delete_field("v8")
    extra["bsi_import_mvals_per_s_8m"] = round(statistics.median(rates), 2)
    del vc8, vv8
    f.import_bits(np.ones(500_000, dtype=np.uint64),
                  _rand_positions(rng, 500_000, cols))

    ex = Executor(h, planner=MeshPlanner(h, make_mesh()))
    for q, key in (("Sum(field=v)", "bsi_sum_p50_ms"),
                   ("Min(field=v)", "bsi_min_p50_ms"),
                   ("Sum(Row(f=1), field=v)", "bsi_sum_filtered_p50_ms"),
                   ("Count(Row(v > 50000))", "bsi_range_count_p50_ms")):
        ex.execute("bsi", q)  # warm/compile
        _, p50, _ = _timer(lambda q=q: ex.execute("bsi", q), N_LAT)
        extra[key] = round(p50, 3)
        _, p50c, _ = _timer(lambda q=q: ex.execute("bsi", q, cache=False),
                         max(5, N_LAT // 3))
        extra[key.replace("_p50_ms", "_cold_p50_ms")] = round(p50c, 3)


# ---------------------------------------------------------------------------
# config 3b: approximate analytics (HLL distinct + SimilarTopN)
# ---------------------------------------------------------------------------


def bench_sketch(extra):
    """Sketch vs exact A/B (pilosa_tpu/sketch).

    Two series: Count(Distinct(...)) through the fused register path
    against its own exact fallback, and SimilarTopN against the
    equivalent client-side loop of N Count(Intersect(...)) queries —
    the one-dispatch claim is asserted against the planner's raw
    counter, not inferred from latency."""
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import FieldOptions, Holder
    from pilosa_tpu.core.field import FIELD_TYPE_INT
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    cols = 16 * SHARD_WIDTH
    n_vals = 2_000_000
    n_rows = 256
    rng = np.random.default_rng(29)

    h = Holder()
    idx = h.create_index("sk")
    v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT,
                                           min=0, max=10_000_000))
    f = idx.create_field("f")
    vc = rng.choice(cols, n_vals, replace=False).astype(np.uint64)
    v.import_values(vc, rng.integers(0, 10_000_000, n_vals))
    f.import_bits(rng.integers(0, n_rows, n_vals, dtype=np.uint64),
                  rng.integers(0, cols, n_vals, dtype=np.uint64))

    planner = MeshPlanner(h, make_mesh())
    ex = Executor(h, planner=planner, result_cache=False)

    sketch_q = "Count(Distinct(field=v, threshold=0))"
    exact_q = "Count(Distinct(field=v, threshold=100000000))"
    (est,) = ex.execute("sk", sketch_q)          # warm/compile
    (true,) = ex.execute("sk", exact_q)
    extra["sketch_distinct_rel_err"] = round(abs(est - true) / true, 4)
    d0 = planner.dispatches
    qps, p50, _ = _timer(lambda: ex.execute("sk", sketch_q), N_LAT)
    assert (planner.dispatches - d0) == N_LAT, \
        "fused distinct must cost exactly one dispatch per query"
    extra["sketch_distinct_qps"] = round(qps, 1)
    extra["sketch_distinct_p50_ms"] = round(p50, 3)
    _, p50e, _ = _timer(lambda: ex.execute("sk", exact_q),
                        max(3, N_LAT // 5))
    extra["sketch_distinct_exact_p50_ms"] = round(p50e, 3)

    sim_q = "SimilarTopN(f, Row(f=7), n=10)"
    ex.execute("sk", sim_q)                      # warm/compile
    d0 = planner.dispatches
    qps, p50, _ = _timer(lambda: ex.execute("sk", sim_q),
                         max(5, N_LAT // 3))
    assert (planner.dispatches - d0) == max(5, N_LAT // 3), \
        "fused SimilarTopN must cost exactly one dispatch per query"
    extra["sketch_simtopn_p50_ms"] = round(p50, 3)
    extra["sketch_simtopn_qps"] = round(qps, 1)

    # the pre-sketch spelling: one Count(Intersect(...)) per candidate
    # row from the client — N round trips instead of one dispatch.
    def loop():
        for rid in range(0, n_rows, 8):   # 32 of 256 rows: a LOWER bound
            ex.execute("sk", f"Count(Intersect(Row(f=7), Row(f={rid})))")
    loop()                                       # warm/compile
    _, p50l, _ = _timer(loop, 3)
    extra["sketch_simtopn_loop32_p50_ms"] = round(p50l, 3)
    planner.close()


# ---------------------------------------------------------------------------
# config 3c: dispatch fusion + same-plan coalescing (one launch per query)
# ---------------------------------------------------------------------------


def bench_dispatch(extra):
    """Fused plan-step programs + dispatch-coalescing A/B.

    * count_dispatches_per_query — device launches for one uncached
      3-step Intersect→Count (MUST be 1: the acceptance assertion).
    * dispatch_agg_uncached_p50_ms_{on,off} — filtered BSI Range→Sum
      with fusion FORCED on vs the stepped path (filter, plane stack,
      reduce). Forced because ``auto`` steps filtered aggregates on the
      XLA CPU backend (see MeshPlanner._fuse_agg_ok); the on/off delta
      here is the CPU artifact that gate exists for.
    * dispatch_agg_plain_uncached_p50_ms_{on,off} — unfiltered Sum,
      where the cached plane cube makes the fused program win on every
      backend (this one fuses under ``auto`` too).
    * dispatch_count_uncached_p50_ms_{on,off} + coalesce_batch_width_p50
      — per-call p50 of a concurrent identical-Count storm with
      coalescing on vs off (result cache off throughout; fusion stays
      on in both, the production pairing).
    """
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder, FieldOptions
    from pilosa_tpu.core.field import FIELD_TYPE_INT
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    rng = np.random.default_rng(23)
    n_shards = 4
    total = n_shards * SHARD_WIDTH
    h = Holder()
    idx = h.create_index("d")
    f = idx.create_field("f")
    g = idx.create_field("g")
    v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT,
                                           min=-100_000, max=100_000))
    for field in (f, g):
        field.import_bits(rng.integers(0, 4, 2_000_000),
                          rng.integers(0, total, 2_000_000,
                                       dtype=np.uint64))
    vc = rng.choice(total, 1_000_000, replace=False).astype(np.uint64)
    v.import_values(vc, rng.integers(-100_000, 100_000, len(vc)))

    planner = MeshPlanner(h, make_mesh())
    ex = Executor(h, planner=planner)
    q = "Count(Intersect(Row(f=1), Row(g=2)))"
    ex.execute("d", q, cache=False)  # compile + warm stacks

    d0 = planner.dispatches
    ex.execute("d", q, cache=False)
    dpq = planner.dispatches - d0
    extra["count_dispatches_per_query"] = dpq
    assert dpq == 1, f"3-step Count took {dpq} dispatches, want 1"

    # Fusion A/B on the BSI aggregates (the path fusion collapsed from
    # three launches to one).
    def agg_p50(agg):
        ex.execute("d", agg, cache=False)  # warm this mode's path
        _, p50, _ = _timer(lambda: ex.execute("d", agg, cache=False),
                           max(10, N_LAT))
        return p50

    def agg_ab(agg, key, fuse_mode):
        os.environ["PILOSA_TPU_DISPATCH_FUSE"] = fuse_mode
        try:
            fused50 = agg_p50(agg)
            os.environ["PILOSA_TPU_DISPATCH_FUSE"] = "off"
            stepped50 = agg_p50(agg)
        finally:
            del os.environ["PILOSA_TPU_DISPATCH_FUSE"]
        extra[f"dispatch_{key}_uncached_p50_ms_on"] = round(fused50, 3)
        extra[f"dispatch_{key}_uncached_p50_ms_off"] = round(stepped50, 3)
        extra[f"dispatch_{key}_p50_speedup"] = round(stepped50 / fused50, 2)

    # Filtered: force fusion so the A/B measures the fused program even
    # on the CPU backend, where "auto" would route it to the stepped
    # path (the comparator+reduction single-module pathology).
    agg_ab("Sum(Row(v >< [-50000, 50000]), field=v)", "agg", "on")
    # Unfiltered: fuses under "auto" on every backend.
    agg_ab("Sum(field=v)", "agg_plain", "auto")
    extra["dispatch_agg_auto_gate"] = (
        "filtered aggs step under auto on backend=cpu; see _fuse_agg_ok")

    # Coalescing A/B: identical uncached Counts from a thread pool —
    # the repeated-dashboard-query shape coalescing targets.
    storm_threads = min(THREADS, 16)
    storm_q = max(min(N_QUERIES, 256), 128)

    def storm():
        lats = _hist()   # thread-safe: LogHistogram locks its observes

        def one(_):
            t0 = time.perf_counter()
            ex.execute("d", q, cache=False)
            lats.observe(time.perf_counter() - t0)

        with ThreadPoolExecutor(max_workers=storm_threads) as pool:
            list(pool.map(one, range(storm_q)))
        return _p50(lats)

    os.environ["PILOSA_TPU_DISPATCH_COALESCE"] = "on"
    try:
        dstart = planner.dispatches
        on50 = storm()
        n_launch = planner.dispatches - dstart
        widths = planner.batch_widths()[-n_launch:] if n_launch else [1]
        os.environ["PILOSA_TPU_DISPATCH_COALESCE"] = "off"
        off50 = storm()
    finally:
        del os.environ["PILOSA_TPU_DISPATCH_COALESCE"]
    extra["coalesce_batch_width_p50"] = statistics.median(widths)
    extra["dispatch_count_uncached_p50_ms_on"] = round(on50, 3)
    extra["dispatch_count_uncached_p50_ms_off"] = round(off50, 3)
    extra["dispatch_count_p50_speedup"] = round(off50 / on50, 2)
    planner.close()


# ---------------------------------------------------------------------------
# config 2c: key translation (ISSUE 20 — device key planes + batched
# host path)
# ---------------------------------------------------------------------------


def bench_translate(extra):
    """Keyed/id parity and the forward-translate fast paths.

    * translate_keyed_count_dispatches — device launches for one warm
      keyed Count (MUST be 1: the translation stage must stay on the
      host snapshot for small batches, never grow a second launch).
    * translate_keyed_vs_id_p50_ratio — warm keyed Count p50 over the
      identical id-addressed Count p50 (the keyed/id parity headline).
    * translate_batch_alloc_speedup_10k — batched translate_keys vs a
      per-key loop, ALLOCATING 10k fresh keys: the per-key loop pays
      one COW snapshot publish per key, the batch pays one total.
      Asserted >= 10x (measures ~100x+).
    * translate_batch_read_speedup_10k — same A/B on the all-hits read
      path (both lock-free; the batch amortizes call overhead).
    * translate_storm_keys_per_s_planes_{on,off} — 4096-key resolve
      storms through the executor's batched resolver with the device
      plane forced on vs off. On the CPU backend the plane's gather
      competes with a host dict walk, so the ratio is reported, not
      gated — the plane exists for HBM-resident deployments.
    """
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.index import IndexOptions
    from pilosa_tpu.core.translate import TranslateStore
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    rng = np.random.default_rng(29)
    n_bits, n_cols = 400_000, SHARD_WIDTH * 2
    h = Holder()
    kidx = h.create_index("tk", IndexOptions(keys=True))
    kf = kidx.create_field("f", FieldOptions(keys=True))
    oidx = h.create_index("ti")
    of = oidx.create_field("f")
    rows = rng.integers(1, 5, n_bits)
    cols = rng.integers(0, n_cols, n_bits, dtype=np.uint64)
    row_ids = kf.translate_store.translate_keys(
        [f"r{r}" for r in range(1, 5)])
    row_map = {r: row_ids[r - 1] for r in range(1, 5)}
    kf.import_bits(np.array([row_map[r] for r in rows.tolist()],
                            dtype=np.uint64), cols)
    of.import_bits(rows.astype(np.uint64), cols)

    planner = MeshPlanner(h, make_mesh())
    ex = Executor(h, planner=planner)
    kq, oq = 'Count(Row(f="r1"))', "Count(Row(f=1))"
    ex.execute("tk", kq, cache=False)
    ex.execute("tk", kq, cache=False)   # warm compile + stacks
    d0 = planner.dispatches
    ex.execute("tk", kq, cache=False)
    dpq = planner.dispatches - d0
    extra["translate_keyed_count_dispatches"] = dpq
    assert dpq == 1, f"warm keyed Count took {dpq} dispatches, want 1"

    _, keyed50, _ = _timer(lambda: ex.execute("tk", kq, cache=False),
                           max(20, N_LAT))
    ex.execute("ti", oq, cache=False)
    _, id50, _ = _timer(lambda: ex.execute("ti", oq, cache=False),
                        max(20, N_LAT))
    extra["translate_keyed_p50_ms"] = round(keyed50, 3)
    extra["translate_id_p50_ms"] = round(id50, 3)
    extra["translate_keyed_vs_id_p50_ratio"] = round(keyed50 / id50, 2)

    # Batched vs per-key host path, 10k keys (satellite a's whole point).
    n_keys = 10_000
    fresh = [f"alloc-{i}" for i in range(n_keys)]
    s_batch, s_loop = TranslateStore(), TranslateStore()
    t0 = time.perf_counter()
    s_batch.translate_keys(fresh)
    t_batch = time.perf_counter() - t0
    t0 = time.perf_counter()
    for k in fresh:
        s_loop.translate_key(k)
    t_loop = time.perf_counter() - t0
    alloc_speedup = t_loop / t_batch
    extra["translate_batch_alloc_speedup_10k"] = round(alloc_speedup, 1)
    assert alloc_speedup >= 10, \
        f"batched alloc only {alloc_speedup:.1f}x per-key, want >= 10x"
    t_read_b = min(_t_once(lambda: s_batch.translate_keys(fresh))
                   for _ in range(5))
    t_read_l = min(_t_once(lambda: [s_batch.translate_key(k)
                                    for k in fresh]) for _ in range(5))
    extra["translate_batch_read_speedup_10k"] = round(t_read_l / t_read_b, 1)

    # Resolver storm: 4096 existing keys per call, planes on vs off.
    storm_keys = [f"c{int(c)}" for c in
                  rng.choice(n_cols, 4096, replace=False)]
    kidx.translate_store.translate_keys(storm_keys)

    def storm():
        lats = _hist()
        for _ in range(30):
            t0 = time.perf_counter()
            ids = ex._resolve_keys(kidx, None, storm_keys)
            lats.observe(time.perf_counter() - t0)
        assert all(v is not None for v in ids)
        return len(storm_keys) / (_p50(lats) / 1e3)

    os.environ["PILOSA_TPU_TRANSLATE_PLANES"] = "on"
    try:
        storm()   # warm: plane build + probe compile outside the timing
        on_kps = storm()
        os.environ["PILOSA_TPU_TRANSLATE_PLANES"] = "off"
        off_kps = storm()
    finally:
        del os.environ["PILOSA_TPU_TRANSLATE_PLANES"]
    extra["translate_storm_keys_per_s_planes_on"] = round(on_kps)
    extra["translate_storm_keys_per_s_planes_off"] = round(off_kps)
    extra["translate_storm_planes_ratio"] = round(on_kps / off_kps, 2)
    extra["translate_plane_debug"] = ex.keyplanes.debug()
    planner.close()


def _t_once(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# config 3b: streaming ingestion (import stream + WAL group commit +
# ingest/query isolation)
# ---------------------------------------------------------------------------


def bench_ingest(extra):
    import tempfile
    import threading

    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.server.httpclient import HTTPInternalClient, NodeHTTPError
    from pilosa_tpu.server.node import ServerNode
    from pilosa_tpu.cluster.node import URI, Node
    from pilosa_tpu.storage.wal import WalWriter

    n = ServerNode(bind="127.0.0.1:0", use_planner=False,
                   qos_max_concurrent=8, ingest_max_inflight_mb=64)
    n.open()
    client = HTTPInternalClient(timeout=120)
    try:
        base = n.address
        peer = Node(id=f"127.0.0.1:{n.port}",
                    uri=URI(host="127.0.0.1", port=n.port))

        def post(path, body):
            import urllib.request
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            with urllib.request.urlopen(r, timeout=60) as resp:
                return resp.read()

        post("/index/ing", "{}")
        post("/index/ing/field/v",
             json.dumps({"options": {"type": "int", "min": -100_000,
                                     "max": 100_000}}))
        post("/index/ing/field/f", "{}")
        rng = np.random.default_rng(23)
        n_shards, per_shard = 8, 250_000
        total = n_shards * per_shard
        reqs = []
        for s in range(n_shards):
            cols = (s * SHARD_WIDTH
                    + rng.choice(SHARD_WIDTH, per_shard,
                                 replace=False).astype(np.uint64))
            vals = rng.integers(-100_000, 100_000, per_shard)
            reqs.append({"kind": "field", "index": "ing", "field": "v",
                         "shard": s, "rowIDs": None, "columnIDs": cols,
                         "values": vals, "clear": False})
        # warm the apply path (fresh fields each timed trial below)
        client.send_import_stream(peer, reqs[:1])
        rates = []
        for t in range(3):
            fname = f"v{t}"
            post(f"/index/ing/field/{fname}",
                 json.dumps({"options": {"type": "int", "min": -100_000,
                                         "max": 100_000}}))
            trial = [dict(r, field=fname) for r in reqs]
            t0 = time.perf_counter()
            client.send_import_stream(peer, trial)
            rates.append(total / (time.perf_counter() - t0) / 1e6)
        extra["bsi_import_stream_mvals_per_s"] = round(
            statistics.median(rates), 2)

        # interactive p99 while the stream hammers the node
        body = json.dumps({
            "rowIDs": rng.integers(0, 8, 100_000).tolist(),
            "columnIDs": rng.integers(0, n_shards * SHARD_WIDTH,
                                      100_000).tolist()})
        post("/index/ing/field/f/import", body)

        def q99(k):
            h = _hist()
            for i in range(k):
                t0 = time.perf_counter()
                post("/index/ing/query", f"Count(Row(f={i % 8}))")
                h.observe(time.perf_counter() - t0)
            return _p99(h)

        q99(10)  # warm
        stop = threading.Event()

        def ingest():
            t = 0
            while not stop.is_set():
                fname = f"bg{t % 2}"
                try:
                    post(f"/index/ing/field/{fname}",
                         json.dumps({"options": {"type": "int",
                                                 "min": -100_000,
                                                 "max": 100_000}}))
                    client.send_import_stream(
                        peer, [dict(r, field=fname) for r in reqs])
                except (NodeHTTPError, ConnectionError, OSError):
                    pass
                t += 1

        th = threading.Thread(target=ingest, daemon=True)
        th.start()
        try:
            extra["import_while_query_p99_ms"] = round(q99(40), 3)
        finally:
            stop.set()
            th.join(timeout=120)
    finally:
        client.close()
        n.close()

    # WAL group commit: fsyncs per million values at a bulk batch size,
    # concurrent appenders sharing the flush window.
    with tempfile.TemporaryDirectory() as td:
        w = WalWriter(os.path.join(td, "g.wal"), fsync_appends=True,
                      group_window=0.002)
        n_threads, appends, batch = 8, 40, 25_000
        rows = np.ones(batch, dtype=np.uint64)
        cols = np.arange(batch, dtype=np.uint64)

        def run():
            for _ in range(appends):
                w.append("addBatch", rows, cols)

        threads = [threading.Thread(target=run) for _ in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mvals = n_threads * appends * batch / 1e6
        extra["wal_group_commit_fsyncs_per_mval"] = round(w.fsyncs / mvals, 2)
        extra["wal_group_commit_mvals_per_s"] = round(
            mvals / (time.perf_counter() - t0), 2)
        w.close()


# ---------------------------------------------------------------------------
# config 4: time-quantum views
# ---------------------------------------------------------------------------


def bench_time(extra):
    from pilosa_tpu.core import Holder, FieldOptions
    from pilosa_tpu.core.field import FIELD_TYPE_TIME
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    cols = 8_000_000
    n_bits = 120_000
    rng = np.random.default_rng(19)
    h = Holder()
    idx = h.create_index("t")
    f = idx.create_field("f", FieldOptions(type=FIELD_TYPE_TIME,
                                           time_quantum="YMDH"))
    import datetime as dt
    base = dt.datetime(2019, 1, 1)
    stamps = [base + dt.timedelta(hours=int(x))
              for x in rng.integers(0, 24 * 90, n_bits)]
    f.import_bits(np.ones(n_bits, dtype=np.uint64),
                  _rand_positions(rng, n_bits, cols), stamps)

    ex = Executor(h, planner=MeshPlanner(h, make_mesh()))
    q = ("Count(Row(f=1, from='2019-01-15T00:00', to='2019-03-15T00:00'))")
    ex.execute("t", q)
    _, p50, _ = _timer(lambda: ex.execute("t", q), N_LAT)
    extra["time_range_count_p50_ms"] = round(p50, 3)


# ---------------------------------------------------------------------------
# config 5: 4-node cluster GroupBy + Count
# ---------------------------------------------------------------------------


def bench_cluster(extra):
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    n_shards = 256  # 268M cols over 4 nodes
    cols = n_shards * SHARD_WIDTH
    rng = np.random.default_rng(23)

    lc = LocalCluster(
        4, planner_factory=lambda i: None)  # per-node planner below
    for cn in lc.nodes:
        cn.executor.planner = MeshPlanner(cn.holder, make_mesh())
    lc.create_index("c")
    lc.create_field("c", "a")
    lc.create_field("c", "b")

    # Import straight into each shard's owning node (the API's shard
    # routing, api.go:920, minus the HTTP hop).
    cl0 = lc.nodes[0].cluster
    groups = cl0.shards_by_node(cl0.nodes, "c", list(range(n_shards)))
    node_by_id = {cn.id: cn for cn in lc.nodes}
    n_bits = 4_000_000
    for fld, n_rows in (("a", 4), ("b", 8)):
        rows = rng.integers(0, n_rows, n_bits).astype(np.uint64)
        colsv = _rand_positions(rng, n_bits, cols)
        shard_of = (colsv // np.uint64(SHARD_WIDTH)).astype(np.int64)
        for node_id, shs in groups.items():
            mask = np.isin(shard_of, shs)
            node_by_id[node_id].handle_import_request(
                "c", fld, rows=rows[mask], cols=colsv[mask])

    q_count = "Count(Intersect(Row(a=1), Row(b=2)))"
    q_group = "GroupBy(Rows(a), Rows(b))"
    lc.query("c", q_count)
    lc.query("c", q_group)
    # Cached = the system behavior for any repeated read; cold bypasses
    # the coordinator's result cache so every remote node and device
    # program runs (remote nodes still use THEIR caches, as they would
    # in production — only the measured query is forced cold).
    qps, p50, _ = _timer(lambda: lc.query("c", q_count), N_LAT, threads=8)
    extra["cluster4_count_qps"] = round(qps, 1)
    extra["cluster4_count_p50_ms"] = round(p50, 3)
    # Uncached threaded fan-out: the wire/mux/device-reduce tax, with
    # the coordinator's result cache out of the way (remote nodes keep
    # theirs, as in production). This is the headline metric for the
    # distributed fan-out cost.
    qps_u, _, _ = _timer(lambda: lc.query("c", q_count, cache=False),
                         N_LAT, threads=8)
    extra["cluster4_count_uncached_qps"] = round(qps_u, 1)
    # Single-node comparator on the SAME data: how much of one node's
    # throughput the 4-node fan-out retains (1.0 = fan-out is free).
    single = LocalCluster(1, planner_factory=lambda i: None)
    single.nodes[0].executor.planner = MeshPlanner(
        single.nodes[0].holder, make_mesh())
    single.create_index("c")
    single.create_field("c", "a")
    single.create_field("c", "b")
    rng1 = np.random.default_rng(23)
    for fld, n_rows in (("a", 4), ("b", 8)):
        rows = rng1.integers(0, n_rows, n_bits).astype(np.uint64)
        colsv = _rand_positions(rng1, n_bits, cols)
        single.nodes[0].handle_import_request("c", fld, rows=rows,
                                              cols=colsv)
    single.query("c", q_count)
    qps_1, _, _ = _timer(lambda: single.query("c", q_count, cache=False),
                         N_LAT, threads=8)
    extra["single_node_count_uncached_qps"] = round(qps_1, 1)
    extra["cluster_vs_single_node_ratio"] = round(
        qps_u / qps_1, 3) if qps_1 else 0.0
    # Device-sync link floor inside the cluster series: the fixed
    # device round-trip every uncached fan-out leg pays at least once.
    import jax
    import jax.numpy as jnp
    _tiny = jax.device_put(np.arange(8, dtype=np.int32))
    _sumf = jax.jit(lambda v: jnp.sum(v))
    int(_sumf(_tiny))
    floors = []
    for _ in range(3):
        t0 = time.perf_counter()
        int(_sumf(_tiny))
        floors.append(time.perf_counter() - t0)
    extra["cluster4_device_sync_floor_ms"] = round(
        statistics.median(floors) * 1e3, 2)
    _, p50c, _ = _timer(lambda: lc.query("c", q_count, cache=False),
                     max(5, N_LAT // 3))
    extra["cluster4_count_cold_p50_ms"] = round(p50c, 3)
    _, p50g, _ = _timer(lambda: lc.query("c", q_group), max(5, N_LAT // 3))
    extra["cluster4_groupby_p50_ms"] = round(p50g, 3)
    _, p50gc, _ = _timer(lambda: lc.query("c", q_group, cache=False),
                      max(5, N_LAT // 3))
    extra["cluster4_groupby_cold_p50_ms"] = round(p50gc, 3)
    extra["cluster4_cols"] = cols


# ---------------------------------------------------------------------------
# config 6b: plan-keyed result cache — hit/miss economics + dashboard qps
# ---------------------------------------------------------------------------


def bench_cache(extra):
    """Result-cache economics on the repeated-dashboard workload: a
    fixed panel of read queries re-served by a 2-node cluster while a
    writer churns ONE shard. Hits must be order(s)-of-magnitude cheaper
    than the cold path, and selective (per-shard) invalidation must
    keep the hit ratio high despite the write churn."""
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    n_shards = 64
    cols = n_shards * SHARD_WIDTH
    rng = np.random.default_rng(31)
    lc = LocalCluster(2, planner_factory=lambda i: None)
    for cn in lc.nodes:
        cn.executor.planner = MeshPlanner(cn.holder, make_mesh())
    lc.create_index("d")
    lc.create_field("d", "a")
    lc.create_field("d", "b")
    cl0 = lc.nodes[0].cluster
    groups = cl0.shards_by_node(cl0.nodes, "d", list(range(n_shards)))
    node_by_id = {cn.id: cn for cn in lc.nodes}
    n_bits = 2_000_000
    for fld, n_rows in (("a", 4), ("b", 8)):
        rows = rng.integers(0, n_rows, n_bits).astype(np.uint64)
        colsv = _rand_positions(rng, n_bits, cols)
        shard_of = (colsv // np.uint64(SHARD_WIDTH)).astype(np.int64)
        for node_id, shs in groups.items():
            mask = np.isin(shard_of, shs)
            node_by_id[node_id].handle_import_request(
                "d", fld, rows=rows[mask], cols=colsv[mask])
    for cn in lc.nodes:
        cn.dirty.flush_now()

    panel = [
        "Count(Row(a=1))",
        "Count(Intersect(Row(a=1), Row(b=2)))",
        "TopN(a, n=5)",
        "Count(Union(Row(a=0), Row(b=3)))",
        "Count(Row(b=1))",
    ]
    for q in panel:  # warm: populate coordinator + remote-leg caches
        lc.query("d", q)

    # hit vs miss service time on the heaviest panel query
    q = panel[1]
    _, hit_p50, _ = _timer(lambda: lc.query("d", q), N_LAT)
    _, miss_p50, _ = _timer(lambda: lc.query("d", q, cache=False),
                            max(5, N_LAT // 3))
    extra["cache_hit_p50_ms"] = round(hit_p50, 4)
    extra["cache_miss_p50_ms"] = round(miss_p50, 3)
    extra["cache_hit_speedup"] = round(miss_p50 / max(hit_p50, 1e-9), 1)

    # repeated dashboard, cached vs cold, same workload both times
    def dashboard():
        for qq in panel:
            lc.query("d", qq)

    def dashboard_cold():
        for qq in panel:
            lc.query("d", qq, cache=False)

    qps, _, _ = _timer(dashboard, N_LAT, threads=4)
    extra["cache_dashboard_qps"] = round(qps * len(panel), 1)
    qps_c, _, _ = _timer(dashboard_cold, max(5, N_LAT // 3), threads=4)
    extra["cache_dashboard_cold_qps"] = round(qps_c * len(panel), 1)
    extra["cache_dashboard_qps_gain"] = round(qps / max(qps_c, 1e-9), 1)

    extra["cache_bytes"] = lc[0].executor.result_cache.total_bytes

    assert extra["cache_hit_speedup"] >= 10, \
        f"hit p50 must be >=10x faster than miss: {extra['cache_hit_speedup']}"
    assert qps > qps_c, "cached dashboard qps must beat the cold path"

    # churn-under-storm half, re-expressed as the ``dashboard_storm``
    # loadgen scenario: a bursty repeated dashboard panel with a churn
    # ingest trickle invalidating shards underneath it. Selective
    # (per-shard) invalidation is what keeps the report's hit ratio
    # high despite the writes.
    from pilosa_tpu.loadgen import get_scenario, run_scenario

    sc = get_scenario("dashboard_storm")
    sc.duration_s = float(os.environ.get("BENCH_SCENARIO_SECONDS", "12"))
    rep = run_scenario(sc)
    extra["cache_storm_scenario"] = sc.name
    extra["cache_storm_qps"] = rep["arrivals"]["rateAchieved"]
    extra["cache_storm_p50_ms"] = \
        rep["perClass"]["interactive"]["client"]["p50Ms"]
    extra["cache_storm_p99_ms"] = \
        rep["perClass"]["interactive"]["client"]["p99Ms"]
    extra["cache_storm_hit_ratio"] = rep["cache"]["hitRatio"]
    assert rep["cache"]["hitRatio"] >= 0.5, \
        f"churned dashboard hit ratio collapsed: {rep['cache']['hitRatio']}"


# ---------------------------------------------------------------------------
# config 7: backup / restore throughput
# ---------------------------------------------------------------------------


def bench_backup(extra):
    """Backup + restore MB/s through the real subsystem: a 2-node
    cluster with durable stores is captured into a LocalDirArchive and
    rebuilt onto a fresh 2-node cluster."""
    import shutil
    import tempfile

    from pilosa_tpu.backup import BackupWriter, LocalDirArchive, RestoreJob
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.config import SHARD_WIDTH

    tmp = tempfile.mkdtemp(prefix="pilosa-bench-backup-")
    try:
        n_shards = 8
        rng = np.random.default_rng(7)
        dirs = [os.path.join(tmp, f"src{i}") for i in range(2)]
        lc = LocalCluster(2, replica_n=1, data_dirs=dirs)
        lc.create_index("bk")
        lc.create_field("bk", "f")
        n_bits = 1_000_000
        rows = rng.integers(0, 64, n_bits).astype(np.uint64)
        cols = _rand_positions(rng, n_bits, n_shards * SHARD_WIDTH)
        shard_of = (cols // np.uint64(SHARD_WIDTH)).astype(np.int64)
        cl0 = lc.nodes[0].cluster
        groups = cl0.shards_by_node(cl0.nodes, "bk", list(range(n_shards)))
        node_by_id = {cn.id: cn for cn in lc.nodes}
        for node_id, shs in groups.items():
            mask = np.isin(shard_of, shs)
            node_by_id[node_id].handle_import_request(
                "bk", "f", rows=rows[mask], cols=cols[mask])
        for cn in lc.nodes:
            cn.store.flush()

        archive = LocalDirArchive(os.path.join(tmp, "archive"))
        n0 = lc[0]
        w = BackupWriter(n0.holder, n0.cluster, lc.client, n0.store,
                         archive)
        t0 = time.perf_counter()
        manifest = w.run()
        dt = time.perf_counter() - t0
        stored = sum(e["size"] for e in manifest["files"])
        extra["backup_mb"] = round(stored / 1e6, 2)
        extra["backup_mb_s"] = round(stored / 1e6 / dt, 1)

        dirs2 = [os.path.join(tmp, f"dst{i}") for i in range(2)]
        lc2 = LocalCluster(2, replica_n=1, data_dirs=dirs2)
        n = lc2[0]
        t0 = time.perf_counter()
        res = RestoreJob(n.holder, n.cluster, lc2.client, archive,
                         manifest["id"], store=n.store).run()
        dt = time.perf_counter() - t0
        extra["restore_mb_s"] = round(res["bytes"] / 1e6 / dt, 1)
        for cn in lc.nodes + lc2.nodes:
            cn.store.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# config: elastic resize — grow + shrink under a live query loop
# ---------------------------------------------------------------------------


def bench_elastic(extra):
    """Serve-through resize re-expressed as a thin loadgen scenario: a
    replica_n=2 cluster serving an open-loop mixed read stream while a
    node joins mid-run and a member is removed later (the ``elastic``
    scenario's chaos timeline). Queries must serve through both
    cutovers with zero client-visible failures, and the report's
    resize counters show the volume migrated over the PTS1 stream."""
    from pilosa_tpu.loadgen import ManagedTarget, get_scenario, run_scenario

    sc = get_scenario("elastic")
    # Never truncate past the chaos timeline — both resizes must fire.
    sc.duration_s = max(
        float(os.environ.get("BENCH_SCENARIO_SECONDS", "20")),
        max(c.at_s for c in sc.chaos) + 4.0)
    # Own the target so the coordinator's /debug/vars (where the resize
    # job counts its streamed volume) is still readable after the run.
    target = ManagedTarget(n_nodes=sc.nodes, replica_n=sc.replica_n,
                           node_opts=sc.node_opts)
    try:
        rep = run_scenario(sc, target=target)
        # The resize job counts its volume on whichever node held the
        # coordinator role — sum across the surviving members.
        dvars = {}
        for i in range(len(target.nodes)):
            for k, v in target.debug_vars(i).get("counters", {}).items():
                dvars[k] = dvars.get(k, 0) + v
    finally:
        target.close()
    inter = rep["perClass"]["interactive"]
    failures = sum(v["counts"]["error"] for v in rep["perClass"].values())
    chaos_ok = [c for c in rep["chaos"] if c["ok"]]
    extra["elastic_scenario"] = sc.name
    extra["elastic_ops"] = rep["arrivals"]["dispatched"]
    extra["elastic_query_failures"] = failures
    extra["elastic_p50_ms"] = inter["client"]["p50Ms"]
    extra["elastic_p99_ms"] = inter["client"]["p99Ms"]
    extra["elastic_chaos_applied"] = len(chaos_ok)
    extra["elastic_bytes_streamed_mb"] = round(
        dvars.get("cluster.resize.bytesStreamed", 0) / 1e6, 2)
    extra["elastic_shards_migrated"] = int(
        dvars.get("cluster.resize.shardsMigrated", 0))
    assert failures == 0, f"{failures} queries failed across the resizes"
    assert len(chaos_ok) == len(rep["chaos"]) == 2, \
        f"resize chaos actions did not all apply: {rep['chaos']}"


# ---------------------------------------------------------------------------
# config 8: overload resilience — 4x oversubscription with a slow peer
# ---------------------------------------------------------------------------


def bench_overload(extra):
    """The overload-resilience drill, re-expressed as a thin loadgen
    scenario config: an oversubscribed open-loop arrival stream into a
    3-node replica_n=2 cluster whose node1 turns gray mid-run (slower
    than the deadline) and later heals. Admission must shed the excess
    (not queue it), the slow peer's breaker must open, hedged reads
    must absorb it, and no query may surface a hard failure. The
    measurement machinery (arrivals, mix, SLO report) all lives in
    pilosa_tpu/loadgen — this function only maps report fields onto
    the bench's historical keys."""
    from pilosa_tpu.loadgen import get_scenario, run_scenario

    sc = get_scenario("overload")
    sc.duration_s = float(os.environ.get("BENCH_SCENARIO_SECONDS", "15"))
    rep = run_scenario(sc)
    inter = rep["perClass"]["interactive"]
    failures = sum(v["counts"]["error"] for v in rep["perClass"].values())
    extra["overload_scenario"] = sc.name
    extra["overload_ops"] = rep["arrivals"]["dispatched"]
    extra["overload_admitted"] = inter["counts"]["ok"]
    extra["overload_shed"] = rep["rates"]["shed"]
    extra["overload_shed_rate"] = inter["shedRate"]
    extra["overload_deadline_misses"] = rep["rates"]["deadlineMiss"]
    extra["overload_failures"] = failures
    extra["overload_admitted_p50_ms"] = inter["client"]["p50Ms"]
    extra["overload_admitted_p99_ms"] = inter["client"]["p99Ms"]
    extra["overload_hedge_fired"] = rep["rates"]["hedgeFired"]
    extra["overload_hedge_won"] = rep["rates"]["hedgeWon"]
    if rep["rates"]["hedgeFired"]:
        extra["overload_hedge_win_rate"] = round(
            rep["rates"]["hedgeWon"] / rep["rates"]["hedgeFired"], 3)
    extra["overload_breaker_opens"] = rep["rates"]["breakerOpens"]
    extra["overload_cache_hit_ratio"] = rep["cache"]["hitRatio"]
    # The layer's contract, enforced: the slow peer never surfaces as a
    # client-visible failure, and its breaker actually opened.
    assert failures == 0, f"{failures} queries failed via the slow peer"
    assert rep["rates"]["breakerOpens"] >= 1, \
        "slow peer's breaker never opened"
    assert rep["rates"]["hedgeFired"] >= 1, \
        "hedge never fired against the slow peer"


# ---------------------------------------------------------------------------
# config 9: observability overhead — profiled vs unprofiled query storm
# ---------------------------------------------------------------------------


def bench_obs(extra):
    """Observability overhead A/B (the profiling-cost acceptance): an
    identical concurrent Count storm with per-query profiling ON (a
    QueryProfile activated around every call, exactly what the served
    ``?profile=true`` path does) vs OFF (every hook degenerates to one
    None contextvar read). The storm p50 must not move more than 3%.

    Methodology: the work unit is a device-bound TopN (per-query cost
    ~1 ms of dispatch, not pure-Python parse), so the fixed per-query
    bookkeeping cost is measured against a realistic denominator rather
    than a degenerate micro-query where GIL queueing amplifies any µs
    of extra service time into a p50 cliff. Rounds alternate OFF/ON so
    machine drift lands on both modes equally, and each mode's p50 is
    the min across rounds — the standard noise-robust estimator."""
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.obs import profile as obs_profile
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    rng = np.random.default_rng(29)
    total = 8 * SHARD_WIDTH
    h = Holder()
    idx = h.create_index("ob")
    f = idx.create_field("f")
    f.import_bits(rng.integers(0, 64, 4_000_000),
                  rng.integers(0, total, 4_000_000, dtype=np.uint64))
    planner = MeshPlanner(h, make_mesh())
    ex = Executor(h, planner=planner)
    q = "TopN(f, n=8)"
    ex.execute("ob", q, cache=False)  # compile + warm stacks

    storm_threads = min(THREADS, 8)
    storm_q = max(min(N_QUERIES, 192), 96)

    def storm(profiled):
        lats = _hist()

        def one(i):
            tok = None
            if profiled:
                tok = obs_profile.activate(obs_profile.QueryProfile(
                    f"bench-{i}", query=q, index="ob"))
            t0 = time.perf_counter()
            try:
                ex.execute("ob", q, cache=False)
            finally:
                dt = time.perf_counter() - t0
                if tok is not None:
                    prof = obs_profile.current()
                    obs_profile.deactivate(tok)
                    prof.finish()
            lats.observe(dt)

        with ThreadPoolExecutor(max_workers=storm_threads) as pool:
            list(pool.map(one, range(storm_q)))
        return _p50(lats)

    storm(False)
    storm(True)  # warm both code paths before measuring
    off_rounds: list[float] = []
    on_rounds: list[float] = []
    for _ in range(4):
        off_rounds.append(storm(False))
        on_rounds.append(storm(True))
    on50 = min(on_rounds)
    off50 = min(off_rounds)
    overhead = (on50 - off50) / off50
    extra["obs_storm_p50_ms_profile_on"] = round(on50, 3)
    extra["obs_storm_p50_ms_profile_off"] = round(off50, 3)
    extra["obs_profile_overhead_pct"] = round(overhead * 100, 2)
    planner.close()
    assert overhead <= 0.03, \
        f"profiling overhead {overhead * 100:.2f}% > 3%"


# ---------------------------------------------------------------------------


def main() -> None:
    import jax

    want = (set(c.strip() for c in CONFIGS.split(","))
            if CONFIGS != "all"
            else {"star", "topn", "bsi", "sketch", "dispatch", "translate",
                  "ingest", "time", "cluster", "cache", "oversub", "backup",
                  "overload", "obs", "elastic"})
    extra: dict = {"backend": jax.default_backend(),
                   "devices": len(jax.devices())}

    # Boot-time buffer-pool reserve, exactly as `pilosa-tpu server` does
    # (config import-pool-mb): fault the import block/staging pages once,
    # before any timed window, so imports measure the import — not this
    # hypervisor's first-touch fault rate (~0.7-2 GB/s vs 8 GB/s warm;
    # THP is unavailable here: AnonHugePages stays 0 under madvise).
    from pilosa_tpu import native as _native
    extra["pool_reserved_mb"] = _native.pool_reserve(1024 << 20) >> 20

    # Host-speed canary: every import metric is bound by this shared
    # vCPU, whose effective speed swings >2x hour to hour (observed
    # cpu_threaded_qps 9.3-27.9 and import 54-122 Mbit/s for identical
    # code). A fixed memset rate recorded in the same run lets a reader
    # normalize import numbers across runs instead of attributing host
    # weather to the code.
    buf = np.empty(1 << 28, dtype=np.uint8)
    buf[:] = 1  # fault pages outside the timed window
    t0 = time.perf_counter()
    for v in (2, 3, 4):
        buf[:] = v
    extra["host_canary_memset_gbps"] = round(
        3 * buf.nbytes / (time.perf_counter() - t0) / 1e9, 2)
    del buf

    qps = cpu_qps = None
    t_all = time.perf_counter()
    if "star" in want:
        qps, cpu_qps = bench_star_trace(extra)
    for name, fn in (("topn", bench_topn), ("bsi", bench_bsi),
                     ("sketch", bench_sketch),
                     ("dispatch", bench_dispatch),
                     ("translate", bench_translate),
                     ("ingest", bench_ingest),
                     ("time", bench_time), ("cluster", bench_cluster),
                     ("cache", bench_cache),
                     ("oversub", bench_oversubscribed),
                     ("backup", bench_backup),
                     ("overload", bench_overload),
                     ("obs", bench_obs),
                     ("elastic", bench_elastic)):
        if name in want:
            t0 = time.perf_counter()
            try:
                fn(extra)
            except Exception as e:  # pragma: no cover
                extra[f"{name}_error"] = repr(e)
            extra[f"{name}_setup_plus_bench_s"] = round(
                time.perf_counter() - t0, 1)
    extra["total_s"] = round(time.perf_counter() - t_all, 1)

    if qps is None:  # star config skipped: report first available metric
        print(json.dumps({"metric": "bench_subset", "value": 0,
                          "unit": "n/a", "vs_baseline": 0, "extra": extra}))
        _fail_on_errors(extra)
        return
    print(json.dumps({
        "metric": "count_intersect_qps_1b_cols_executor",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 2),
        "extra": extra,
    }))
    _fail_on_errors(extra)


def _fail_on_errors(extra: dict) -> None:
    """CI-style guard (VERDICT r2 #3): a config crash must be LOUD — the
    JSON line above still prints, but the process exits non-zero so a
    shipped bench run can never silently carry a *_error key."""
    errors = {k: v for k, v in extra.items() if k.endswith("_error")}
    if errors:
        print(f"BENCH FAILED: {errors}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
