"""Headline benchmark: Count(Intersect) QPS over a 1-billion-column index.

BASELINE.json metric: "Count(Intersect) QPS on 1B-col index" with north
star ≥10× single-node CPU. The reference publishes no absolute numbers
(BASELINE.md), so the CPU baseline is measured here, on this host, as a
single-threaded dense popcount(a & b) over the identical blocks — the
dense-domain equivalent of the reference's hottest kernel
(roaring/roaring.go:3121 intersectionCountBitmapBitmap over uint64 words;
single-threaded like one go-bench op).

The TPU number is *pipelined* QPS: N independent queries dispatched
asynchronously, one final sync — how a loaded query server behaves.
(Per-query sync latency through the axon tunnel is ~100 ms of pure
network RTT; on-device compute per query is microseconds. Pipelining is
the honest server-throughput measure on tunneled hardware.)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_COLS = int(os.environ.get("BENCH_COLS", 1_000_000_000))
N_QUERIES = int(os.environ.get("BENCH_QUERIES", 200))
CPU_QUERIES = int(os.environ.get("BENCH_CPU_QUERIES", 3))
DENSITY = float(os.environ.get("BENCH_DENSITY", 0.05))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.config import SHARD_WIDTH, WORDS_PER_SHARD

    n_shards = (N_COLS + SHARD_WIDTH - 1) // SHARD_WIDTH
    rng = np.random.default_rng(7)

    # Two bitmap rows ("f=1", "g=2") over n_shards shards, ~DENSITY fill.
    # Dense uint32 blocks — exactly the planner's leaf layout.
    def random_blocks():
        import math
        words = rng.integers(0, 1 << 32, size=(n_shards, WORDS_PER_SHARD),
                             dtype=np.uint32)
        # AND of k random masks ≈ density 2^-k (one mask ≈ 0.5).
        k = max(1, round(-math.log2(max(DENSITY, 1e-9))))
        for _ in range(k - 1):
            words &= rng.integers(0, 1 << 32, size=words.shape, dtype=np.uint32)
        return words

    a_host = random_blocks()
    b_host = random_blocks()

    # ---- CPU baseline: single-threaded popcount(a & b) ----
    lut = np.array([bin(i).count("1") for i in range(256)], dtype=np.uint8)

    def cpu_count():
        total = 0
        for s in range(n_shards):  # shard loop, like the per-shard mapFn
            inter = a_host[s] & b_host[s]
            total += int(lut[inter.view(np.uint8)].sum(dtype=np.int64))
        return total

    t0 = time.perf_counter()
    for _ in range(CPU_QUERIES):
        expected = cpu_count()
    cpu_dt = (time.perf_counter() - t0) / CPU_QUERIES
    cpu_qps = 1.0 / cpu_dt

    # ---- TPU: one fused XLA program over the sharded stack ----
    from pilosa_tpu.parallel.mesh import make_mesh, shard_spec

    mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    pad = (-n_shards) % n_dev
    if pad:
        zeros = np.zeros((pad, WORDS_PER_SHARD), np.uint32)
        a_host_p = np.concatenate([a_host, zeros])
        b_host_p = np.concatenate([b_host, zeros])
    else:
        a_host_p, b_host_p = a_host, b_host

    spec = shard_spec(mesh)
    a = jax.device_put(a_host_p, spec)
    b = jax.device_put(b_host_p, spec)
    jax.block_until_ready((a, b))

    @jax.jit
    def count_intersect(x, y):
        pc = jax.lax.population_count(jnp.bitwise_and(x, y)).astype(jnp.int32)
        return jnp.sum(pc, axis=-1)  # [S] per-shard counts

    got = int(np.asarray(count_intersect(a, b), dtype=np.int64).sum())
    assert got == expected, (got, expected)

    # Pipelined throughput: dispatch N, sync once.
    t0 = time.perf_counter()
    outs = [count_intersect(a, b) for _ in range(N_QUERIES)]
    jax.block_until_ready(outs)
    tpu_dt = (time.perf_counter() - t0) / N_QUERIES
    tpu_qps = 1.0 / tpu_dt

    print(json.dumps({
        "metric": "count_intersect_qps_1b_cols",
        "value": round(tpu_qps, 1),
        "unit": "qps",
        "vs_baseline": round(tpu_qps / cpu_qps, 2),
    }))
    print(f"# backend={jax.default_backend()} devices={n_dev} "
          f"cols={n_shards * SHARD_WIDTH:,} shards={n_shards} "
          f"count={got:,} cpu_qps={cpu_qps:.2f} tpu_ms={tpu_dt*1e3:.3f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
