"""Framework error types + name validation.

Reference: pilosa.go (public Err* values :40-117, nameRegexp :119,
validateName :155).
"""

from __future__ import annotations

import re


class PilosaError(Exception):
    """Base class; .message matches the reference's error strings so HTTP
    responses can be byte-compatible."""

    message = "pilosa error"

    def __init__(self, message: str | None = None):
        super().__init__(message or self.message)


class IndexNotFoundError(PilosaError):
    message = "index not found"


class IndexExistsError(PilosaError):
    message = "index already exists"


class FieldNotFoundError(PilosaError):
    message = "field not found"


class FieldExistsError(PilosaError):
    message = "field already exists"


class BSIGroupNotFoundError(PilosaError):
    message = "bsigroup not found"


class BSIGroupValueTooLowError(PilosaError):
    message = "value too low for bsigroup"


class BSIGroupValueTooHighError(PilosaError):
    message = "value too high for bsigroup"


class InvalidBSIGroupRangeError(PilosaError):
    message = "invalid bsigroup range"


class InvalidViewError(PilosaError):
    message = "invalid view"


class InvalidCacheTypeError(PilosaError):
    message = "invalid cache type"


class InvalidFieldTypeError(PilosaError):
    message = "invalid field type"


class InvalidTimeQuantumError(PilosaError):
    message = "invalid time quantum"


class ApiMethodNotAllowedError(PilosaError):
    """Reference newAPIMethodNotAllowedError (api.go:124): the cluster's
    state (STARTING / RESIZING) refuses this operation right now."""

    message = "api method not allowed"


class ClusterFencedError(PilosaError):
    """This node cannot reach a majority of the ring: it has fenced
    itself and refuses non-internal traffic (503 + Retry-After on the
    HTTP surface) so a partitioned minority never accepts writes the
    majority will skip. Reads may be re-enabled behind the explicit
    stale-reads knob (Cluster.fence_stale_reads)."""

    message = "node is fenced: cannot reach a quorum of the cluster"

    #: seconds a client should wait before retrying — one failure-
    #: detector sweep is the soonest the fence can possibly lift.
    retry_after = 5.0


class NameError_(PilosaError):
    message = "invalid name"


class QueryError(PilosaError):
    message = "invalid query"


class TranslateStoreReadOnlyError(PilosaError):
    message = "translate store could not find or create key, translate store read only"


class NotImplementedError_(PilosaError):
    message = "not implemented"


class FragmentNotFoundError(PilosaError):
    message = "fragment not found"


class ShardOutOfBoundsError(PilosaError):
    message = "shard out of bounds"


class ClusterDoesNotOwnShardError(PilosaError):
    message = "node does not own shard"


# Reference: pilosa.go:119 — lowercase start, [a-z0-9_-], max 64 chars.
_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,63}$")


def validate_name(name: str) -> None:
    """Reference validateName (pilosa.go:155)."""
    if not _NAME_RE.match(name):
        raise NameError_(f"invalid index or field name: {name!r}")
