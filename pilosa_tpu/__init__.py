"""pilosa_tpu — a TPU-native distributed bitmap index.

A ground-up rebuild of the capabilities of Pilosa (the Go distributed bitmap
index) designed for TPU hardware: bitmap rows live as dense uint32 word blocks
in HBM, set-algebra and popcount run as XLA/Pallas kernels on the VPU, the
per-shard map/reduce runs as ``shard_map`` over a ``jax.sharding.Mesh`` with
ICI all-reduce, and the cluster layer speaks multi-host JAX over DCN instead
of HTTP+gossip.

Layering (mirrors SURVEY.md §1 of the reference):

- :mod:`pilosa_tpu.ops`      — bitmap math kernels (reference: ``roaring/``)
- :mod:`pilosa_tpu.core`     — fragment/row/view/field/index/holder data model
- :mod:`pilosa_tpu.pql`      — PQL parser (reference: ``pql/``)
- :mod:`pilosa_tpu.exec`     — query executor + fused planner (``executor.go``)
- :mod:`pilosa_tpu.parallel` — mesh, placement, shard_map execution
- :mod:`pilosa_tpu.storage`  — WAL + snapshot persistence
- :mod:`pilosa_tpu.server`   — HTTP API surface (``api.go``, ``http/``)
- :mod:`pilosa_tpu.cluster`  — membership/replication/anti-entropy
"""

__version__ = "0.1.0"

from pilosa_tpu.config import SHARD_WIDTH, shard_width_exponent  # noqa: F401
