"""Per-query cost profiles: where did these 2.8 ms go?

A QueryProfile is a contextvar-scoped ledger accumulated along the whole
read path: admission wait, parse + plan, result-cache lookup, per-step
dispatch count and device ms (fused vs stepped, coalesce batch width),
TransferBatcher wave membership, and one entry per remote leg (wire
bytes in/out, decode ms, rtt, hedge/breaker events) with the remote
node's own profile nested inside — a cluster query returns a complete
cross-node timeline.

Enablement is opt-in per query (``?profile=true``: the profile rides
inline in the response envelope and the query is exempt from the result
cache) and always-on for retention: the coordinator keeps the slowest N
profiles in a ProfileRing served at ``/debug/queries`` and
``/debug/queries/<trace-id>``. When no profile is active, every hook in
the hot path is one contextvar read returning None — the off path
allocates nothing (asserted by tests/test_obs.py equivalence test).

Threading: legs land from map_reduce pool threads and dispatch records
land from the coalescer's flusher thread (which the profile reaches by
captured reference, not contextvar), so mutation goes through one lock.
"""

from __future__ import annotations

import contextvars
import threading
import time

_current_profile: contextvars.ContextVar["QueryProfile | None"] = \
    contextvars.ContextVar("pilosa_profile", default=None)

#: per-query bounded detail lists (dispatch widths, wave widths, legs):
#: a pathological query cannot grow its own profile without bound.
MAX_DETAIL = 128


def current() -> "QueryProfile | None":
    """The active profile, or None (the entire cost of profiling-off)."""
    return _current_profile.get()


def activate(prof: "QueryProfile | None"):
    """Install ``prof`` as the active profile; returns a reset token."""
    return _current_profile.set(prof)


def deactivate(token) -> None:
    _current_profile.reset(token)


class QueryProfile:
    """One query's cost ledger. Cheap to create, locked to mutate."""

    __slots__ = ("trace_id", "query", "index", "node", "qos_class",
                 "remote", "start", "timings", "cache_hit", "fused_steps",
                 "dispatches", "dispatch_widths", "device_ms",
                 "transfer_waves", "wave_widths", "inline_steals",
                 "remote_legs", "events", "status", "_lock")

    def __init__(self, trace_id: str, query: str = "", index: str = "",
                 node: str = "", qos_class: str = "", remote: bool = False):
        self.trace_id = trace_id
        self.query = query[:512]
        self.index = index
        self.node = node
        self.qos_class = qos_class
        self.remote = remote
        self.start = time.perf_counter()
        self.timings: dict[str, float] = {}      # phase -> ms
        self.cache_hit = False
        self.fused_steps = 0
        self.dispatches = 0
        self.dispatch_widths: list[int] = []
        self.device_ms = 0.0
        self.transfer_waves = 0
        self.wave_widths: list[int] = []
        self.inline_steals = 0
        # Lazy: most queries never grow a leg or an event — allocating
        # these in the ctor would tax every profiled local query.
        self.remote_legs: list[dict] | None = None
        self.events: dict[str, int] | None = None
        self.status = "ok"
        self._lock = threading.Lock()

    # -- recording hooks (each guarded by `current() is None` upstream) --

    def add_ms(self, phase: str, ms: float) -> None:
        with self._lock:
            self.timings[phase] = self.timings.get(phase, 0.0) + ms

    def add_dispatch(self, width: int, device_ms: float = 0.0) -> None:
        with self._lock:
            self.dispatches += 1
            self.device_ms += device_ms
            if len(self.dispatch_widths) < MAX_DETAIL:
                self.dispatch_widths.append(int(width))

    def add_wave(self, width: int) -> None:
        with self._lock:
            self.transfer_waves += 1
            if len(self.wave_widths) < MAX_DETAIL:
                self.wave_widths.append(int(width))

    def add_inline_steal(self) -> None:
        with self._lock:
            self.inline_steals += 1

    def add_remote_leg(self, node: str, shards: int, bytes_out: int,
                       bytes_in: int, decode_ms: float, rtt_ms: float,
                       hedged: bool = False, error: str = "",
                       remote: dict | None = None) -> None:
        with self._lock:
            if self.remote_legs is None:
                self.remote_legs = []
            elif len(self.remote_legs) >= MAX_DETAIL:
                return
            leg = {"node": node, "shards": shards,
                   "bytesOut": int(bytes_out), "bytesIn": int(bytes_in),
                   "decodeMs": round(decode_ms, 4),
                   "rttMs": round(rtt_ms, 4), "hedged": bool(hedged)}
            if error:
                leg["error"] = error
            if remote:
                leg["remote"] = remote
            self.remote_legs.append(leg)

    def bump(self, event: str, n: int = 1) -> None:
        with self._lock:
            if self.events is None:
                self.events = {}
            self.events[event] = self.events.get(event, 0) + n

    # -- rendering -------------------------------------------------------

    def finish(self) -> dict:
        """Close the ledger and render it. The remote totals are SUMS of
        the per-leg entries by construction, so the acceptance invariant
        (per-peer bytes/decode-ms sum to the coordinator totals) holds
        exactly; the tests assert the legs themselves are each recorded
        once."""
        with self._lock:
            total_ms = (time.perf_counter() - self.start) * 1000.0
            self.timings.setdefault("totalMs", round(total_ms, 4))
            doc = {
                "traceId": self.trace_id,
                "node": self.node,
                "query": self.query,
                "index": self.index,
                "qosClass": self.qos_class,
                "status": self.status,
                "timings": {k: round(v, 4) for k, v in self.timings.items()},
                "cacheHit": self.cache_hit,
                "fusedSteps": self.fused_steps,
                "dispatch": {
                    "count": self.dispatches,
                    "deviceMs": round(self.device_ms, 4),
                    "widths": list(self.dispatch_widths),
                },
                "transfer": {
                    "waves": self.transfer_waves,
                    "widths": list(self.wave_widths),
                    "inlineSteals": self.inline_steals,
                },
            }
            if self.events:
                doc["events"] = dict(self.events)
            if self.remote_legs:
                legs = [dict(leg) for leg in self.remote_legs]
                doc["remoteLegs"] = legs
                doc["remoteTotals"] = {
                    "legs": len(legs),
                    "bytesOut": sum(leg["bytesOut"] for leg in legs),
                    "bytesIn": sum(leg["bytesIn"] for leg in legs),
                    "decodeMs": round(sum(leg["decodeMs"] for leg in legs),
                                      4),
                    "rttMs": round(sum(leg["rttMs"] for leg in legs), 4),
                    "hedgedLegs": sum(1 for leg in legs if leg["hedged"]),
                    "errorLegs": sum(1 for leg in legs if "error" in leg),
                }
            return doc


class ProfileRing:
    """Retain the slowest-N finished profiles, addressable by trace id.

    ``record()`` takes the dict ``QueryProfile.finish()`` produced —
    retention happens after response write, so keeping dicts (not live
    profiles) means /debug/queries never races an in-flight ledger.
    """

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}     # trace_id -> finished doc

    def record(self, doc: dict) -> None:
        tid = doc.get("traceId")
        if not tid:
            return
        ms = doc.get("timings", {}).get("totalMs", 0.0)
        with self._lock:
            prev = self._entries.get(tid)
            if prev is not None:
                # Same trace re-observed (retry): keep the slower run.
                if prev.get("timings", {}).get("totalMs", 0.0) >= ms:
                    return
            self._entries[tid] = doc
            if len(self._entries) > self.capacity:
                fastest = min(
                    self._entries,
                    key=lambda t: self._entries[t].get("timings", {})
                    .get("totalMs", 0.0))
                del self._entries[fastest]

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            return self._entries.get(trace_id)

    def snapshot(self) -> list[dict]:
        """Slowest-first listing for /debug/queries."""
        with self._lock:
            docs = list(self._entries.values())
        docs.sort(key=lambda d: d.get("timings", {}).get("totalMs", 0.0),
                  reverse=True)
        return docs

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
