"""Observability: stats, tracing, logging.

Reference: stats/ (StatsClient stats.go:31, expvar default, statsd/,
prometheus/), tracing/ (Tracer/Span tracing.go:32, global singleton :23,
Jaeger backend via opentracing), logger/ (logger.go), plus the runtime
monitor in server.go:813-855. Diagnostics phone-home (diagnostics.go) is
intentionally NOT implemented (always off).
"""

from pilosa_tpu.obs.histogram import (
    SECONDS_BOUNDS,
    WIDTH_BOUNDS,
    LogHistogram,
)
from pilosa_tpu.obs.logger import Logger, NopLogger, StandardLogger
from pilosa_tpu.obs.otlp import OTLPTracer
from pilosa_tpu.obs.profile import ProfileRing, QueryProfile
from pilosa_tpu.obs.profiler import sample_profile
from pilosa_tpu.obs.runtime import RuntimeMonitor, collect_runtime_gauges
from pilosa_tpu.obs.stats import (
    MemoryStats,
    NopStats,
    StatsClient,
    StatsdStats,
    prometheus_text,
)
from pilosa_tpu.obs.tracing import (
    NopTracer,
    SimpleTracer,
    Span,
    Tracer,
    current_trace_id,
    get_tracer,
    new_trace_id,
    set_tracer,
    start_span,
)

__all__ = [
    "Logger", "NopLogger", "StandardLogger",
    "LogHistogram", "SECONDS_BOUNDS", "WIDTH_BOUNDS",
    "MemoryStats", "NopStats", "StatsClient", "StatsdStats",
    "ProfileRing", "QueryProfile",
    "prometheus_text",
    "RuntimeMonitor", "collect_runtime_gauges",
    "NopTracer", "OTLPTracer", "SimpleTracer", "Span", "Tracer",
    "current_trace_id", "get_tracer", "new_trace_id", "sample_profile",
    "set_tracer", "start_span",
]
