"""OTLP/HTTP trace exporter — a concrete backend for the Tracer protocol.

Reference: tracing/opentracing/opentracing.go (the Jaeger glue behind the
reference's Tracer interface). Here the wire format is OTLP/HTTP JSON
(``/v1/traces`` on a standard collector, default port 4318) so any
OpenTelemetry collector/Jaeger-all-in-one ingests it without a client
dependency — the payload is assembled by hand and POSTed with urllib.

Spans batch in memory and flush on a background ticker (or when the
batch fills); export failures drop the batch and never block or break
the traced code path.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.request


def _trace_id_hex(trace_id: str | None) -> str:
    """Map our string correlation ids onto OTLP's 16-byte hex ids."""
    if not trace_id:
        trace_id = os.urandom(8).hex()
    return hashlib.md5(trace_id.encode()).hexdigest()  # 32 hex chars


class _OTLPSpan:
    __slots__ = ("operation", "trace_id", "span_id", "parent_id",
                 "start_ns", "end_ns", "tags", "_tracer")

    def __init__(self, tracer: "OTLPTracer", operation: str,
                 trace_id: str | None, parent_id: str | None):
        self._tracer = tracer
        self.operation = operation
        # Fixed at span START (not serialization): a per-payload random
        # fallback would split one logical trace across trace ids.
        self.trace_id = _trace_id_hex(trace_id)
        self.parent_id = parent_id
        self.span_id = os.urandom(8).hex()
        self.start_ns = time.time_ns()
        self.end_ns: int | None = None
        self.tags: dict = {}

    def set_tag(self, key, value) -> None:
        self.tags[key] = value

    def finish(self) -> None:
        if self.end_ns is None:
            self.end_ns = time.time_ns()
            self._tracer._enqueue(self)


class OTLPTracer:
    """Tracer protocol implementation exporting to an OTLP collector."""

    def __init__(self, endpoint: str = "http://127.0.0.1:4318/v1/traces",
                 service_name: str = "pilosa-tpu",
                 batch_size: int = 128, flush_interval: float = 2.0,
                 timeout: float = 5.0):
        self.endpoint = endpoint
        self.service_name = service_name
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.timeout = timeout
        self._buf: list[_OTLPSpan] = []
        self._lock = threading.Lock()
        self._closed = False
        self.exported = 0
        self.dropped = 0
        self._ticker = threading.Thread(target=self._run, daemon=True,
                                        name="otlp-export")
        self._ticker.start()

    # -- Tracer protocol ---------------------------------------------------

    def start_span(self, operation: str, parent_id: str | None = None):
        from pilosa_tpu.obs import tracing
        return _OTLPSpan(self, operation, tracing.current_trace_id(),
                         parent_id)

    # -- batching ----------------------------------------------------------

    def _enqueue(self, span: _OTLPSpan) -> None:
        flush = False
        with self._lock:
            if self._closed:
                return
            self._buf.append(span)
            flush = len(self._buf) >= self.batch_size
        if flush:
            self.flush()

    def _run(self) -> None:
        while not self._closed:
            time.sleep(self.flush_interval)
            self.flush()

    def _payload(self, spans: list[_OTLPSpan]) -> bytes:
        otlp_spans = []
        for s in spans:
            attrs = [{"key": str(k),
                      "value": {"stringValue": str(v)}}
                     for k, v in s.tags.items()]
            otlp_spans.append({
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentSpanId": s.parent_id or "",
                "name": s.operation,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(s.start_ns),
                "endTimeUnixNano": str(s.end_ns),
                "attributes": attrs,
            })
        return json.dumps({"resourceSpans": [{
            "resource": {"attributes": [{
                "key": "service.name",
                "value": {"stringValue": self.service_name}}]},
            "scopeSpans": [{"scope": {"name": "pilosa_tpu"},
                            "spans": otlp_spans}],
        }]}).encode()

    def flush(self) -> None:
        with self._lock:
            spans, self._buf = self._buf, []
        if not spans:
            return
        req = urllib.request.Request(
            self.endpoint, data=self._payload(spans), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
            self.exported += len(spans)
        except Exception:
            self.dropped += len(spans)  # never break the traced path

    def close(self) -> None:
        self._closed = True
        self.flush()
