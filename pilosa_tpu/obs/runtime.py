"""Runtime monitor: periodic host + device health gauges.

Reference: server.go:812-855 (monitorRuntime: goroutines, heap, GC,
open FDs via gcnotify/ + gopsutil/). The TPU-native twist is the gauge
that actually matters on this architecture: device memory — both the
planner's HBM-resident stack-cache occupancy against its budget and the
backend's own memory stats when the platform exposes them.
"""

from __future__ import annotations

import os
import threading

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def collect_runtime_gauges(stats, planner=None,
                           probe_device: bool = True, qos=None) -> dict:
    """One sweep of gauges into ``stats``; returns them for callers that
    surface the snapshot directly (the /info route, tests)."""
    out: dict[str, float] = {}

    out["threads"] = float(threading.active_count())
    try:
        with open("/proc/self/statm") as f:
            parts = f.read().split()
        out["rssBytes"] = float(int(parts[1]) * _PAGE)
        out["vmsBytes"] = float(int(parts[0]) * _PAGE)
    except (OSError, IndexError, ValueError):
        pass
    try:
        out["openFDs"] = float(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass

    if planner is not None:
        # Stack-cache HBM occupancy vs its budget — the eviction system
        # works silently; this is how an operator sees pressure.
        snap = planner.cache_stats()
        out["plannerCacheBytes"] = float(snap["bytes"])
        out["plannerCacheBudgetBytes"] = float(snap["budget_bytes"])
        out["plannerCacheEntries"] = float(snap["entries"])
        out["plannerCacheEvictions"] = float(snap.get("evictions", 0))
        # Dispatch accounting (fused programs + coalescing): launches
        # and queries-absorbed-by-batching since boot. The live
        # planner.dispatchCount/dispatchCoalesced counters on
        # /debug/vars tick per launch; these gauges snapshot totals.
        out["plannerDispatches"] = float(snap.get("dispatches", 0))
        out["plannerDispatchesCoalesced"] = float(
            snap.get("dispatches_coalesced", 0))

    if planner is not None and probe_device:
        # Only device-using nodes probe device memory: jax.local_devices
        # would otherwise force backend init (seconds over the tunnel)
        # on planner-less nodes for gauges they can't use.
        try:
            import jax
            dev = jax.local_devices()[0]
            mem = getattr(dev, "memory_stats", lambda: None)()
            if mem:
                for key in ("bytes_in_use", "peak_bytes_in_use",
                            "bytes_limit"):
                    if key in mem:
                        out[f"device_{key}"] = float(mem[key])
        except Exception:
            pass  # platform without memory stats / no device

    # Import buffer-pool health (native recycled page pool): an
    # operator watching freeBytes fall toward zero is watching imports
    # head back to cold first-touch fault cost — the signal to raise
    # import-pool-mb (the top-up loop covers steady drain).
    try:
        from pilosa_tpu import native
        pool = native.pool_stats()
        if pool is not None:
            out["poolFreeBytes"] = float(pool["free_bytes"])
            out["poolLimitBytes"] = float(pool["limit_bytes"])
            out["poolFreshMmaps"] = float(pool["fresh_mmaps"])
            out["poolRecycledAllocs"] = float(pool["recycled_allocs"])
    except Exception:
        pass

    if qos is not None:
        # Admission pressure: queue depth / in-flight per class, plus
        # lifetime shed and deadline-miss totals. The per-class splits
        # go out as tagged qos.* gauges via export_gauges.
        try:
            snap = qos.snapshot()
            out["qosActive"] = float(snap["active"])
            out["qosQueueDepth"] = float(snap["queuedTotal"])
            out["qosShedTotal"] = float(snap["shed"])
            out["qosDeadlineMissTotal"] = float(snap["deadlineMiss"])
            qos.export_gauges(stats)
        except Exception:
            pass  # monitoring must never kill the node

    for name, value in out.items():
        stats.gauge(f"runtime.{name}", value)
    return out


class RuntimeMonitor:
    """Jittered ticker around collect_runtime_gauges (the monitorRuntime
    loop)."""

    DEFAULT_INTERVAL = 30.0

    def __init__(self, stats, planner=None,
                 interval: float = DEFAULT_INTERVAL, qos=None):
        self.stats = stats
        self.planner = planner
        self.qos = qos
        self.interval = interval
        self._timer: threading.Timer | None = None
        self._closed = False
        self._lock = threading.Lock()

    def start(self) -> None:
        if self.interval <= 0:
            return
        # Host-side sweep inline (cheap, includes planner cache stats);
        # the device-memory probe waits for the first background tick so
        # ServerNode.open() never blocks on backend init.
        collect_runtime_gauges(self.stats, self.planner,
                               probe_device=False, qos=self.qos)
        self._schedule()

    def _schedule(self) -> None:
        import random

        def tick():
            try:
                collect_runtime_gauges(self.stats, self.planner,
                                       qos=self.qos)
            except Exception:
                pass  # monitoring must never kill the node
            finally:
                self._schedule()

        # close() races tick(): take the lock so a timer can never be
        # installed after close() cancelled the previous one.
        with self._lock:
            if self._closed:
                return
            self._timer = threading.Timer(
                self.interval * random.uniform(0.8, 1.2), tick)
            self._timer.daemon = True
            self._timer.start()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._timer is not None:
                self._timer.cancel()
