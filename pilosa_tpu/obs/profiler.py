"""Whole-process CPU profiling for a live node.

Reference: http/handler.go:281 exposes Go's pprof (CPU/heap) on a
running server. Python's cProfile only instruments the calling thread,
which is useless for a threaded server — so this is a SAMPLING profiler:
every tick it walks every thread's stack (``sys._current_frames``) and
aggregates per-function self/cumulative time, then serializes the result
in cProfile's marshal format so the standard ``pstats`` tooling
(``python -m pstats``, snakeviz, gprof2dot) reads it directly.

Overhead is bounded by the sampling interval (default 5 ms → ~1-2% on a
busy process), and unlike an instrumenting profiler it can be switched
on against production traffic.
"""

from __future__ import annotations

import marshal
import sys
import threading
import time


def sample_profile(seconds: float, interval: float = 0.005,
                   skip_thread: int | None = None) -> bytes:
    """Sample all threads for ``seconds``; returns a pstats-loadable
    marshal blob (write to a file, then ``pstats.Stats(path)``)."""
    stats: dict = {}
    own = threading.get_ident()
    deadline = time.monotonic() + max(0.05, float(seconds))
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == own or tid == skip_thread:
                continue
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append((code.co_filename, code.co_firstlineno,
                              code.co_name))
                f = f.f_back
            seen = set()
            for depth, key in enumerate(stack):
                e = stats.get(key)
                if e is None:
                    e = stats[key] = [0, 0, 0.0, 0.0]
                if depth == 0:
                    e[2] += interval       # tt: executing (top of stack)
                if key not in seen:
                    e[0] += 1
                    e[1] += 1
                    e[3] += interval       # ct: anywhere on the stack
                    seen.add(key)
        time.sleep(interval)
    # cProfile dump format: {(file, line, func): (cc, nc, tt, ct,
    # callers)}; callers omitted (empty) — pstats accepts it.
    return marshal.dumps({k: (v[0], v[1], v[2], v[3], {})
                          for k, v in stats.items()})
