"""Tracing: vendor-neutral Tracer/Span with a global singleton.

Reference: tracing/tracing.go (Tracer :32, Span :45, GlobalTracer :23,
StartSpanFromContext, InjectHTTPHeaders/ExtractHTTPHeaders for
cross-node propagation). SimpleTracer records spans in memory; a Jaeger/
OTLP exporter would implement the same two-method interface.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Protocol

TRACE_HEADER = "X-Pilosa-Trace-Id"

#: the active trace correlation id, carried across node boundaries via
#: TRACE_HEADER (reference InjectHTTPHeaders/ExtractHTTPHeaders,
#: tracing.go:37-49 + the http client's span injection).
_current_trace: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("pilosa_trace", default=None)
_trace_seq = itertools.count(1)
_trace_prefix = f"{os.getpid():x}"


def current_trace_id() -> str | None:
    return _current_trace.get()


def new_trace_id() -> str:
    """Mint a fresh trace id (same scheme spans use: pid-hex + seq)."""
    return f"{_trace_prefix}-{next(_trace_seq)}"


def set_current_trace(trace_id: str | None):
    """Returns a token for contextvars reset."""
    return _current_trace.set(trace_id)


def reset_current_trace(token) -> None:
    _current_trace.reset(token)


def inject_http_headers(headers: dict) -> dict:
    """Attach the active trace id to outgoing node-to-node requests."""
    tid = _current_trace.get()
    if tid:
        headers[TRACE_HEADER] = tid
    return headers


def extract_http_headers(headers) -> str | None:
    """Read a propagated trace id from incoming request headers."""
    return headers.get(TRACE_HEADER)


class Span(Protocol):
    def finish(self) -> None: ...
    def set_tag(self, key: str, value) -> None: ...


class Tracer(Protocol):
    def start_span(self, operation: str, parent_id: str | None = None) -> Span: ...


class _NopSpan:
    def finish(self) -> None:
        pass

    def set_tag(self, key, value) -> None:
        pass


class NopTracer:
    """Reference NopTracer (tracing.go:52)."""

    def start_span(self, operation: str, parent_id: str | None = None):
        return _NopSpan()


@dataclass
class RecordedSpan:
    operation: str
    start: float
    parent_id: str | None = None
    end: float | None = None
    tags: dict = field(default_factory=dict)
    span_id: str = ""

    def finish(self) -> None:
        self.end = time.perf_counter()

    def set_tag(self, key, value) -> None:
        self.tags[key] = value

    @property
    def duration(self) -> float | None:
        return None if self.end is None else self.end - self.start


class SimpleTracer:
    """In-memory recording tracer (test + debugging backend)."""

    def __init__(self, max_spans: int = 10_000):
        self.spans: list[RecordedSpan] = []
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._next = 0

    def start_span(self, operation: str, parent_id: str | None = None):
        span = RecordedSpan(operation=operation, start=time.perf_counter(),
                            parent_id=parent_id)
        with self._lock:
            self._next += 1
            span.span_id = str(self._next)
            if len(self.spans) < self.max_spans:
                self.spans.append(span)
        return span


_global: Tracer = NopTracer()


def set_tracer(t: Tracer) -> None:
    global _global
    _global = t


def get_tracer() -> Tracer:
    return _global


@contextlib.contextmanager
def start_span(operation: str, parent_id: str | None = None):
    """with start_span("executor.Execute"): ... — the
    StartSpanFromContext analog used at executor/API boundaries. Spans
    join the active cross-node trace (starting one if absent) and tag
    themselves with its id, so a query's spans correlate across every
    node it touched."""
    tid = _current_trace.get()
    token = None
    if tid is None:
        tid = f"{_trace_prefix}-{next(_trace_seq)}"
        token = _current_trace.set(tid)
    span = _global.start_span(operation, parent_id)
    span.set_tag("trace.id", tid)
    try:
        yield span
    finally:
        span.finish()
        if token is not None:
            _current_trace.reset(token)
