"""Tracing: vendor-neutral Tracer/Span with a global singleton.

Reference: tracing/tracing.go (Tracer :32, Span :45, GlobalTracer :23,
StartSpanFromContext, InjectHTTPHeaders/ExtractHTTPHeaders for
cross-node propagation). SimpleTracer records spans in memory; a Jaeger/
OTLP exporter would implement the same two-method interface.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Protocol

TRACE_HEADER = "X-Pilosa-Trace-Id"


class Span(Protocol):
    def finish(self) -> None: ...
    def set_tag(self, key: str, value) -> None: ...


class Tracer(Protocol):
    def start_span(self, operation: str, parent_id: str | None = None) -> Span: ...


class _NopSpan:
    def finish(self) -> None:
        pass

    def set_tag(self, key, value) -> None:
        pass


class NopTracer:
    """Reference NopTracer (tracing.go:52)."""

    def start_span(self, operation: str, parent_id: str | None = None):
        return _NopSpan()


@dataclass
class RecordedSpan:
    operation: str
    start: float
    parent_id: str | None = None
    end: float | None = None
    tags: dict = field(default_factory=dict)
    span_id: str = ""

    def finish(self) -> None:
        self.end = time.perf_counter()

    def set_tag(self, key, value) -> None:
        self.tags[key] = value

    @property
    def duration(self) -> float | None:
        return None if self.end is None else self.end - self.start


class SimpleTracer:
    """In-memory recording tracer (test + debugging backend)."""

    def __init__(self, max_spans: int = 10_000):
        self.spans: list[RecordedSpan] = []
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._next = 0

    def start_span(self, operation: str, parent_id: str | None = None):
        span = RecordedSpan(operation=operation, start=time.perf_counter(),
                            parent_id=parent_id)
        with self._lock:
            self._next += 1
            span.span_id = str(self._next)
            if len(self.spans) < self.max_spans:
                self.spans.append(span)
        return span


_global: Tracer = NopTracer()


def set_tracer(t: Tracer) -> None:
    global _global
    _global = t


def get_tracer() -> Tracer:
    return _global


@contextlib.contextmanager
def start_span(operation: str, parent_id: str | None = None):
    """with start_span("executor.Execute"): ... — the
    StartSpanFromContext analog used at executor/API boundaries."""
    span = _global.start_span(operation, parent_id)
    try:
        yield span
    finally:
        span.finish()
