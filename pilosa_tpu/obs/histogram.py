"""Fixed log-bucket histograms: bounded memory, mergeable, quantiles.

Replaces the unbounded per-key timing lists in MemoryStats (ISSUE 11:
a sustained-traffic memory leak) with O(buckets) state per series. The
bucket layout is FIXED at construction — log-spaced bounds — so two
histograms with the same bounds merge by adding counts, which is what
the cluster /metrics aggregation and the bench harness need.

Each bucket also retains the LAST observation's (value, trace_id) as an
exemplar; prometheus_text() emits exemplars only on p99-and-above
buckets, so a slow bucket in a Grafana heatmap links straight to a
retained profile at /debug/queries/<trace-id>.
"""

from __future__ import annotations

import bisect
import threading

#: default bounds for latency-in-seconds series: 100 µs doubling up to
#: ~13 s (18 finite buckets + the implicit +Inf). One query's histogram
#: is ~20 machine words — the whole registry stays bounded no matter how
#: long the node serves.
SECONDS_BOUNDS: tuple[float, ...] = tuple(1e-4 * (2 ** i) for i in range(18))

#: bounds for small-integer width series (coalesce batch width,
#: TransferBatcher wave width, queue depth): exact powers of two.
WIDTH_BOUNDS: tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class LogHistogram:
    """Fixed-bound histogram with per-bucket exemplars.

    ``lock=False`` skips the internal lock for callers that already
    serialize observes (MemoryStats holds its registry lock around every
    ``timing()``), keeping the hot path to one bisect + three adds.
    """

    __slots__ = ("bounds", "counts", "sum", "count", "_exemplars", "_lock")

    def __init__(self, bounds: tuple[float, ...] = SECONDS_BOUNDS,
                 lock: bool = True):
        self.bounds = tuple(bounds)
        # counts[i] observations fell in (bounds[i-1], bounds[i]];
        # counts[-1] is the +Inf overflow bucket.
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self._exemplars: dict[int, tuple[float, str]] = {}
        self._lock = threading.Lock() if lock else None

    def observe(self, value: float, trace_id: str | None = None) -> None:
        i = bisect.bisect_left(self.bounds, value)
        if self._lock is not None:
            with self._lock:
                self._observe_at(i, value, trace_id)
        else:
            self._observe_at(i, value, trace_id)

    def _observe_at(self, i: int, value: float, trace_id) -> None:
        self.counts[i] += 1
        self.sum += value
        self.count += 1
        if trace_id:
            self._exemplars[i] = (value, trace_id)

    def merge(self, other: "LogHistogram") -> None:
        """Fold another histogram (same bounds) into this one."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count
        self._exemplars.update(other._exemplars)

    def quantile(self, q: float) -> float:
        """Approximate quantile by linear interpolation inside the
        bucket the rank lands in (0 when empty; the last finite bound
        when the rank falls in +Inf — a floor, clearly marked bounded)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i >= len(self.bounds):        # +Inf bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * frac
            seen += c
        return self.bounds[-1]

    def bucket_items(self) -> list[tuple[str, int]]:
        """Cumulative (le_label, count) pairs for Prometheus exposition,
        ending with ("+Inf", total)."""
        out = []
        cum = 0
        for i, b in enumerate(self.bounds):
            cum += self.counts[i]
            out.append((f"{b:g}", cum))
        out.append(("+Inf", self.count))
        return out

    def p99_bucket_index(self) -> int:
        """Index of the bucket containing p99 — exemplar emission is
        gated to buckets at or above this index."""
        if self.count == 0:
            return len(self.counts)
        rank = 0.99 * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return i
        return len(self.counts) - 1

    def exemplar(self, i: int) -> tuple[float, str] | None:
        return self._exemplars.get(i)

    def snapshot(self) -> dict:
        """Plain-JSON view for the /debug endpoints."""
        return {
            "count": self.count,
            "sum": self.sum,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
            "p999": self.quantile(0.999),
            "buckets": [
                {"le": le, "count": c} for le, c in self.bucket_items()
                if c > 0 or le == "+Inf"
            ],
        }
