"""Stats: tagged counters/gauges/timings.

Reference: stats/stats.go:31 (StatsClient interface: WithTags, Count,
Gauge, Histogram, Timing, SetLogger), default expvar backend, and
prometheus/prometheus.go scraped at /metrics. Here MemoryStats is the
expvar analog and doubles as the Prometheus registry — prometheus_text()
renders the exposition format without a client library.
"""

from __future__ import annotations

import threading
from typing import Protocol

from pilosa_tpu.obs.histogram import LogHistogram
from pilosa_tpu.obs.tracing import current_trace_id


class StatsClient(Protocol):
    def with_tags(self, *tags: str) -> "StatsClient": ...
    def count(self, name: str, value: int = 1, rate: float = 1.0) -> None: ...
    def gauge(self, name: str, value: float) -> None: ...
    def timing(self, name: str, seconds: float) -> None: ...


class NopStats:
    """Reference NopStatsClient."""

    def with_tags(self, *tags: str) -> "NopStats":
        return self

    def count(self, name: str, value: int = 1, rate: float = 1.0) -> None:
        pass

    def gauge(self, name: str, value: float) -> None:
        pass

    def timing(self, name: str, seconds: float) -> None:
        pass


class MemoryStats:
    """In-memory tagged metrics (expvar analog + prometheus registry)."""

    def __init__(self, tags: tuple[str, ...] = (), _parent=None):
        self.tags = tags
        if _parent is None:
            self._lock = threading.Lock()
            self.counters: dict[tuple[str, tuple], float] = {}
            self.gauges: dict[tuple[str, tuple], float] = {}
            # Bounded log-bucket histograms, NOT lists: a sustained-
            # traffic node used to grow one float per observation per
            # series forever (ISSUE 11 leak). Each value is O(buckets).
            self.timings: dict[tuple[str, tuple], LogHistogram] = {}
        else:
            self._lock = _parent._lock
            self.counters = _parent.counters
            self.gauges = _parent.gauges
            self.timings = _parent.timings

    def with_tags(self, *tags: str) -> "MemoryStats":
        return MemoryStats(tuple(sorted(set(self.tags) | set(tags))),
                           _parent=self)

    def count(self, name: str, value: int = 1, rate: float = 1.0) -> None:
        with self._lock:
            key = (name, self.tags)
            self.counters[key] = self.counters.get(key, 0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[(name, self.tags)] = value

    def timing(self, name: str, seconds: float) -> None:
        # Exemplar = the active trace id, read OUTSIDE the lock (one
        # contextvar get; None when untraced).
        tid = current_trace_id()
        with self._lock:
            key = (name, self.tags)
            h = self.timings.get(key)
            if h is None:
                # The registry lock already serializes observes.
                h = self.timings[key] = LogHistogram(lock=False)
            h.observe(seconds, trace_id=tid)

    def counter_value(self, name: str, *tags: str) -> float:
        return self.counters.get((name, tuple(sorted(tags))), 0)

    def timing_count(self, name: str, *tags: str) -> int:
        h = self.timings.get((name, tuple(sorted(tags))))
        return 0 if h is None else h.count

    def timing_sum(self, name: str, *tags: str) -> float:
        h = self.timings.get((name, tuple(sorted(tags))))
        return 0.0 if h is None else h.sum

    def timing_quantile(self, name: str, q: float, *tags: str) -> float:
        h = self.timings.get((name, tuple(sorted(tags))))
        return 0.0 if h is None else h.quantile(q)


class StatsdStats:
    """Fire-and-forget UDP statsd backend (reference statsd/statsd.go;
    tags use the datadog-style ``|#k:v`` suffix). Wraps every send in a
    broad except — metrics must never take the node down — and shares
    one socket across tag children."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "pilosa.", tags: tuple[str, ...] = (),
                 _parent=None):
        self.tags = tags
        if _parent is None:
            import socket
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._addr = (host, int(port))
            self.prefix = prefix
        else:
            self._sock = _parent._sock
            self._addr = _parent._addr
            self.prefix = _parent.prefix

    def with_tags(self, *tags: str) -> "StatsdStats":
        return StatsdStats(tags=tuple(sorted(set(self.tags) | set(tags))),
                           _parent=self)

    def _send(self, payload: str) -> None:
        try:
            if self.tags:
                payload += "|#" + ",".join(self.tags)
            self._sock.sendto(payload.encode(), self._addr)
        except OSError:
            pass

    def count(self, name: str, value: int = 1, rate: float = 1.0) -> None:
        self._send(f"{self.prefix}{name}:{value}|c")

    def gauge(self, name: str, value: float) -> None:
        self._send(f"{self.prefix}{name}:{value}|g")

    def timing(self, name: str, seconds: float) -> None:
        self._send(f"{self.prefix}{name}:{seconds * 1e3:.3f}|ms")


def _fmt_labels(tags: tuple[str, ...], extra: str = "") -> str:
    """Render ``{k="v",...}``; ``extra`` is a pre-formatted pair (the
    histogram ``le=...`` label) merged after the tag labels."""
    pairs = []
    for t in tags:
        k, _, v = t.partition(":")
        pairs.append(f'{_sanitize(k)}="{v or "true"}"')
    if extra:
        pairs.append(extra)
    if not pairs:
        return ""
    return "{" + ",".join(pairs) + "}"


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def prometheus_text(stats: MemoryStats) -> str:
    """Prometheus exposition format (the /metrics payload,
    prometheus/prometheus.go analog)."""
    lines = []
    with stats._lock:
        for (name, tags), v in sorted(stats.counters.items()):
            lines.append(f"# TYPE pilosa_{_sanitize(name)} counter")
            lines.append(f"pilosa_{_sanitize(name)}{_fmt_labels(tags)} {v}")
        for (name, tags), v in sorted(stats.gauges.items()):
            lines.append(f"# TYPE pilosa_{_sanitize(name)} gauge")
            lines.append(f"pilosa_{_sanitize(name)}{_fmt_labels(tags)} {v}")
        for (name, tags), h in sorted(stats.timings.items()):
            n = _sanitize(name)
            # Timing keys like "qos.waitSeconds" already name the unit;
            # don't render pilosa_qos_waitSeconds_seconds.
            if n.lower().endswith("seconds"):
                n = n[:-len("seconds")].rstrip("_")
            lines.append(f"# TYPE pilosa_{n}_seconds histogram")
            p99 = h.p99_bucket_index()
            for i, (le, cum) in enumerate(h.bucket_items()):
                le_label = f'le="{le}"'
                line = (f"pilosa_{n}_seconds_bucket"
                        f"{_fmt_labels(tags, le_label)} {cum}")
                # OpenMetrics exemplar on p99-and-above buckets only:
                # the slow tail links to a retained /debug/queries
                # profile; fast buckets stay exemplar-free (payload
                # size, and nobody clicks into a p50 bucket).
                ex = h.exemplar(i) if i >= p99 else None
                if ex is not None:
                    val, tid = ex
                    line += f' # {{trace_id="{tid}"}} {val:g}'
                lines.append(line)
            lines.append(f"pilosa_{n}_seconds_count{_fmt_labels(tags)} "
                         f"{h.count}")
            lines.append(f"pilosa_{n}_seconds_sum{_fmt_labels(tags)} "
                         f"{h.sum}")
    return "\n".join(lines) + "\n"
