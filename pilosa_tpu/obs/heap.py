"""Heap / memory observability (VERDICT r4 missing #3).

The reference exposes Go pprof heap at /debug/pprof (http/handler.go:
281); an operator can always answer "where did the RAM go".  This
node's memory lives in four places the Python allocator can't see as
one number: Python objects (tracemalloc), the native recycled page pool
(roaring_codec pool_stats), the planner's budgeted HBM stack cache, and
the per-index host rows (sparse position arrays / dense word blocks /
pending buffers).  ``heap_stats`` gathers all four into one JSON for
the ``/debug/heap`` route.

tracemalloc is started lazily on the first call (it has ~2x allocation
overhead while tracing, so it is not on by default); the first snapshot
therefore covers allocations made after that call.
"""

from __future__ import annotations

import tracemalloc
from typing import Any


def _host_row_bytes(hr) -> int:
    n = 0
    if hr.positions is not None:
        n += hr.positions.nbytes
    if hr.dense is not None:
        n += hr.dense.nbytes
    pending = getattr(hr, "_pending", None)
    if pending:
        n += 8 * len(pending)  # buffered positions (set of ints)
    return n


def holder_heap(holder) -> dict[str, Any]:
    """Per-index host-side row memory: {index: {bytes, fragments, rows,
    dense_rows}} plus totals."""
    out: dict[str, Any] = {}
    for iname in holder.index_names():
        idx = holder.index(iname)
        if idx is None:
            continue
        ib = frags = rows = dense = 0
        # list() snapshots: concurrent imports mutate these dicts and a
        # live iterator would raise mid-walk (same lockless-reader
        # discipline as fragment.py's contains/rows_list).
        for f in list(idx.fields.values()):
            for v in list(f.views.values()):
                for frag in list(v.fragments.values()):
                    frags += 1
                    for hr in list(frag.rows.values()):
                        rows += 1
                        if hr.is_dense:
                            dense += 1
                        ib += _host_row_bytes(hr)
        out[iname] = {"host_row_bytes": ib, "fragments": frags,
                      "rows": rows, "dense_rows": dense}
    return out


def tracemalloc_top(n: int = 25) -> dict[str, Any]:
    """Top-N allocation sites by retained bytes; starts tracing on the
    first call (stats accumulate from then on)."""
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return {"tracing": "started",
                "note": "tracemalloc started now; allocation sites appear "
                        "from the next call on"}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    traced_current, traced_peak = tracemalloc.get_traced_memory()
    return {
        "tracing": "on",
        "traced_current_bytes": traced_current,
        "traced_peak_bytes": traced_peak,
        "top": [{"site": str(s.traceback[0]) if s.traceback else "?",
                 "bytes": s.size, "count": s.count}
                for s in stats[:n]],
    }


def heap_stats(holder, planner=None, top_n: int = 25) -> dict[str, Any]:
    """One answer to "where did the RAM go" (see module doc)."""
    from pilosa_tpu import native

    out: dict[str, Any] = {
        "tracemalloc": tracemalloc_top(top_n),
        "native_pool": native.pool_stats() or {"available": False},
        "host_rows": holder_heap(holder),
    }
    if planner is not None and hasattr(planner, "cache_stats"):
        out["planner_cache"] = planner.cache_stats()
    try:  # process-level ground truth, when the platform offers it
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(("VmRSS:", "VmHWM:")):
                    key = line.split(":")[0].lower()
                    out[f"{key}_kib"] = int(line.split()[1])
    except OSError:
        pass
    return out
