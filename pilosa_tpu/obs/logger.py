"""Logger interface with verbose/debug split.

Reference: logger/logger.go (Logger interface: Printf/Debugf, NopLogger,
standard + verbose implementations).
"""

from __future__ import annotations

import sys
import time
from typing import Protocol


class Logger(Protocol):
    def printf(self, fmt: str, *args) -> None: ...
    def debugf(self, fmt: str, *args) -> None: ...


class NopLogger:
    def printf(self, fmt: str, *args) -> None:
        pass

    def debugf(self, fmt: str, *args) -> None:
        pass


class StandardLogger:
    def __init__(self, stream=None, verbose: bool = False):
        self.stream = stream or sys.stderr
        self.verbose = verbose

    def _emit(self, fmt: str, args) -> None:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        self.stream.write(f"{ts} {fmt % args if args else fmt}\n")

    def printf(self, fmt: str, *args) -> None:
        self._emit(fmt, args)

    def debugf(self, fmt: str, *args) -> None:
        if self.verbose:
            self._emit(fmt, args)
