"""API — the complete externally-reachable operation surface.

Reference: api.go (API struct :42, Query :135, index/field CRUD :162-467,
Import/ImportValue :920-1127, ExportCSV :500, schema :726-758, Status and
cluster ops :1129-1260). Every HTTP/CLI entry point goes through here; the
HTTP layer is a thin router over these methods.
"""

from __future__ import annotations

import io
from datetime import timezone
from typing import Any, Iterable

import numpy as np

from pilosa_tpu.cluster.cluster import (
    STATE_DEGRADED,
    STATE_NORMAL,
    STATE_REMOVED,
    STATE_RESIZING,
)
from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.core.row import Row
from pilosa_tpu.errors import (
    ApiMethodNotAllowedError,
    ClusterFencedError,
    FieldNotFoundError,
    FragmentNotFoundError,
    IndexNotFoundError,
)
from pilosa_tpu.exec.executor import ExecOptions, Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.obs import profile as _profile
from pilosa_tpu.pql import parse


class API:
    """Reference API (api.go:42)."""

    def __init__(self, holder: Holder, executor: Executor, cluster=None,
                 syncer=None):
        self.holder = holder
        self.executor = executor
        self.cluster = cluster
        self.syncer = syncer
        #: DiskStore (set by ServerNode when a data dir is configured)
        #: so view/field deletions can unlink their on-disk fragments.
        self.store = None
        #: cluster key-allocation hook: (index, field|None, keys) -> ids
        #: (ClusterKeyTranslator); None = allocate locally.
        self.translator = None
        #: this server's own ring entry (cluster.node.Node), set by
        #: ServerNode — used to answer routing queries on a standalone
        #: node, where there is no cluster to consult.
        self.local_node = None
        #: QoS front (pilosa_tpu.qos.AdmissionController), set by
        #: ServerNode; None = no admission gate, no default deadline,
        #: no slow-query log — the pre-QoS behavior.
        self.qos = None
        #: slowest-N retained query profiles (obs.profile.ProfileRing),
        #: set by ServerNode; served at /debug/queries.
        self.profile_ring = None

    #: method-availability matrix per cluster state (reference
    #: api.go:99-105 validAPIMethods + :1379-1411 method sets): during
    #: STARTING only control-plane traffic flows; during RESIZING only
    #: control plane + abort. (Serve-through resize never enters
    #: RESIZING; the state survives for manual/legacy transitions. The
    #: old fragment-data pull path is gone — fragments move over the
    #: PTS1 import stream now.)
    _METHODS_RESIZING = frozenset({"resize-abort"})

    #: read-only methods a FENCED node may keep serving when the
    #: operator opts into staleness (Cluster.fence_stale_reads) — a
    #: minority partition's data can be arbitrarily behind the majority.
    _METHODS_FENCED_READS = frozenset({"query", "export-csv"})

    def _validate(self, method: str, internal: bool = False) -> None:
        if self.cluster is None:
            return  # standalone node: always NORMAL
        if getattr(self.cluster, "fenced", False) and not internal:
            # Quorum fence: this node cannot see a majority of the ring,
            # so accepting client traffic risks split-brain writes the
            # majority will never learn about. Internal traffic
            # (peer-forwarded imports, remote query legs, repair pushes
            # from the majority) is exempt — it is how the fence heals.
            if not (self.cluster.fence_stale_reads
                    and method in self._METHODS_FENCED_READS):
                raise ClusterFencedError(
                    f"api method {method} refused: node is fenced "
                    f"(no quorum)")
        state = self.cluster.state
        if state in (STATE_NORMAL, STATE_DEGRADED):
            return
        if (internal and method in self._METHODS_FENCED_READS
                and state != STATE_REMOVED):
            # A partitioned minority sees >= replicaN peers DOWN and
            # sits in STARTING by the ladder below — but the majority's
            # detector may already have healed and resumed fanning read
            # legs here, and our local fragments are still its replica
            # copies. Internal reads stay up; writes stay gated (a
            # joiner's grant is the migration-table carve-out below).
            return
        if state == STATE_RESIZING and method in self._METHODS_RESIZING:
            return
        if (method in ("import", "import-value", "import-roaring")
                and getattr(self.cluster, "migration", None) is not None
                and state != STATE_REMOVED):
            # Mid-migration dual-apply legs (and the resize-push bulk
            # stream itself) must land on a STARTING joiner: it has a
            # migration table from resize-begin, which is the
            # coordinator's explicit grant to receive data for shards
            # it will own after the commit.
            return
        raise ApiMethodNotAllowedError(
            f"api method {method} not allowed in state {state}")

    #: public alias for route handlers that serve holder state directly
    #: (fragment streaming) rather than through an API method.
    validate_method = _validate

    def _xlate_keys(self, idx, f, keys: Iterable[str]) -> list[int]:
        keys = list(keys)
        if self.translator is not None:
            return self.translator(idx.name,
                                   f.name if f is not None else None, keys)
        # One batched allocation: one lock, one epoch bump per batch.
        store = (f if f is not None else idx).translate_store
        return store.translate_keys(keys)

    # -- query (api.go:135) ------------------------------------------------

    def query(self, index: str, query: str,
              shards: list[int] | None = None, column_attrs: bool = False,
              exclude_row_attrs: bool = False, exclude_columns: bool = False,
              remote: bool = False, accept_frames: bool = False,
              cache: bool = True):
        """Execute PQL; returns the QueryResponse JSON dict
        ({"results": [...]} shape, handler.go:60-75) — or, for remote
        calls whose peer accepts them, binary frames (bytes) carrying
        Row results as roaring blobs (wire.encode_frames)."""
        if (remote
                and self.cluster is not None
                and getattr(self.cluster, "migration", None) is not None
                and self.cluster.state != STATE_REMOVED):
            # Dual-apply write legs arrive as remote PQL (Set/Clear)
            # and must land on a STARTING joiner mid-migration. Reads
            # are never routed here pre-commit — the coordinator's
            # old-ring placement doesn't know joiners exist.
            pass
        else:
            # Remote legs are coordinator-internal: a fenced node must
            # still answer the majority's fan-out (it may be THEIR
            # replica), only client-facing traffic is gated.
            self._validate("query", internal=remote)
        opt = ExecOptions(remote=remote, column_attrs=column_attrs,
                          exclude_row_attrs=exclude_row_attrs,
                          exclude_columns=exclude_columns)
        epochs = None
        if remote and shards:
            # Read BEFORE executing: the reported vector is never
            # fresher than the data in the result, so a write landing
            # mid-leg raises the next report and invalidates the
            # coordinator's cached entry (see cache/remote.py).
            idx = self.holder.index(index)
            if idx is not None:
                epochs = idx.epoch.shard_vector(shards)
        results = self.executor.execute(index, query, shards=shards, opt=opt,
                                        cache=cache)
        if remote:
            # Node-to-node response: typed envelope the coordinator can
            # decode back to internal results (encoding/proto analog),
            # stamped with this node's shard-epoch vector.
            from pilosa_tpu.server import wire
            extra = ({"shardEpochs": {str(s): e for s, e in epochs.items()}}
                     if epochs else None)
            prof = _profile.current()
            if prof is not None:
                # The coordinator asked for a nested per-leg timeline:
                # close this node's ledger and ride it home in the
                # response header next to the epoch stamp.
                from pilosa_tpu.exec import fuse as _fuse
                prof.fused_steps = _fuse.fused_steps()
                extra = dict(extra or {})
                extra["profile"] = prof.finish()
            if accept_frames:
                # accept_frames == 2 means the peer negotiated the v2
                # layout (aggregates as typed array blobs); plain True
                # keeps the v1 layout for not-yet-upgraded peers.
                return wire.encode_frames(
                    results, extra=extra,
                    version=2 if accept_frames == 2 else 1)
            resp = {"results": [wire.encode_result(r) for r in results]}
            if extra:
                resp.update(extra)
            return resp
        resp: dict[str, Any] = {"results": [result_to_json(r) for r in results]}
        if opt.column_attrs:
            resp["columnAttrs"] = self._column_attr_sets(index, results)
        return resp

    def _column_attr_sets(self, index: str, results: list) -> list[dict]:
        """Attrs of every column appearing in Row results
        (reference executor.go ColumnAttrSets assembly)."""
        idx = self.holder.index_or_raise(index)
        cols: set[int] = set()
        for r in results:
            if isinstance(r, Row):
                cols.update(int(c) for c in r.columns())
        out = []
        for c in sorted(cols):
            attrs = idx.column_attr_store.attrs(c)
            if attrs:
                out.append({"id": c, "attrs": attrs})
        return out

    # -- schema CRUD (api.go:162-467) --------------------------------------

    def create_index(self, name: str, options: dict | None = None):
        self._validate("create-index")
        idx = self.holder.create_index(
            name, IndexOptions.from_json(options or {}))
        self._broadcast({"type": "create-index", "index": name,
                         "options": options or {}})
        return idx

    def delete_index(self, name: str) -> None:
        self._validate("delete-index")
        self.holder.delete_index(name)
        if self.store is not None:
            # Unlink the on-disk tree too: recreating the name must not
            # resurrect deleted data on the next restart.
            self.store.delete_subtree_files(name)
        self._broadcast({"type": "delete-index", "index": name})

    def create_field(self, index: str, field: str,
                     options: dict | None = None):
        self._validate("create-field")
        idx = self.holder.index_or_raise(index)
        f = idx.create_field(field, FieldOptions.from_json(options or {}))
        self._broadcast({"type": "create-field", "index": index,
                         "field": field, "options": options or {}})
        return f

    def delete_field(self, index: str, field: str) -> None:
        self._validate("delete-field")
        idx = self.holder.index_or_raise(index)
        idx.delete_field(field)
        if self.store is not None:
            self.store.delete_subtree_files(index, field)
        self._broadcast({"type": "delete-field", "index": index,
                         "field": field})

    def views(self, index: str, field: str) -> list[str]:
        """Reference API.Views (api.go:760)."""
        f = self._field_or_raise(index, field)
        return f.view_names()

    def delete_view(self, index: str, field: str, view: str) -> None:
        """Reference API.DeleteView (api.go:779): drop a view locally
        and broadcast so every node holding its shards follows
        (DeleteViewMessage, server.go:618)."""
        self._validate("delete-view")
        f = self._field_or_raise(index, field)
        f.delete_view(view)
        if self.store is not None:
            self.store.delete_subtree_files(index, field, view)
        self._broadcast({"type": "delete-view", "index": index,
                         "field": field, "view": view})

    def _field_or_raise(self, index: str, field: str):
        idx = self.holder.index_or_raise(index)
        f = idx.field(field)
        if f is None:
            raise FieldNotFoundError(field)
        return f

    def schema(self) -> list[dict]:
        return self.holder.schema()

    def apply_schema(self, schema: list[dict], remote: bool = False) -> None:
        """Reference API.ApplySchema (api.go:738): replicate a whole
        schema onto this cluster. remote=False fans the schema out to
        every node first (each peer applies with remote=true); designed
        for seeding an empty cluster from another one's schema."""
        self._validate("apply-schema")
        # Local first, then best-effort fan-out: an unreachable peer
        # must not leave the cluster half-applied with the ORIGIN node
        # empty — stragglers converge via anti-entropy's schema pull.
        self.holder.apply_schema(schema)
        if not remote and self.cluster is not None:
            for node in self.cluster.nodes:
                if node.id == self.cluster.local_id or node.state == "DOWN":
                    continue
                try:
                    self.cluster.client.post_schema(node, schema)
                except (ConnectionError, RuntimeError, LookupError):
                    pass

    def index_info(self, index: str) -> dict:
        return self.holder.index_or_raise(index).info()

    # -- imports (api.go:920-1127) -----------------------------------------

    def import_bits(self, index: str, field: str, row_ids: Iterable[int],
                    column_ids: Iterable[int],
                    timestamps: Iterable[int | None] | None = None,
                    row_keys: Iterable[str] | None = None,
                    column_keys: Iterable[str] | None = None,
                    clear: bool = False) -> None:
        """Batch bit import with key translation; routes each shard's
        batch to owning nodes when clustered."""
        self._validate("import")
        idx = self.holder.index_or_raise(index)
        f = idx.field(field)
        if f is None:
            raise FieldNotFoundError()
        if row_keys is not None:
            row_ids = self._xlate_keys(idx, f, row_keys)
        if column_keys is not None:
            column_ids = self._xlate_keys(idx, None, column_keys)
        ts = None
        if timestamps is not None:
            ts = [tq.parse_time(t) if t else None for t in timestamps]
        row_ids = list(row_ids)
        column_ids = list(column_ids)
        if self.cluster is not None:
            self._route_import(index, field, row_ids, column_ids, ts, clear,
                               values=None)
        else:
            f.import_bits(row_ids, column_ids, ts, clear=clear)
        idx.add_existence(column_ids)

    def import_values(self, index: str, field: str,
                      column_ids: Iterable[int], values: Iterable[int],
                      column_keys: Iterable[str] | None = None,
                      clear: bool = False) -> None:
        self._validate("import-value")
        idx = self.holder.index_or_raise(index)
        f = idx.field(field)
        if f is None:
            raise FieldNotFoundError()
        if column_keys is not None:
            column_ids = self._xlate_keys(idx, None, column_keys)
        column_ids = list(column_ids)
        values = list(values)
        if self.cluster is not None:
            self._route_import(index, field, None, column_ids, None, clear,
                               values=values)
        else:
            f.import_values(column_ids, values, clear=clear)
        idx.add_existence(column_ids)

    def _route_import(self, index, field, row_ids, column_ids, ts, clear,
                      values):
        """Group by shard, send each batch to every owning node
        (api.go:967-1030).

        The by-shard split is a stable argsort + boundary scan (the
        per-element dict walk was the coordinator's bottleneck at
        production rate; stable keeps last-write-wins order within a
        shard). Remote batches carry epoch-second timestamps (binary
        wire) and every remote node's batches go out as ONE pipelined
        import stream when the transport supports it."""
        n = len(column_ids)
        if n == 0:
            return
        cols_arr = np.asarray(column_ids, dtype=np.uint64)
        shards = cols_arr // np.uint64(SHARD_WIDTH)
        order = np.argsort(shards, kind="stable")
        sorted_shards = shards[order]
        bounds = np.flatnonzero(np.diff(sorted_shards)) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [n]))
        rows_arr = (np.asarray(row_ids, dtype=np.uint64)
                    if row_ids is not None else None)
        vals_arr = (np.asarray(values, dtype=np.int64)
                    if values is not None else None)
        epoch = None
        if ts is not None:
            # parse_time yields naive-UTC datetimes; ship epoch seconds
            # so the wire can pack them as a raw u64 blob.
            epoch = [None if t is None else
                     int(t.replace(tzinfo=timezone.utc).timestamp())
                     for t in ts]
        f = self.holder.field(index, field)
        for _attempt in range(3):
            if self._route_import_pass(index, field, f, ts, clear, values,
                                       order, sorted_shards, starts, ends,
                                       cols_arr, rows_arr, vals_arr, epoch):
                return
        # Topology kept moving across every retry; the last idempotent
        # pass still applied under SOME complete placement and marked
        # dirty shards for the scrubber.

    def _route_import_pass(self, index, field, f, ts, clear, values,
                           order, sorted_shards, starts, ends,
                           cols_arr, rows_arr, vals_arr, epoch) -> bool:
        """One routing pass; returns True when the topology held still
        for its whole duration. A resize commit landing mid-pass could
        strand a shard batch on the old owners with no dual leg (the
        migration table is cleared at commit), so the caller re-applies
        — imports are idempotent — until owners and table were stable."""
        v0 = self.cluster.topology_version
        mig = getattr(self.cluster, "migration", None)
        remote: dict[str, tuple[Any, list[dict]]] = {}
        dual: dict[str, tuple[Any, list[dict]]] = {}
        for s, e in zip(starts.tolist(), ends.tolist()):
            shard = int(sorted_shards[s])
            sel = order[s:e]
            cols = cols_arr[sel]
            rows_b = rows_arr[sel] if rows_arr is not None else None
            vals_b = vals_arr[sel] if vals_arr is not None else None
            ts_b = ([epoch[i] for i in sel.tolist()]
                    if epoch is not None else None)
            for node in self.cluster.shard_nodes(index, shard):
                if node.id == self.cluster.local_id:
                    if values is None:
                        f.import_bits(
                            rows_b, cols,
                            [ts[i] for i in sel.tolist()] if ts else None,
                            clear=clear)
                    else:
                        f.import_values(cols, vals_b, clear=clear)
                else:
                    req = {"kind": "field", "index": index, "field": field,
                           "shard": shard, "rowIDs": rows_b,
                           "columnIDs": cols, "values": vals_b,
                           "clear": clear}
                    if ts_b is not None:
                        req["timestamps"] = ts_b
                    remote.setdefault(node.id, (node, []))[1].append(req)
            if mig is not None:
                # Serve-through resize: mirror each shard batch to the
                # shard's future owners (AFTER old owners above, per
                # the catch-up epoch guard's apply-order contract).
                for node in mig.dual_targets(self.cluster, index, shard):
                    if node.id == self.cluster.local_id:
                        try:  # shrink: this node gains the shard
                            if values is None:
                                f.import_bits(
                                    rows_b, cols,
                                    [ts[i] for i in sel.tolist()]
                                    if ts else None, clear=clear)
                            else:
                                f.import_values(cols, vals_b, clear=clear)
                            self.cluster.stats.count(
                                "cluster.resize.dualWrites")
                        except (RuntimeError, LookupError, ValueError) as ex:
                            self.cluster.dirty_shards.mark(index, shard)
                            self.cluster.stats.count(
                                "cluster.resize.dualWriteFailed")
                            self.cluster._report_dual_write_failure(
                                mig, node.id, ex)
                        continue
                    req = {"kind": "field", "index": index, "field": field,
                           "shard": shard, "rowIDs": rows_b,
                           "columnIDs": cols, "values": vals_b,
                           "clear": clear}
                    if ts_b is not None:
                        req["timestamps"] = ts_b
                    dual.setdefault(node.id, (node, []))[1].append(req)
        send_stream = getattr(self.cluster.client,
                              "send_import_stream", None)

        def ship(node, reqs):
            if send_stream is not None and len(reqs) > 1:
                send_stream(node, reqs)
            else:
                for r in reqs:
                    self.cluster.client.send_import(
                        node, index, field, r["shard"], rows=r["rowIDs"],
                        cols=r["columnIDs"], values=r["values"],
                        timestamps=r.get("timestamps"), clear=clear)
        for node, reqs in remote.values():
            ship(node, reqs)
        for node, reqs in dual.values():
            # Dual legs must not fail the user's import: the old-ring
            # writes above already landed, so a target failure is the
            # TARGET's problem — dirty-mark for scrub and tell the
            # coordinator to fail it out of the job.
            try:
                ship(node, reqs)
                self.cluster.stats.count("cluster.resize.dualWrites",
                                         len(reqs))
            except (ConnectionError, RuntimeError, LookupError) as ex:
                for r in reqs:
                    self.cluster.dirty_shards.mark(index, r["shard"])
                self.cluster.stats.count("cluster.resize.dualWriteFailed")
                self.cluster._report_dual_write_failure(mig, node.id, ex)
        return (self.cluster.topology_version == v0
                and getattr(self.cluster, "migration", None) is mig)

    def import_roaring(self, index: str, field: str, shard: int,
                       data: bytes, clear: bool = False) -> None:
        """Reference API.ImportRoaring (api.go:368)."""
        self._validate("import-roaring")
        idx = self.holder.index_or_raise(index)
        f = idx.field(field)
        if f is None:
            raise FieldNotFoundError()
        if self.cluster is not None:
            self._import_roaring_fanout(index, field, shard, data, clear, f)
        else:
            f.import_roaring(shard, data, clear=clear)

    def _import_roaring_fanout(self, index, field, shard, data, clear, f):
        for _attempt in range(3):
            # Same mid-commit guard as _route_import: snapshot the
            # migration table BEFORE resolving owners, re-apply (the
            # roaring import is idempotent) if a resize moved the
            # topology under this fan-out.
            v0 = self.cluster.topology_version
            mig = getattr(self.cluster, "migration", None)
            for node in self.cluster.shard_nodes(index, shard):
                if node.id == self.cluster.local_id:
                    f.import_roaring(shard, data, clear=clear)
                else:
                    self.cluster.client.send_import_roaring(
                        node, index, field, shard, data, clear)
            if mig is not None:
                for node in mig.dual_targets(self.cluster, index, shard):
                    try:
                        if node.id == self.cluster.local_id:
                            f.import_roaring(shard, data, clear=clear)
                        else:
                            self.cluster.client.send_import_roaring(
                                node, index, field, shard, data, clear)
                        self.cluster.stats.count(
                            "cluster.resize.dualWrites")
                    except (ConnectionError, RuntimeError,
                            LookupError, ValueError) as ex:
                        self.cluster.dirty_shards.mark(index, shard)
                        self.cluster.stats.count(
                            "cluster.resize.dualWriteFailed")
                        self.cluster._report_dual_write_failure(
                            mig, node.id, ex)
            if (self.cluster.topology_version == v0
                    and getattr(self.cluster, "migration", None) is mig):
                return

    # -- export (api.go:500) -----------------------------------------------

    def export_csv(self, index: str, field: str, shard: int) -> str:
        """CSV of row,col (or keys) for one shard (reference exportShard)."""
        self._validate("export-csv")
        idx = self.holder.index_or_raise(index)
        f = idx.field(field)
        if f is None:
            raise FieldNotFoundError()
        frag = self.holder.fragment(index, field, "standard", shard)
        if frag is None:
            raise FragmentNotFoundError()
        # Reverse translation is batched: ONE snapshot pass per store
        # over the shard's distinct ids, then a dict render per bit —
        # the per-bit translate_id loop this replaces took a lock round
        # per cell.
        rows = [(rid, positions) for rid, positions in frag.rows_snapshot()]
        base = shard * SHARD_WIDTH
        row_names: dict[int, str] = {}
        if f.keys:
            rids = [rid for rid, _ in rows]
            row_names = {
                rid: (name if name is not None else str(rid))
                for rid, name in zip(rids,
                                     f.translate_store.translate_ids(rids))}
        col_names: dict[int, str] = {}
        if idx.options.keys:
            cols = sorted({int(pos) + base
                           for _, positions in rows for pos in positions})
            col_names = {
                col: (name if name is not None else str(col))
                for col, name in zip(
                    cols, idx.translate_store.translate_ids(cols))}
        buf = io.StringIO()
        for rid, positions in rows:
            rk = row_names.get(rid) if f.keys else str(rid)
            for pos in positions:
                col = int(pos) + base
                ck = col_names.get(col) if idx.options.keys else str(col)
                buf.write(f"{rk},{ck}\n")
        return buf.getvalue()

    # -- cluster/status (api.go:726-1260) ----------------------------------

    def status(self) -> dict:
        if self.cluster is None:
            return {"state": "NORMAL", "nodes": [], "localID": "standalone"}
        return {
            "state": self.cluster.state,
            "nodes": [n.to_json() for n in self.cluster.nodes],
            "localID": self.cluster.local_id,
        }

    def hosts(self) -> dict:
        if self.cluster is None:
            return {"version": 0, "nodes": [], "state": STATE_NORMAL}
        return {"version": self.cluster.topology_version,
                "nodes": [n.to_json() for n in self.cluster.nodes],
                "state": self.cluster.state}

    def info(self) -> dict:
        import pilosa_tpu
        return {"shardWidth": SHARD_WIDTH,
                "version": pilosa_tpu.__version__}

    def fragment_nodes(self, index: str, shard: int) -> list[dict]:
        """Nodes owning (index, shard) under the current ring —
        reference GET /internal/fragment/nodes (http/handler.go:1290
        handleGetFragmentNodes): clients use it to route direct
        fragment reads/writes."""
        if self.cluster is None:
            if self.local_node is not None:
                # Standalone: every shard routes to THIS node — return
                # its real id/URI so clients can actually dial it
                # (ADVICE r4 #2; the reference returns the actual node).
                return [self.local_node.to_json()]
            return [{"id": "standalone", "uri": {}, "isCoordinator": True}]
        return [n.to_json() for n in self.cluster.shard_nodes(index, shard)]

    def delete_available_shard(self, index: str, field: str,
                               shard: int) -> None:
        """Reference api.DeleteAvailableShard (api.go; DELETE
        /internal/index/{i}/field/{f}/remote-available-shards/{s})."""
        self._validate("delete-available-shard")
        idx = self.holder.index_or_raise(index)
        f = idx.field(field)
        if f is None:
            from pilosa_tpu.errors import FieldNotFoundError
            raise FieldNotFoundError(field)
        f.remove_remote_available_shard(shard)

    def max_shards(self) -> dict:
        return {name: max(self.holder.index(name).available_shards())
                for name in self.holder.index_names()}

    def translate_keys(self, index: str, field: str | None,
                       keys: list[str]) -> list[int]:
        """Public + /internal/translate/keys surface. Routes through the
        cluster translator (coordinator allocates; on the coordinator
        itself this is a local allocation, so the internal RPC
        terminates here — no forwarding loop)."""
        self._validate("translate-keys")
        idx = self.holder.index_or_raise(index)
        f = None
        if field:
            f = idx.field(field)
            if f is None:
                raise FieldNotFoundError()
        return self._xlate_keys(idx, f, keys)

    def translate_entries(self, index: str, field: str | None,
                          after_id: int) -> list[tuple[int, str]]:
        """/internal/translate/entries: the replica entry stream
        (reference translate.go:93 MultiTranslateEntryReader)."""
        idx = self.holder.index_or_raise(index)
        if field:
            f = idx.field(field)
            if f is None:
                raise FieldNotFoundError()
            return f.translate_store.entries_since(after_id)
        return idx.translate_store.entries_since(after_id)

    def recalculate_caches(self) -> None:
        """Row counts are maintained exactly; nothing to rebuild. Kept for
        route parity (api.go RecalculateCaches)."""
        self._validate("recalculate-caches")

    # -- internals ---------------------------------------------------------

    def fragment_blocks(self, index, field, view, shard) -> dict[int, bytes]:
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            raise FragmentNotFoundError()
        return frag.checksum_blocks()

    def fragment_block_data(self, index, field, view, shard, block):
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            raise FragmentNotFoundError()
        return frag.block_data(block)

    def _attr_store(self, index: str, field: str | None):
        """Column attrs (field=None) or a field's row attrs (reference
        api.go:817-918 attr-diff surface)."""
        idx = self.holder.index_or_raise(index)
        if field is None:
            return idx.column_attr_store
        f = idx.field(field)
        if f is None:
            raise FieldNotFoundError()
        return f.row_attr_store

    def attr_blocks(self, index: str, field: str | None) -> list:
        return self._attr_store(index, field).blocks()

    def attr_block_data(self, index: str, field: str | None,
                        block: int) -> dict:
        return self._attr_store(index, field).block_data(block)

    def _broadcast(self, message: dict) -> None:
        if self.cluster is None:
            return
        for node in self.cluster.nodes:
            if node.id == self.cluster.local_id or node.state == "DOWN":
                continue
            try:
                self.cluster.client.send_message(node, message)
            except (ConnectionError, RuntimeError):
                pass
