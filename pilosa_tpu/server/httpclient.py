"""InternalClient over HTTP — the real-cluster transport.

Reference: http/client.go:37 (queries via POST /index/{i}/query with
remote=true, fragment sync via /internal/fragment/*, messages via
/internal/cluster/message). JSON bodies; stdlib http.client, no
dependencies.

Connections are persistent (HTTP/1.1 keep-alive) and pooled per
(scheme, host, port): the per-request TCP handshake + slow-start was a
fixed tax on every cluster leg (the reference uses Go's pooling
http.Transport for the same reason). The pool is shared across threads
behind one short-critical-section lock so the failure detector can
invalidate a peer's idle sockets for EVERY thread; a reused socket that
the peer closed while idle gets ONE transparent retry on a fresh
connection — only when the failure proves the request never reached
application code.

Liveness probes never ride the pool: a probe must test the peer's
ability to ACCEPT connections, and a cached socket only proves the
socket itself still works. A peer whose listener died (crash, restart,
failover to a new process on the same address) can keep old sockets
half-alive long after it stopped being the node at that address — so a
failed probe also bumps the peer's pool epoch, closing its idle
connections and preventing in-flight ones from being returned.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import socket
import threading
import time
import urllib.parse
from typing import Any

import numpy as np

from pilosa_tpu.cluster.node import Node
from pilosa_tpu.obs import profile as _profile
from pilosa_tpu.qos.deadline import DeadlineExceededError
from pilosa_tpu.qos.deadline import inject_http_headers as _inject_deadline
from pilosa_tpu.qos.deadline import current_deadline as _current_deadline


class NodeHTTPError(RuntimeError):
    """A live peer rejected the request (HTTP status attached). Stays a
    RuntimeError so existing 'alive but refused' handling keeps working;
    failover paths must keep catching ConnectionError only.

    ``retry_after`` carries the peer's Retry-After hint (seconds) when
    it shed the request (QoS 503); None otherwise."""

    def __init__(self, code: int, message: str,
                 retry_after: float | None = None):
        super().__init__(message)
        self.code = code
        self.retry_after = retry_after


#: bounded exponential backoff for idempotent requests a peer shed
#: (503). Full jitter (AWS-style) so a synchronized burst of retries
#: doesn't re-overload the node that just told everyone to back off.
RETRY_503_ATTEMPTS = 3
RETRY_BASE_DELAY = 0.1
RETRY_MAX_DELAY = 5.0

def _epoch_vector(raw: dict | None) -> dict:
    """Normalize a wire shardEpochs payload (JSON string keys) back to
    the {int shard: int epoch} shape RemoteEpochTable.observe expects."""
    if not raw:
        return {}
    return {int(s): int(e) for s, e in raw.items()}


#: connection failures that, on a REUSED socket, mean the peer closed it
#: while idle — the request never reached application code, so one
#: transparent retry on a fresh connection is safe for any method.
_STALE_CONN_ERRORS = (http.client.RemoteDisconnected,
                      http.client.BadStatusLine,
                      http.client.CannotSendRequest,
                      ConnectionResetError,
                      BrokenPipeError)


class _RewindableChunks:
    """Iterable-only body: http.client sees no length and sends chunked
    transfer-encoding. Unlike a generator, iteration restarts from the
    top, so _http's one stale-connection retry re-sends the whole
    stream instead of a truncated tail."""

    def __init__(self, chunks: list[bytes]):
        self._chunks = chunks

    def __iter__(self):
        return iter(self._chunks)


class _ConnPool:
    """Shared keep-alive pool: {(scheme, host, port): idle connections}.

    A checked-out connection is owned exclusively by the borrowing
    thread (http.client serializes one request at a time), so the lock
    only guards the idle lists — a dict pop/append, nanoseconds next to
    a network round-trip.

    Each peer key carries an *epoch*. ``invalidate`` bumps it and closes
    the idle connections; a connection checked out under an older epoch
    is closed instead of returned, so a socket that was mid-request to a
    dead listener can never re-enter the pool.
    """

    #: idle connections kept per peer — enough for the handful of
    #: threads (executor legs, syncer, prober) that talk to one peer
    #: concurrently without hoarding sockets.
    MAX_IDLE_PER_PEER = 8

    def __init__(self):
        self._lock = threading.Lock()
        self._idle: dict[tuple, list[http.client.HTTPConnection]] = {}
        self._epoch: dict[tuple, int] = {}

    def get(self, key: tuple):
        """-> (idle connection or None, current epoch for the key)."""
        with self._lock:
            epoch = self._epoch.get(key, 0)
            conns = self._idle.get(key)
            return (conns.pop() if conns else None), epoch

    def put(self, key: tuple, conn, epoch: int) -> None:
        with self._lock:
            if epoch == self._epoch.get(key, 0):
                lst = self._idle.setdefault(key, [])
                if len(lst) < self.MAX_IDLE_PER_PEER:
                    lst.append(conn)
                    return
        # Epoch advanced while this connection was in flight (the peer
        # failed a liveness probe), or the peer's idle list is full.
        try:
            conn.close()
        except Exception:
            pass

    def invalidate(self, key: tuple) -> None:
        with self._lock:
            self._epoch[key] = self._epoch.get(key, 0) + 1
            conns = self._idle.pop(key, [])
        for conn in conns:
            try:
                conn.close()
            except Exception:
                pass

    def close_all(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, {}
            for key in idle:
                self._epoch[key] = self._epoch.get(key, 0) + 1
        for conns in idle.values():
            for conn in conns:
                try:
                    conn.close()
                except Exception:
                    pass


def _split_url(url: str) -> tuple[str, str, int, str]:
    parts = urllib.parse.urlsplit(url)
    scheme = parts.scheme or "http"
    port = parts.port or (443 if scheme == "https" else 80)
    path = parts.path or "/"
    if parts.query:
        path += "?" + parts.query
    return scheme, parts.hostname or "", port, path


class _MuxLeg:
    """One outbound query leg riding a multiplexed peer channel."""

    __slots__ = ("index", "query", "shards", "timeout_ms", "trace",
                 "profile", "done", "frame", "error", "bytes_out")

    def __init__(self, index: str, query: str, shards, timeout_ms,
                 trace: str | None, profile: bool = False):
        self.index = index
        self.query = query
        self.shards = shards
        self.timeout_ms = timeout_ms
        self.trace = trace
        self.profile = profile
        self.done = False
        self.frame: bytes | None = None
        self.error: BaseException | None = None
        self.bytes_out = len(query)

    def to_json(self) -> dict:
        d: dict = {"index": self.index, "query": self.query}
        if self.shards:
            d["shards"] = list(self.shards)
        if self.timeout_ms is not None:
            d["timeoutMs"] = self.timeout_ms
        if self.trace:
            d["trace"] = self.trace
        if self.profile:
            d["profile"] = True
        return d


class _MuxUnsupportedError(Exception):
    """Sentinel: the peer doesn't speak the mux envelope (old version);
    the submitting leg falls back to a per-query request."""


class _PeerChannel:
    """Per-peer request multiplexer (group commit).

    The first leg to a free channel dispatches immediately — batching
    adds ZERO latency to an idle peer. Legs arriving while a batch is
    in flight queue up; when the wire frees, one of their threads
    drains the whole queue as the next batch. Under concurrent load
    the coordinator therefore sends one pipelined request per peer per
    congestion window instead of one per query.

    Every leg keeps its own deadline, trace id, epoch stamp, and error
    status (the envelope carries them per leg); only transport-level
    outcomes — connection failure, breaker state — are shared, exactly
    as they would be on one physical connection.
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._queue: list[_MuxLeg] = []
        self._busy = False

    def submit(self, client: "HTTPInternalClient", node: Node,
               leg: _MuxLeg) -> _MuxLeg:
        """Blocks until the leg is resolved (frame or error set)."""
        with self._cv:
            self._queue.append(leg)
            batch = None
            while not leg.done:
                if not self._busy:
                    # Become the dispatcher for everything queued
                    # (including our own leg — nobody drained it yet).
                    batch, self._queue = self._queue, []
                    self._busy = True
                    break
                self._cv.wait(timeout=0.1)
                if leg.done:
                    break
                # A queued (not yet in-flight) leg whose deadline died
                # while another batch holds the wire gives up its slot;
                # an in-flight leg must wait for its outcome.
                dl = _current_deadline()
                rem = dl.remaining() if dl is not None else None
                if (rem is not None and rem <= 0) and leg in self._queue:
                    self._queue.remove(leg)
                    leg.error = DeadlineExceededError(
                        "deadline expired before remote call")
                    leg.done = True
        if batch is not None:
            try:
                client._send_mux_batch(node, batch)
            finally:
                with self._cv:
                    for b in batch:
                        b.done = True
                    self._busy = False
                    self._cv.notify_all()
        return leg


class HTTPInternalClient:
    """Implements the InternalClient protocol against peer HTTP servers."""

    def __init__(self, timeout: float = 30.0, ca_cert: str | None = None,
                 skip_verify: bool | None = None):
        self._ssl_ctx = None
        self._pool = _ConnPool()
        self.timeout = timeout
        self.ca_cert = ca_cert
        #: Optional BreakerRegistry (cluster.breaker). When set, every
        #: request consults the peer's breaker first — an open breaker
        #: fast-fails with BreakerOpenError (a ConnectionError) so the
        #: executor's replica failover kicks in without burning a
        #: socket timeout on a known-sick peer.
        self.breakers = None
        #: Optional StatsClient: wire-level counters (cluster.wireBytesIn/
        #: wireBytesOut/wireDecodeMs) land on /debug/vars when set.
        self.stats = None
        #: Coalesce concurrent outbound query legs to the same peer into
        #: one multiplexed request (POST /internal/query-mux). Peers that
        #: 404/400 the envelope (older version) are remembered and get
        #: per-query requests instead — see _mux_allowed.
        self.multiplex = True
        self._channels: dict[str, _PeerChannel] = {}
        self._channels_lock = threading.Lock()
        self._mux_unsupported: set[str] = set()
        #: Peers that rejected the PTS1 import stream (older version);
        #: they get per-batch /internal/import requests instead.
        self._stream_unsupported: set[str] = set()
        #: Optional PartitionFaults (cluster.faults): chaos-injected
        #: outbound link cuts, consulted before any wire traffic so a
        #: drill's "partition" behaves like the network it models —
        #: drop fails the dial, timeout burns the delay first.
        self.faults = None
        self._leg_local = threading.local()
        # Verification policy (reference tls.skip-verify,
        # server/config.go): with a CA bundle, verify by default; the
        # CERT_NONE fallback is only for CA-less (self-signed) clusters
        # or an explicit skip_verify=True.
        self.skip_verify = (skip_verify if skip_verify is not None
                            else ca_cert is None)
        if ca_cert is not None:
            # Fail fast at startup: a typo'd CA path raising lazily on
            # the first HTTPS request would kill background threads
            # (join/announce, anti-entropy) with an uncaught error.
            import ssl
            ssl.create_default_context(cafile=ca_cert)

    def _url(self, node: Node, path: str) -> str:
        return f"{node.uri}{path}"

    def _check_fault(self, node: Node) -> None:
        """Injected-partition gate: raise ConnectionError (feeding the
        breaker, like any real connection failure) when the link to
        this peer is faulted."""
        if self.faults is None:
            return
        try:
            self.faults.check(node.id)
        except ConnectionError:
            if self.breakers is not None:
                self.breakers.record_failure(node.id)
            raise

    def _ctx(self, url: str):
        """SSL context for https peers. Plain http gets None."""
        if not url.startswith("https:"):
            return None
        ctx = self._ssl_ctx
        if ctx is None:
            import ssl
            ctx = ssl.create_default_context(cafile=self.ca_cert)
            if self.skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl_ctx = ctx
        return ctx

    def _deadline_timeout(self) -> float:
        """Per-request socket timeout capped to the active deadline's
        remaining budget; raises instead of sending a request that
        cannot finish in time."""
        dl = _current_deadline()
        if dl is None:
            return self.timeout
        rem = dl.remaining()
        if rem is None:
            dl.check()  # cancel-only token
            return self.timeout
        if rem <= 0 or dl.cancelled:
            raise DeadlineExceededError("deadline expired before remote call")
        return max(0.05, min(self.timeout, rem))

    def _http(self, url: str, method: str = "GET",
              body: bytes | None = None, headers: dict | None = None,
              timeout: float | None = None):
        """One request over a pooled keep-alive connection.

        Returns (status, response-headers Message, body bytes). Raises
        the OSError family on connection problems (socket timeouts
        included) — callers map those to ConnectionError with the peer
        id attached. A reused socket the peer closed while idle gets one
        transparent fresh-connection retry; a fresh connection's failure
        is real and propagates.
        """
        scheme, host, port, path = _split_url(url)
        key = (scheme, host, port)
        if timeout is None:
            timeout = self.timeout
        while True:
            conn, epoch = self._pool.get(key)
            reused = conn is not None
            if conn is None:
                if scheme == "https":
                    conn = http.client.HTTPSConnection(
                        host, port, timeout=timeout, context=self._ctx(url))
                else:
                    conn = http.client.HTTPConnection(host, port,
                                                      timeout=timeout)
            else:
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
            try:
                conn.request(method, path, body=body, headers=headers or {})
                resp = conn.getresponse()
                data = resp.read()
            except _STALE_CONN_ERRORS:
                conn.close()
                if reused:
                    continue  # idle socket died under us; retry fresh
                raise
            except BaseException:
                # Timeouts and dial failures are real; so is any error
                # mid-response. Never return a half-used connection to
                # the pool.
                conn.close()
                raise
            if resp.will_close:
                conn.close()
            else:
                try:
                    # Cluster legs are latency-bound small messages:
                    # never let Nagle hold a reply back (~40 ms).
                    conn.sock.setsockopt(socket.IPPROTO_TCP,
                                         socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                self._pool.put(key, conn, epoch)
            return resp.status, resp.msg, data

    def close(self) -> None:
        """Close every pooled idle connection; in-flight checkouts are
        closed on return (their epoch is stale)."""
        self._pool.close_all()

    def _request_raw(self, node: Node, method: str, path: str,
                     body: bytes | None = None,
                     accept: str | None = None,
                     content_type: str = "application/json",
                     retry_503: bool = False) -> tuple[bytes, str]:
        """Returns (body, content-type).

        ``retry_503=True`` (idempotent requests only): when the peer
        sheds with 503, retry up to RETRY_503_ATTEMPTS times with
        bounded exponential backoff + full jitter, honoring the peer's
        Retry-After hint as the floor — and never sleeping past the
        active deadline.
        """
        if self.breakers is not None:
            self.breakers.check(node.id)
        self._check_fault(node)
        attempt = 0
        try:
            while True:
                headers: dict = {}
                if body is not None:
                    headers["Content-Type"] = content_type
                if accept is not None:
                    headers["Accept"] = accept
                from pilosa_tpu.obs.tracing import inject_http_headers
                inject_http_headers(headers)
                _inject_deadline(headers)
                try:
                    status, msg, data = self._http(
                        self._url(node, path), method, body, headers,
                        timeout=self._deadline_timeout())
                except OSError as e:
                    # Connection failures AND deadline overruns (socket
                    # timeout surfaces as OSError) both feed the breaker:
                    # a peer too slow to answer within budget is as
                    # useless as one that refuses the dial.
                    if self.breakers is not None:
                        self.breakers.record_failure(node.id)
                    raise ConnectionError(
                        f"node {node.id} unreachable: {e}") from e
                if status < 400:
                    if self.breakers is not None:
                        self.breakers.record_success(node.id)
                    return data, msg.get("Content-Type", "") or ""
                # The peer is alive but rejected the request —
                # application error, NOT a connection failure
                # (failover must not trigger, and the breaker must
                # not feed: a shedding peer is healthy, just busy).
                if self.breakers is not None:
                    self.breakers.record_success(node.id)
                detail = data.decode(errors="replace")
                if status == 404:
                    raise LookupError(f"{node.id}: {detail}")
                retry_after = None
                if status == 503:
                    try:
                        retry_after = float(msg.get("Retry-After"))
                    except (TypeError, ValueError):
                        retry_after = None
                    if retry_503 and attempt < RETRY_503_ATTEMPTS:
                        delay = self._backoff_delay(attempt, retry_after)
                        if delay is not None:
                            time.sleep(delay)
                            attempt += 1
                            continue
                raise NodeHTTPError(
                    status, f"node {node.id} HTTP {status}: {detail}",
                    retry_after=retry_after)
        except (ConnectionError, NodeHTTPError, LookupError):
            raise  # breaker outcome already recorded above
        except BaseException:
            # Escaped before any outcome was recorded — e.g. the active
            # deadline expired before dialing (DeadlineExceededError
            # from _deadline_timeout). That proves nothing about the
            # peer, so release a claimed half-open probe instead of
            # leaving it wedged (a stuck lease would fast-fail the peer
            # until process restart).
            if self.breakers is not None:
                self.breakers.abort(node.id)
            raise

    @staticmethod
    def _backoff_delay(attempt: int, retry_after: float | None) -> float | None:
        """Jittered, bounded delay before re-sending a shed request, or
        None when the active deadline can't afford the wait (give the
        remaining budget back to the caller's failover logic instead of
        sleeping it away)."""
        cap = min(RETRY_MAX_DELAY, RETRY_BASE_DELAY * (2 ** attempt))
        delay = random.uniform(0, cap)
        if retry_after is not None:
            # The shedding node knows its queue better than our curve
            # does; keep jitter on top so retries don't synchronize.
            delay = retry_after + random.uniform(0, cap)
        dl = _current_deadline()
        if dl is not None:
            rem = dl.remaining()
            if rem is not None and rem <= delay:
                return None
        return delay

    def _request(self, node: Node, method: str, path: str,
                 body: bytes | None = None,
                 content_type: str = "application/json",
                 retry_503: bool | None = None) -> Any:
        # GETs are idempotent by contract and always retry a shed;
        # POST callers must opt in explicitly (reads like /query and
        # key translation are safe, imports and messages are not).
        if retry_503 is None:
            retry_503 = method == "GET"
        data, _ = self._request_raw(node, method, path, body,
                                    content_type=content_type,
                                    retry_503=retry_503)
        return json.loads(data) if data else {}

    def _post_import(self, node: Node, req: dict,
                     json_only: bool = False) -> None:
        """POST /internal/import, binary frames first (wire
        .encode_import: raw arrays, ~µs to produce vs a Python json
        walk of millions of ints), falling back to the JSON body once
        if the peer rejects the frame — a not-yet-upgraded node in a
        mixed-version cluster 400s on the magic, and a replicated
        write must not be lost to a rolling upgrade (imports are
        idempotent, so the retry is safe)."""
        if not json_only:
            from pilosa_tpu.server import wire
            try:
                self._request(node, "POST", "/internal/import",
                              wire.encode_import(req),
                              content_type="application/octet-stream")
                return
            except NodeHTTPError as e:
                # Only a 400 can mean "peer doesn't speak the frame
                # format" (an old node's JSON parse fails before any
                # application logic). A 5xx may have PARTIALLY applied —
                # re-sending silently would double-apply clears — and
                # carries no hope that a different encoding succeeds.
                if e.code != 400:
                    raise
        body = dict(req)
        for k in ("rowIDs", "columnIDs", "values"):
            if body.get(k) is not None:
                body[k] = np.asarray(body[k]).tolist()
        self._request(node, "POST", "/internal/import",
                      json.dumps(body).encode())

    # -- multiplexed peer channel --------------------------------------------

    def leg_wire_bytes(self) -> dict | None:
        """Wire bytes of the LAST query leg this thread sent — read by
        the coordinator's per-leg tracing span right after the call."""
        return getattr(self._leg_local, "bytes", None)

    def leg_remote_profile(self) -> dict | None:
        """The remote node's own QueryProfile for the LAST leg this
        thread sent (carried in the frames header when the coordinator
        asked for profiling), or None. Read by map_reduce's per-leg
        profile recorder right after the call returns — remote calls
        are synchronous on the pool thread, so the thread-local stash
        always belongs to the leg just completed."""
        return getattr(self._leg_local, "remote_profile", None)

    def _count_wire(self, n_out: int, n_in: int, decode_ms: float = 0.0):
        st = self.stats
        if st is not None:
            st.count("cluster.wireBytesOut", n_out)
            st.count("cluster.wireBytesIn", n_in)
            if decode_ms:
                st.count("cluster.wireDecodeMs", decode_ms)

    def _mux_allowed(self, node: Node) -> bool:
        env = os.environ.get("PILOSA_TPU_MULTIPLEX", "").strip().lower()
        if env in ("off", "0", "false"):
            return False
        if env in ("on", "1", "true"):
            return node.id not in self._mux_unsupported
        return self.multiplex and node.id not in self._mux_unsupported

    def _channel(self, node: Node) -> _PeerChannel:
        with self._channels_lock:
            ch = self._channels.get(node.id)
            if ch is None:
                ch = self._channels[node.id] = _PeerChannel()
            return ch

    def _send_mux_batch(self, node: Node, batch: list[_MuxLeg]) -> None:
        """Dispatch one multiplexed request carrying every queued leg.

        Runs on ONE submitter thread (the channel's current dispatcher);
        resolves every leg with a frame or an error and never raises —
        a transport failure is every leg's failure, exactly as if each
        had dialed and hit the same dead peer. Per-leg application
        outcomes (503 shed, 404, quarantine) come back inside the
        envelope and are mapped by each leg's own submitter.
        """
        from pilosa_tpu.server import wire
        try:
            if self.breakers is not None:
                self.breakers.check(node.id)
            try:
                self._check_fault(node)
            except ConnectionError as err:
                for leg in batch:
                    leg.error = err
                return
            body = wire.encode_mux_request([leg.to_json() for leg in batch])
            # The envelope waits for its slowest leg: socket timeout is
            # the largest per-leg budget (deadline-capped by callers).
            budget = max((leg.timeout_ms or int(self.timeout * 1000))
                         for leg in batch) / 1000.0
            try:
                status, msg, data = self._http(
                    self._url(node, "/internal/query-mux"), "POST", body,
                    {"Content-Type": wire.MUX_CONTENT_TYPE},
                    timeout=max(0.05, min(self.timeout, budget)))
            except OSError as e:
                if self.breakers is not None:
                    self.breakers.record_failure(node.id)
                err = ConnectionError(f"node {node.id} unreachable: {e}")
                err.__cause__ = e
                for leg in batch:
                    leg.error = err
                return
            if self.breakers is not None:
                # Any HTTP status proves the peer is alive (same rule as
                # _request_raw) — shedding and rejections are app-level.
                self.breakers.record_success(node.id)
            self._count_wire(len(body), len(data))
            if status in (400, 404, 405):
                # The peer predates the mux envelope (no route, or its
                # parser rejects the magic). Remember and fall back to
                # per-query requests — mixed-version clusters must keep
                # answering (same contract as _post_import's 400 rule).
                self._mux_unsupported.add(node.id)
                for leg in batch:
                    leg.error = _MuxUnsupportedError()
                return
            if status >= 400:
                err = NodeHTTPError(
                    status,
                    f"node {node.id} HTTP {status}: "
                    f"{data.decode(errors='replace')}")
                for leg in batch:
                    leg.error = err
                return
            outcomes = wire.decode_mux_response(data)
            if len(outcomes) != len(batch):
                raise ValueError(
                    f"mux response has {len(outcomes)} legs, sent "
                    f"{len(batch)}")
            for leg, o in zip(batch, outcomes):
                if "frame" in o:
                    leg.frame = o["frame"]
                else:
                    leg.error = NodeHTTPError(
                        o["status"],
                        f"node {node.id} HTTP {o['status']}: {o['error']}",
                        retry_after=o.get("retryAfter"))
        except BaseException as e:  # noqa: BLE001 — every leg must resolve
            if self.breakers is not None:
                self.breakers.abort(node.id)
            for leg in batch:
                if leg.frame is None and leg.error is None:
                    leg.error = e

    def _mux_query(self, node: Node, index: str, query: str,
                   shards: list[int] | None):
        """One query leg over the peer's multiplexed channel. Same
        outcome mapping as the per-query path: quarantine -> typed
        ShardCorruptError, shed (503) -> bounded jittered retry, 404 ->
        LookupError. Raises _MuxUnsupportedError for old peers (caller
        falls back per-query)."""
        from pilosa_tpu.obs import tracing
        from pilosa_tpu.server import wire
        want_profile = _profile.current() is not None
        attempt = 0
        while True:
            # Deadline-capped per-leg budget; raises if already expired.
            timeout_ms = int(self._deadline_timeout() * 1000)
            leg = _MuxLeg(index, query, shards, timeout_ms,
                          tracing.current_trace_id(),
                          profile=want_profile)
            self._channel(node).submit(self, node, leg)
            if leg.error is not None:
                e = leg.error
                if isinstance(e, NodeHTTPError) and e.code == 503:
                    if "quarantined" in str(e):
                        from pilosa_tpu.storage.quarantine import (
                            ShardCorruptError,
                        )
                        raise ShardCorruptError() from e
                    if attempt < RETRY_503_ATTEMPTS:
                        delay = self._backoff_delay(attempt, e.retry_after)
                        if delay is not None:
                            time.sleep(delay)
                            attempt += 1
                            continue
                if isinstance(e, NodeHTTPError) and e.code == 404:
                    raise LookupError(f"node {node.id}: {e}") from e
                raise e
            frame = leg.frame
            t0 = time.perf_counter()
            results, header = wire.decode_frames_meta(frame)
            decode_ms = (time.perf_counter() - t0) * 1000.0
            st = self.stats
            if st is not None:
                st.count("cluster.wireDecodeMs", decode_ms)
            self._leg_local.bytes = {"out": leg.bytes_out,
                                     "in": len(frame),
                                     "decodeMs": decode_ms}
            self._leg_local.remote_profile = header.get("profile")
            return results, _epoch_vector(header.get("shardEpochs"))

    # -- InternalClient protocol -------------------------------------------

    def query_node(self, node: Node, index: str, query: str,
                   shards: list[int] | None, remote: bool = True):
        return self.query_node_meta(node, index, query, shards, remote)[0]

    def query_node_meta(self, node: Node, index: str, query: str,
                        shards: list[int] | None, remote: bool = True):
        """(results, shard-epoch vector): the peer stamps its response
        with the epochs it read before executing (api.py query), which
        feed the coordinator's RemoteEpochTable for cache stamps. Peers
        predating the stamp report {} — the cache just misses."""
        path = f"/index/{index}/query?remote={'true' if remote else 'false'}"
        if shards:
            path += "&shards=" + ",".join(str(s) for s in shards)
        if _profile.current() is not None:
            # The coordinator is profiling: ask the peer to send its own
            # ledger back in the frames header (nested per-leg timeline).
            path += "&profile=true"
        from pilosa_tpu.server import wire
        if remote:
            if self._mux_allowed(node):
                try:
                    return self._mux_query(node, index, query, shards)
                except _MuxUnsupportedError:
                    pass  # old peer; the per-query path below still works
            return self._query_node_frames(node, path, query)
        # Forwarded reads are idempotent POSTs: a shed leg may back off
        # and retry within the deadline budget, same as the remote path.
        resp = self._request(node, "POST", path, query.encode(),
                             retry_503=True)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["results"], _epoch_vector(resp.get("shardEpochs"))

    def _query_node_frames(self, node: Node, path: str, query: str):
        """Per-query remote leg. Advertises binary-frame support (v2:
        aggregate results ship as typed array blobs too — TopN pairs,
        GroupBy tables, rowid lists; wire.encode_frames): Row results
        come back as roaring blobs instead of JSON int lists (~10-100x
        smaller for large rows). Reads are idempotent, so a shed (503)
        leg may back off and retry."""
        from pilosa_tpu.server import wire
        body = query.encode()
        try:
            data, ctype = self._request_raw(
                node, "POST", path, body,
                accept=wire.FRAMES_ACCEPT_V2, retry_503=True)
        except NodeHTTPError as e:
            if e.code == 503 and "quarantined" in str(e):
                # The peer refused because ITS copy of a shard is
                # corrupt: surface the typed error so the
                # coordinator fails this leg over to a replica.
                from pilosa_tpu.storage.quarantine import ShardCorruptError
                raise ShardCorruptError() from e
            raise
        self._leg_local.bytes = {"out": len(body), "in": len(data)}
        self._leg_local.remote_profile = None
        if ctype.startswith(wire.FRAMES_CONTENT_TYPE):
            t0 = time.perf_counter()
            results, header = wire.decode_frames_meta(data)
            decode_ms = (time.perf_counter() - t0) * 1000.0
            self._count_wire(len(body), len(data), decode_ms)
            self._leg_local.bytes["decodeMs"] = decode_ms
            self._leg_local.remote_profile = header.get("profile")
            return results, _epoch_vector(header.get("shardEpochs"))
        self._count_wire(len(body), len(data))
        resp = json.loads(data) if data else {}
        if "error" in resp:
            raise RuntimeError(resp["error"])
        self._leg_local.remote_profile = resp.get("profile")
        return ([wire.decode_result(r) for r in resp["results"]],
                _epoch_vector(resp.get("shardEpochs")))

    def fragment_blocks(self, node, index, field, view, shard):
        resp = self._request(
            node, "GET",
            f"/internal/fragment/blocks?index={index}&field={field}"
            f"&view={view}&shard={shard}")
        return {b["id"]: bytes.fromhex(b["checksum"])
                for b in resp.get("blocks", [])}

    def fragment_block_data(self, node, index, field, view, shard, block):
        resp = self._request(
            node, "GET",
            f"/internal/fragment/block/data?index={index}&field={field}"
            f"&view={view}&shard={shard}&block={block}")
        return (np.asarray(resp["rowIDs"], dtype=np.uint64),
                np.asarray(resp["columnIDs"], dtype=np.uint64))

    def import_bits(self, node, index, field, view, shard, rows, cols,
                    clear=False):
        self._post_import(node, {
            "kind": "fragment", "index": index, "field": field,
            "view": view, "shard": shard, "rowIDs": rows,
            "columnIDs": cols, "clear": clear,
        })

    def send_import(self, node, index, field, shard, rows=None, cols=None,
                    values=None, timestamps=None, clear=False):
        req = {"kind": "field", "index": index, "field": field,
               "shard": shard, "rowIDs": rows,
               "columnIDs": cols if cols is not None else [],
               "values": values, "clear": clear}
        if timestamps is not None:
            # Epoch-second ints (or None per element); the wire encoder
            # packs them as a u64 blob with a sentinel for None. An old
            # peer's binary decoder hands the raw array to its timestamp
            # parser, which rejects it (400) before any mutation — the
            # JSON fallback below then carries the ints, which every
            # version's parse_time accepts.
            req["timestamps"] = timestamps
        self._post_import(node, req)

    def send_import_stream(self, node, reqs, chunked: bool = False,
                           qos_class: str | None = None) -> int:
        """POST many shard-batch import requests as ONE pipelined PTS1
        stream (/internal/import-stream): the peer decodes, WAL-appends,
        and device-uploads chunk k while chunk k+1 is still on the wire,
        so the per-request round-trip stops gating bulk ingest.

        Backpressure contract: a 429 reply carries ``{"applied": k}``
        (the server applied a strict prefix and drained the rest) plus
        Retry-After — sleep, then resume from chunk k. Peers that
        400/404/405 the stream (older version: no route, or the parser
        rejects the magic) are remembered and replayed per-batch through
        _post_import — nothing was applied, and imports are idempotent,
        so the replay is safe (same contract as the mux envelope).

        ``chunked=True`` sends chunked transfer-encoding instead of one
        Content-Length body; the server pipelines either way (it reads
        length-prefixed frames incrementally off the socket).

        Returns the number of requests applied (== len(reqs)).
        """
        from pilosa_tpu.server import wire
        reqs = list(reqs)
        if not reqs:
            return 0
        if node.id in self._stream_unsupported:
            for r in reqs:
                self._post_import(node, r)
            return len(reqs)
        start = 0
        stalls = 0
        while start < len(reqs):
            chunks = ([wire.stream_preamble()]
                      + [wire.stream_chunk(r) for r in reqs[start:]]
                      + [wire.stream_end()])
            body = _RewindableChunks(chunks) if chunked else b"".join(chunks)
            if self.breakers is not None:
                self.breakers.check(node.id)
            self._check_fault(node)
            hdrs = {"Content-Type": wire.STREAM_CONTENT_TYPE}
            if qos_class:
                hdrs["X-Qos-Class"] = qos_class
            try:
                status, msg, data = self._http(
                    self._url(node, "/internal/import-stream"), "POST",
                    body, hdrs)
            except OSError as e:
                if self.breakers is not None:
                    self.breakers.record_failure(node.id)
                raise ConnectionError(
                    f"node {node.id} unreachable: {e}") from e
            if self.breakers is not None:
                self.breakers.record_success(node.id)
            self._count_wire(sum(len(c) for c in chunks), len(data))
            if status < 400:
                return len(reqs)
            if status in (400, 404, 405):
                # "applied" in the body means the ROUTE answered: a new
                # server reporting a chunk that failed to apply — not an
                # old peer missing the route. Surface it; only a bare
                # rejection triggers the per-batch fallback.
                try:
                    payload = json.loads(data)
                except (ValueError, TypeError):
                    payload = {}
                if isinstance(payload, dict) and "applied" in payload:
                    raise NodeHTTPError(
                        status, f"node {node.id} HTTP {status}: "
                                f"{data.decode(errors='replace')}")
                self._stream_unsupported.add(node.id)
                for r in reqs[start:]:
                    self._post_import(node, r)
                return len(reqs)
            if status == 429:
                applied = 0
                try:
                    applied = int(json.loads(data).get("applied", 0))
                except (ValueError, TypeError, AttributeError):
                    pass
                start += applied
                # A saturated-but-draining gate makes progress between
                # rounds; zero progress several rounds running means the
                # pipeline is wedged on something else — surface it.
                stalls = 0 if applied else stalls + 1
                if stalls > RETRY_503_ATTEMPTS:
                    raise NodeHTTPError(
                        status,
                        f"node {node.id} ingest backpressure made no "
                        f"progress after {stalls} retries",
                        retry_after=None)
                try:
                    delay = float(msg.get("Retry-After"))
                except (TypeError, ValueError):
                    delay = 1.0
                time.sleep(min(max(delay, 0.0), RETRY_MAX_DELAY))
                continue
            raise NodeHTTPError(
                status, f"node {node.id} HTTP {status}: "
                        f"{data.decode(errors='replace')}")
        return len(reqs)

    def send_message(self, node: Node, message: dict):
        self._request(node, "POST", "/internal/cluster/message",
                      json.dumps(message).encode())

    def send_import_roaring(self, node, index, field, shard, data: bytes,
                            clear=False):
        path = (f"/index/{index}/field/{field}/import-roaring/{shard}"
                f"?remote=true" + ("&clear=true" if clear else ""))
        self._request(node, "POST", path, data)

    # Fragment movement now rides the PTS1 import stream
    # (send_import_stream with qos_class="internal") — the old
    # /internal/fragment/data pull path (fetch_fragment /
    # fetch_fragment_chunks) is gone.

    #: liveness probes use their own short timeout — the general 30s
    #: request timeout would make a blackholed peer stall every
    #: failure-detector sweep for minutes (memberlist probes are
    #: sub-second; confirmNodeDown cluster.go:1724 retries fast).
    PROBE_TIMEOUT = 2.0

    def probe(self, node) -> None:
        """Liveness probe on a FRESH connection, never a pooled one.

        A pooled socket only proves that one socket still works — the
        probe's job is to prove the peer still *accepts* connections. A
        crashed-or-restarted listener can leave old keep-alive sockets
        talking to a stale process on the same address; on probe failure
        the peer's pooled connections are invalidated so data legs can't
        keep riding them either.
        """
        self._check_fault(node)
        url = self._url(node, "/version")
        scheme, host, port, path = _split_url(url)
        timeout = min(self.PROBE_TIMEOUT, self.timeout)
        if scheme == "https":
            conn = http.client.HTTPSConnection(
                host, port, timeout=timeout, context=self._ctx(url))
        else:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            # Any HTTP status counts: alive but unhappy is still alive.
            conn.request("GET", path)
            resp = conn.getresponse()
            resp.read()
        except OSError as e:
            self._pool.invalidate((scheme, host, port))
            raise ConnectionError(f"node {node.id} unreachable: {e}") from e
        finally:
            conn.close()

    def indirect_probe(self, via, target) -> bool:
        """Ask ``via`` to probe ``target`` on our behalf (memberlist's
        indirect ping, gossip/gossip.go:43-443): distinguishes "target
        is dead" from "the link between US and target is down".  True
        iff the intermediary reached the target."""
        try:
            self._check_fault(via)
        except ConnectionError:
            return False  # can't even reach the intermediary
        q = urllib.parse.urlencode({"scheme": target.uri.scheme,
                                    "host": target.uri.host,
                                    "port": target.uri.port})
        url = self._url(via, f"/internal/probe?{q}")
        try:
            status, _, data = self._http(
                url, timeout=min(2 * self.PROBE_TIMEOUT, self.timeout))
            if status >= 400:
                return False
            return bool(json.loads(data or b"{}").get("ok"))
        except (OSError, ValueError):
            return False

    def translate_keys(self, node, index, field, keys):
        # Key translation creates-or-returns the same ids on every
        # call: idempotent, so a shed may back off and retry.
        body = json.dumps({"index": index, "field": field,
                           "keys": list(keys)}).encode()
        resp = self._request(node, "POST", "/internal/translate/keys", body,
                             retry_503=True)
        return resp["ids"]

    def translate_entries(self, node, index, field, after_id):
        path = (f"/internal/translate/entries?index={index}"
                f"&after={int(after_id)}")
        if field:
            path += f"&field={field}"
        resp = self._request(node, "GET", path)
        return [(int(i), k) for i, k in resp["entries"]]

    def nodes(self, node) -> dict:
        """Peer membership pull: {"version", "nodes"} (transitive
        discovery — the memberlist LocalState/MergeRemoteState analog,
        gossip/gossip.go:295-443)."""
        return self._request(node, "GET", "/internal/nodes")

    def availability(self, node) -> dict:
        """Peer per-field shard availability ({index: {field: [shards]}}
        — the additive NodeStatus half, server.go:640)."""
        return self._request(node, "GET", "/internal/availability")

    def debug_query_profile(self, node, trace: str) -> dict | None:
        """One peer's retained profile for ``trace``, or None when that
        peer's ring doesn't have it. ``local=true`` stops the peer from
        fanning out in turn (resolution is one hop, never a cycle)."""
        try:
            return self._request(
                node, "GET", f"/debug/queries/{trace}?local=true")
        except LookupError:
            return None

    def post_schema(self, node, schema: list[dict]) -> None:
        """Push a schema to one peer (reference PostSchema fan-out from
        API.ApplySchema, api.go:747; remote=true stops re-fan-out)."""
        self._request(node, "POST", "/schema?remote=true",
                      json.dumps({"indexes": schema}).encode())

    def schema(self, node) -> list[dict]:
        """Peer schema pull (reference NodeStatus carries Schema;
        server.go:640 handles it on receive)."""
        resp = self._request(node, "GET", "/schema")
        return resp["indexes"]

    def backup_keys(self, node) -> list:
        """Fragment keys a peer holds durable files for (backup
        coordinator enumeration)."""
        resp = self._request(node, "GET", "/internal/backup/keys")
        return resp.get("keys", [])

    def backup_fragment(self, node, index, field, view, shard) -> dict:
        """One fragment's verified (snap, wal) pair from a peer. A 503
        means that copy is quarantined — surface the typed error so the
        coordinator fails over to a replica."""
        import base64
        q = urllib.parse.urlencode({"index": index, "field": field,
                                    "view": view, "shard": shard})
        try:
            resp = self._request(node, "GET",
                                 f"/internal/backup/fragment?{q}")
        except NodeHTTPError as e:
            if e.code == 503 and "quarantined" in str(e):
                from pilosa_tpu.storage.quarantine import ShardCorruptError
                raise ShardCorruptError() from e
            raise
        return {
            "snap": (base64.b64decode(resp["snap"])
                     if resp.get("snap") else None),
            "wal": (base64.b64decode(resp["wal"])
                    if resp.get("wal") else None),
            "ops": int(resp.get("ops") or 0),
        }

    def attr_blocks(self, node, index, field):
        path = f"/internal/attr/blocks?index={index}"
        if field:
            path += f"&field={field}"
        resp = self._request(node, "GET", path)
        return [(int(b["id"]), bytes.fromhex(b["checksum"]))
                for b in resp["blocks"]]

    def attr_block_data(self, node, index, field, block):
        path = f"/internal/attr/data?index={index}&block={int(block)}"
        if field:
            path += f"&field={field}"
        resp = self._request(node, "GET", path)
        return {int(i): a for i, a in resp["attrs"].items()}
