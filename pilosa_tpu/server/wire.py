"""Typed wire codec for internal (node-to-node) query results.

Reference: encoding/proto/proto.go:29 — the protobuf Serializer used for
``remote=true`` query responses (QueryResponse with typed Row/Pairs/
ValCount/GroupCounts payloads). Here: a tagged-JSON envelope with the
same type fidelity; the coordinator decodes back to internal result
objects before reducing.
"""

from __future__ import annotations

from typing import Any

from pilosa_tpu.core.row import Row
from pilosa_tpu.exec.result import FieldRow, GroupCount, Pair, ValCount


def encode_result(r: Any) -> dict:
    if isinstance(r, Row):
        return {"t": "row", "columns": [int(c) for c in r.columns()],
                "attrs": r.attrs}
    if isinstance(r, ValCount):
        return {"t": "valcount", "val": r.val, "count": r.count}
    if isinstance(r, Pair):
        return {"t": "pair", "id": r.id, "count": r.count, "key": r.key}
    if isinstance(r, list):
        if r and isinstance(r[0], Pair):
            return {"t": "pairs",
                    "items": [[p.id, p.count] for p in r]}
        if r and isinstance(r[0], GroupCount):
            return {"t": "groupcounts",
                    "items": [{"group": [[fr.field, fr.row_id]
                                         for fr in gc.group],
                               "count": gc.count} for gc in r]}
        return {"t": "rowids", "items": [int(x) for x in r]}
    if isinstance(r, bool) or isinstance(r, int) or r is None:
        return {"t": "scalar", "v": r}
    raise TypeError(f"unencodable internal result {type(r)}")


def decode_result(d: dict) -> Any:
    t = d.get("t")
    if t == "row":
        row = Row.from_columns(d["columns"])
        row.attrs = d.get("attrs") or {}
        return row
    if t == "valcount":
        return ValCount(d["val"], d["count"])
    if t == "pair":
        return Pair(id=d["id"], count=d["count"], key=d.get("key", ""))
    if t == "pairs":
        return [Pair(id=i, count=c) for i, c in d["items"]]
    if t == "groupcounts":
        return [GroupCount(group=[FieldRow(field=f, row_id=rid)
                                  for f, rid in item["group"]],
                           count=item["count"])
                for item in d["items"]]
    if t == "rowids":
        return list(d["items"])
    if t == "scalar":
        return d["v"]
    raise TypeError(f"undecodable internal result {d!r}")
