"""Typed wire codec for internal (node-to-node) query results.

Reference: encoding/proto/proto.go:29 — the protobuf Serializer used for
``remote=true`` query responses (QueryResponse with typed Row/Pairs/
ValCount/GroupCounts payloads). Here: a tagged-JSON envelope with the
same type fidelity; the coordinator decodes back to internal result
objects before reducing.
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

from pilosa_tpu.core.row import Row
from pilosa_tpu.exec.result import FieldRow, GroupCount, Pair, ValCount

#: binary frame response for remote queries (see encode_frames).
FRAMES_CONTENT_TYPE = "application/x-pilosa-frames"
_FRAME_MAGIC = b"PTF1"


def encode_result(r: Any) -> dict:
    if isinstance(r, Row):
        return {"t": "row", "columns": [int(c) for c in r.columns()],
                "attrs": r.attrs}
    if isinstance(r, ValCount):
        return {"t": "valcount", "val": r.val, "count": r.count}
    if isinstance(r, Pair):
        return {"t": "pair", "id": r.id, "count": r.count, "key": r.key}
    if isinstance(r, list):
        if r and isinstance(r[0], Pair):
            return {"t": "pairs",
                    "items": [[p.id, p.count] for p in r]}
        if r and isinstance(r[0], GroupCount):
            return {"t": "groupcounts",
                    "items": [{"group": [[fr.field, fr.row_id]
                                         for fr in gc.group],
                               "count": gc.count} for gc in r]}
        return {"t": "rowids", "items": [int(x) for x in r]}
    if isinstance(r, bool) or isinstance(r, int) or r is None:
        return {"t": "scalar", "v": r}
    raise TypeError(f"unencodable internal result {type(r)}")


def decode_result(d: dict) -> Any:
    t = d.get("t")
    if t == "row":
        row = Row.from_columns(d["columns"])
        row.attrs = d.get("attrs") or {}
        return row
    if t == "valcount":
        return ValCount(d["val"], d["count"])
    if t == "pair":
        return Pair(id=d["id"], count=d["count"], key=d.get("key", ""))
    if t == "pairs":
        return [Pair(id=i, count=c) for i, c in d["items"]]
    if t == "groupcounts":
        return [GroupCount(group=[FieldRow(field=f, row_id=rid)
                                  for f, rid in item["group"]],
                           count=item["count"])
                for item in d["items"]]
    if t == "rowids":
        return list(d["items"])
    if t == "scalar":
        return d["v"]
    raise TypeError(f"undecodable internal result {d!r}")


# -- binary frames (reference encoding/proto/proto.go:29) -------------------
#
# A distributed Row() result is a bitmap; as a JSON int list a 1M-bit row
# costs ~8 MB of text. The frame format keeps the tagged-JSON envelope
# for small typed results but carries each Row as SERIALIZED ROARING
# BYTES (the codec both ends already share) in a length-prefixed binary
# section:
#
#   "PTF1" | u32 header_len | header JSON | blob 0 | blob 1 | ...
#
# header = {"results": [...], "blobs": [len0, len1, ...]} where a Row
# appears as {"t": "row_frame", "blob": k, "attrs": {...}}.


def encode_frames(results: list) -> bytes:
    blobs: list[bytes] = []
    metas: list[dict] = []
    from pilosa_tpu import native
    for r in results:
        if isinstance(r, Row):
            cols = np.asarray(r.columns(), dtype=np.uint64)
            metas.append({"t": "row_frame", "blob": len(blobs),
                          "attrs": r.attrs})
            blobs.append(native.encode_roaring(cols))
        else:
            metas.append(encode_result(r))
    header = json.dumps({"results": metas,
                         "blobs": [len(b) for b in blobs]}).encode()
    return b"".join([_FRAME_MAGIC, struct.pack("<I", len(header)), header]
                    + blobs)


def decode_frames(data: bytes) -> list[Any]:
    if data[:4] != _FRAME_MAGIC:
        raise ValueError("bad frame magic")
    (hlen,) = struct.unpack_from("<I", data, 4)
    header = json.loads(data[8:8 + hlen].decode())
    off = 8 + hlen
    blobs = []
    for ln in header["blobs"]:
        blobs.append(data[off:off + ln])
        off += ln
    from pilosa_tpu import native
    out: list[Any] = []
    for m in header["results"]:
        if m.get("t") == "row_frame":
            row = Row.from_columns(native.decode_roaring(blobs[m["blob"]]))
            row.attrs = m.get("attrs") or {}
            out.append(row)
        else:
            out.append(decode_result(m))
    return out
