"""Typed wire codec for internal (node-to-node) query results.

Reference: encoding/proto/proto.go:29 — the protobuf Serializer used for
``remote=true`` query responses (QueryResponse with typed Row/Pairs/
ValCount/GroupCounts payloads). Here: a tagged-JSON envelope with the
same type fidelity; the coordinator decodes back to internal result
objects before reducing.
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

from pilosa_tpu.core.row import Row
from pilosa_tpu.exec.result import FieldRow, GroupCount, Pair, ValCount
from pilosa_tpu.sketch.hll import DistinctValues, HLLSketch, SimPartial

#: binary frame response for remote queries (see encode_frames).
FRAMES_CONTENT_TYPE = "application/x-pilosa-frames"
#: Accept value advertising frame VERSION 2 (aggregate results as raw
#: array blobs, not JSON int lists). Version negotiation is one-sided
#: and safe in mixed-version clusters: an old peer substring-matches the
#: base content type and answers v1 frames (which v2 clients decode),
#: and an old client never sends ";v=2" so a new peer answers it v1.
FRAMES_ACCEPT_V2 = FRAMES_CONTENT_TYPE + ";v=2"
_FRAME_MAGIC = b"PTF1"

#: multiplexed peer-channel envelope: N query legs in one POST
#: /internal/query-mux request, N PTF1 frames (or per-leg errors) in
#: one response (see encode_mux_request/encode_mux_response below).
MUX_CONTENT_TYPE = "application/x-pilosa-mux"
_MUX_MAGIC = b"PTM1"
MUX_VERSION = 1


def encode_result(r: Any) -> dict:
    if isinstance(r, Row):
        return {"t": "row", "columns": [int(c) for c in r.columns()],
                "attrs": r.attrs}
    if isinstance(r, ValCount):
        return {"t": "valcount", "val": r.val, "count": r.count}
    if isinstance(r, Pair):
        return {"t": "pair", "id": r.id, "count": r.count, "key": r.key}
    if isinstance(r, HLLSketch):
        return {"t": "hll", "p": int(r.p),
                "regs": [int(x) for x in r.regs]}
    if isinstance(r, DistinctValues):
        return {"t": "distinct", "vals": [int(x) for x in r.values]}
    if isinstance(r, SimPartial):
        # ``order`` (device top-k) deliberately stays off the wire: it
        # only ranks ONE node's totals and the coordinator re-ranks the
        # merged sums.
        return {"t": "simpartial",
                "ids": [int(x) for x in r.ids],
                "overlap": [int(x) for x in r.overlap],
                "selfcnt": [int(x) for x in r.selfcnt],
                "filtcnt": int(r.filtcnt)}
    if isinstance(r, list):
        if r and isinstance(r[0], Pair):
            d = {"t": "pairs",
                 "items": [[p.id, p.count] for p in r]}
            # Keyed TopN: ids alone lose the translated keys across the
            # node boundary; ship them alongside (sparse fields stay
            # absent so unkeyed results pay nothing).
            if any(p.key for p in r):
                d["keys"] = [p.key for p in r]
            return d
        if r and isinstance(r[0], GroupCount):
            return {"t": "groupcounts",
                    "items": [{"group": [[fr.field, fr.row_id]
                                         for fr in gc.group],
                               "count": gc.count} for gc in r]}
        return {"t": "rowids", "items": [int(x) for x in r]}
    if isinstance(r, bool) or isinstance(r, int) or r is None:
        return {"t": "scalar", "v": r}
    raise TypeError(f"unencodable internal result {type(r)}")


def decode_result(d: dict) -> Any:
    t = d.get("t")
    if t == "row":
        row = Row.from_columns(d["columns"])
        row.attrs = d.get("attrs") or {}
        return row
    if t == "valcount":
        return ValCount(d["val"], d["count"])
    if t == "pair":
        return Pair(id=d["id"], count=d["count"], key=d.get("key", ""))
    if t == "pairs":
        keys = d.get("keys")
        if keys:
            return [Pair(id=i, count=c, key=k)
                    for (i, c), k in zip(d["items"], keys)]
        return [Pair(id=i, count=c) for i, c in d["items"]]
    if t == "groupcounts":
        # FieldRow.row_key deliberately does not cross the wire: group
        # keys are translated ONCE, coordinator-side, after the reduce
        # (exec/executor.py), so remote legs ship ids only.
        return [GroupCount(group=[FieldRow(field=f, row_id=rid)  # analysis: ignore[wire-symmetry]
                                  for f, rid in item["group"]],
                           count=item["count"])
                for item in d["items"]]
    if t == "rowids":
        return list(d["items"])
    if t == "hll":
        return HLLSketch(p=int(d["p"]),
                         regs=np.asarray(d["regs"], dtype=np.uint8))
    if t == "distinct":
        return DistinctValues(values=np.asarray(d["vals"], dtype=np.int64))
    if t == "simpartial":
        return SimPartial(ids=np.asarray(d["ids"], dtype=np.uint64),
                          overlap=np.asarray(d["overlap"], dtype=np.int64),
                          selfcnt=np.asarray(d["selfcnt"], dtype=np.int64),
                          filtcnt=int(d["filtcnt"]))
    if t == "scalar":
        return d["v"]
    raise TypeError(f"undecodable internal result {d!r}")


# -- binary frames (reference encoding/proto/proto.go:29) -------------------
#
# A distributed Row() result is a bitmap; as a JSON int list a 1M-bit row
# costs ~8 MB of text. The frame format keeps the tagged-JSON envelope
# for small typed results but carries each Row as SERIALIZED ROARING
# BYTES (the codec both ends already share) in a length-prefixed binary
# section:
#
#   "PTF1" | u32 header_len | header JSON | blob 0 | blob 1 | ...
#
# header = {"results": [...], "blobs": [len0, len1, ...]} where a Row
# appears as {"t": "row_frame", "blob": k, "attrs": {...}}.
#
# VERSION 2 extends the binary sections to the aggregate results that
# used to ride the JSON envelope as Python int lists — a 10k-group
# GroupBy was a json walk on both ends:
#
#   {"t": "pairs_frame",  "ids": A, "counts": A, "keys": [...]?}
#   {"t": "groupcounts_frame", "fields": [f...], "rows": A, "counts": A,
#    "n": N}                        (rows = N x depth row-major u64)
#   {"t": "rowids_frame", "ids": A}
#   {"t": "valcount_frame", "vc": A}       (i64 [val, count])
#
# where A = {"blob": k, "dtype": "<u8", "n": N} exactly like PTI1
# import arrays (u64 ids narrow to u32 when they fit; the dtype string
# restores the width on decode). Aggregates below _AGG_BLOB_MIN items,
# keyed group rows, and non-uniform group shapes keep the JSON metas —
# both encodings decode bit-identically.

#: below this many items the tagged-JSON meta is cheaper than blob
#: bookkeeping; the cutover only changes the encoding, never the result.
_AGG_BLOB_MIN = 16


def _arr_meta(a: np.ndarray, blobs: list[bytes]) -> dict:
    """Append ``a`` as a binary section, return its header meta
    (the PTI1 array idiom: u64 that fits 32 bits ships as u32)."""
    if a.dtype == np.uint64 and len(a) and int(a.max()) < (1 << 32):
        a = a.astype(np.uint32)
    meta = {"blob": len(blobs), "dtype": a.dtype.str, "n": int(len(a))}
    blobs.append(np.ascontiguousarray(a).tobytes())
    return meta


def _encode_agg_frame(r: Any, blobs: list[bytes]) -> dict | None:
    """Binary meta for a large aggregate result, or None when the
    tagged-JSON envelope is the better (or only faithful) encoding."""
    if isinstance(r, ValCount):
        if (isinstance(r.val, int) and isinstance(r.count, int)
                and not isinstance(r.val, bool)):
            return {"t": "valcount_frame",
                    "vc": _arr_meta(np.array([r.val, r.count],
                                             dtype=np.int64), blobs)}
        return None
    if isinstance(r, HLLSketch):
        # Register blob: 2^p raw uint8 bytes instead of a JSON int list
        # (a p=14 register file is 16 KiB of bytes vs ~64 KiB of text).
        return {"t": "hll_frame", "p": int(r.p),
                "regs": _arr_meta(np.asarray(r.regs, dtype=np.uint8),
                                  blobs)}
    if isinstance(r, DistinctValues):
        return {"t": "distinct_frame",
                "vals": _arr_meta(np.asarray(r.values, dtype=np.int64),
                                  blobs)}
    if isinstance(r, SimPartial):
        # ``order`` stays off the wire — see the JSON encoding above.
        return {"t": "simpartial_frame", "filtcnt": int(r.filtcnt),
                "ids": _arr_meta(np.asarray(r.ids, dtype=np.uint64),
                                 blobs),
                "overlap": _arr_meta(np.asarray(r.overlap,
                                                dtype=np.int64), blobs),
                "selfcnt": _arr_meta(np.asarray(r.selfcnt,
                                                dtype=np.int64), blobs)}
    if not isinstance(r, list) or len(r) < _AGG_BLOB_MIN:
        return None
    if isinstance(r[0], Pair):
        if not all(isinstance(p, Pair) for p in r):
            return None
        n = len(r)
        meta = {"t": "pairs_frame",
                "ids": _arr_meta(np.fromiter((p.id for p in r),
                                             dtype=np.uint64, count=n),
                                 blobs),
                "counts": _arr_meta(np.fromiter((p.count for p in r),
                                                dtype=np.int64, count=n),
                                    blobs)}
        if any(p.key for p in r):
            meta["keys"] = [p.key for p in r]
        return meta
    if isinstance(r[0], GroupCount):
        fields = [fr.field for fr in r[0].group]
        uniform = all(
            isinstance(gc, GroupCount) and len(gc.group) == len(fields)
            and all(fr.field == f and not fr.row_key
                    for fr, f in zip(gc.group, fields))
            for gc in r)
        if not uniform:
            return None  # keyed / ragged groups keep the JSON meta
        n = len(r)
        rows = np.fromiter((fr.row_id for gc in r for fr in gc.group),
                           dtype=np.uint64, count=n * len(fields))
        counts = np.fromiter((gc.count for gc in r),
                             dtype=np.int64, count=n)
        return {"t": "groupcounts_frame", "fields": fields, "n": n,
                "rows": _arr_meta(rows, blobs),
                "counts": _arr_meta(counts, blobs)}
    # Plain rowid lists (Rows() remote legs). Anything non-integral
    # falls back to the JSON meta.
    try:
        ids = np.fromiter((int(x) for x in r), dtype=np.uint64,
                          count=len(r))
    except (TypeError, ValueError, OverflowError):
        return None
    return {"t": "rowids_frame", "ids": _arr_meta(ids, blobs)}


def encode_frames(results: list, extra: dict | None = None,
                  version: int = 2) -> bytes:
    """``extra`` merges response-level metadata into the frame header;
    decoders that don't know the keys ignore them. Current keys:
    ``shardEpochs`` (the serving node's pre-execution epoch vector) and
    ``profile`` (the node's own QueryProfile ledger when the
    coordinator queried with profiling on — obs/profile.py; the client
    stashes it per thread for map_reduce's per-leg recorder).

    ``version=1`` keeps aggregates in the JSON envelope — the shape an
    old (pre-v2) coordinator can decode; peers answer v1 unless the
    client's Accept advertised ``;v=2`` (FRAMES_ACCEPT_V2)."""
    blobs: list[bytes] = []
    metas: list[dict] = []
    from pilosa_tpu import native
    for r in results:
        if isinstance(r, Row):
            cols = np.asarray(r.columns(), dtype=np.uint64)
            metas.append({"t": "row_frame", "blob": len(blobs),
                          "attrs": r.attrs})
            blobs.append(native.encode_roaring(cols))
            continue
        m = _encode_agg_frame(r, blobs) if version >= 2 else None
        metas.append(m if m is not None else encode_result(r))
    head = {"results": metas, "blobs": [len(b) for b in blobs]}
    if extra:
        head.update(extra)
    header = json.dumps(head).encode()
    return b"".join([_FRAME_MAGIC, struct.pack("<I", len(header)), header]
                    + blobs)


#: binary body for /internal/import (forwarded shard-routed imports).
#: JSON int lists cost ~11 bytes/value to encode plus a Python-level
#: json walk of millions of ints; raw little-endian arrays are ~8
#: bytes/value and microseconds to produce (reference analog: protobuf
#: ImportRequest, encoding/proto/proto.go — binary on the wire, not
#: JSON). Layout: "PTI1" | u32 header_len | header JSON | blob0 | ...
#: where header = {"fields": {...scalars...}, "arrays": {name:
#: {"blob": k, "dtype": "<u8", "n": N}}, "blobs": [len0, ...]}.
#: Single-row batches (the bulk-load shape) collapse rowIDs to a
#: rowConst scalar instead of shipping N identical values.
_IMPORT_MAGIC = b"PTI1"
_IMPORT_ARRAYS = (("rowIDs", np.uint64), ("columnIDs", np.uint64),
                  ("values", np.int64), ("timestamps", np.uint64))
#: per-element "no timestamp" sentinel in the timestamps array — epoch
#: seconds can never reach it, and it pins the array at u64 so the u32
#: narrowing below never fires on a mixed batch.
_TS_NONE = (1 << 64) - 1


def encode_import(req: dict) -> bytes:
    blobs: list[bytes] = []
    arrays: dict = {}
    fields = {k: v for k, v in req.items()
              if k not in ("rowIDs", "columnIDs", "values", "timestamps")}
    for name, dtype in _IMPORT_ARRAYS:
        v = req.get(name)
        if v is None:
            continue
        if name == "timestamps":
            # Unix epoch seconds, None riding as the u64 sentinel.
            a = np.ascontiguousarray(
                [_TS_NONE if t is None else int(t) for t in v],
                dtype=np.uint64)
        else:
            a = np.ascontiguousarray(v, dtype=dtype)
        if name == "rowIDs" and len(a) and (a == a[0]).all():
            fields["rowConst"] = int(a[0])
            fields["rowN"] = len(a)
            continue
        # Ids that fit 32 bits ship as u32 (halves the common case:
        # column ids under 4B columns); the header's dtype restores the
        # width on decode.
        if dtype is np.uint64 and len(a) and int(a.max()) < (1 << 32):
            a = a.astype(np.uint32)
        arrays[name] = {"blob": len(blobs),
                        "dtype": a.dtype.str, "n": len(a)}
        blobs.append(a.tobytes())
    header = json.dumps({"fields": fields, "arrays": arrays,
                         "blobs": [len(b) for b in blobs]}).encode()
    return b"".join([_IMPORT_MAGIC, struct.pack("<I", len(header)), header]
                    + blobs)


def is_import_frame(data: bytes) -> bool:
    return data[:4] == _IMPORT_MAGIC


def decode_import(data: bytes) -> dict:
    """Raises ValueError on ANY malformed frame (truncated header,
    missing keys, bad blob indexes) so the HTTP layer maps it to 400
    like malformed JSON, not a 500."""
    if not is_import_frame(data):
        raise ValueError("bad import frame magic")
    try:
        (hlen,) = struct.unpack_from("<I", data, 4)
        header = json.loads(data[8:8 + hlen].decode())
        off = 8 + hlen
        blobs = []
        for ln in header["blobs"]:
            blobs.append(data[off:off + ln])
            off += ln
        req = dict(header["fields"])
        for name, meta in header["arrays"].items():
            a = np.frombuffer(blobs[meta["blob"]],
                              dtype=np.dtype(meta["dtype"]))
            if len(a) != meta["n"]:
                raise ValueError(f"import frame: {name} length mismatch")
            if name in ("rowIDs", "columnIDs"):
                a = a.astype(np.uint64)  # restore width (and writability)
            req[name] = a
        if "rowConst" in req:
            req["rowIDs"] = np.full(req.pop("rowN"), req.pop("rowConst"),
                                    dtype=np.uint64)
        if "timestamps" in req:
            # Back to the handler's list[int|None] shape (tq.parse_time
            # accepts plain ints, not numpy scalars).
            req["timestamps"] = [
                None if t == _TS_NONE else t
                for t in req["timestamps"].astype(np.uint64).tolist()]
        return req
    except (struct.error, KeyError, IndexError, TypeError,
            UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"malformed import frame: {e!r}") from e


# -- streaming import (chunked PTI1 pipeline) -------------------------------
#
# Bulk loads used to pay one HTTP round trip (and one whole-body decode)
# per shard batch. The import stream multiplexes MANY shard batches over
# one connection as length-prefixed PTI1 frames the server can decode,
# WAL-append, and upload PER CHUNK while the client is still sending the
# rest — a pipeline, not a request loop (reference analog: ctl/'s
# shard-batched import client feeding /import continuously).
#
#   "PTS1" | u32 len0 | <PTI1 frame 0> | u32 len1 | <PTI1 frame 1> | ...
#   ... | u32 0                                        (terminator)
#
# The envelope is VERSIONED by its magic exactly like the mux channel:
# an old peer 404s the route, and the client falls back to per-chunk
# /internal/import posts, so mixed-version rings keep working.

STREAM_CONTENT_TYPE = "application/x-pilosa-import-stream"
_STREAM_MAGIC = b"PTS1"
#: one chunk's frame may not exceed this (a corrupt/hostile length
#: prefix must not make the server buffer gigabytes).
STREAM_MAX_CHUNK = 256 << 20


def stream_preamble() -> bytes:
    return _STREAM_MAGIC


def stream_chunk(req: dict) -> bytes:
    frame = encode_import(req)
    return struct.pack("<I", len(frame)) + frame


def stream_end() -> bytes:
    return struct.pack("<I", 0)


def _read_exact(read, n: int) -> bytes:
    parts = []
    need = n
    while need:
        b = read(need)
        if not b:
            raise ValueError("truncated import stream")
        parts.append(b)
        need -= len(b)
    return b"".join(parts)


def iter_stream_frames(read):
    """Yield raw PTI1 frame bytes from a file-like ``read(n)`` callable,
    validating the preamble and stopping at the zero-length terminator.
    Yields BYTES, not decoded requests, so a backpressuring server can
    keep draining (cheaply) after it stops applying. Raises ValueError
    on malformation — the 400 signal an old client needs."""
    if _read_exact(read, 4) != _STREAM_MAGIC:
        raise ValueError("bad import stream magic")
    while True:
        (ln,) = struct.unpack("<I", _read_exact(read, 4))
        if ln == 0:
            return
        if ln > STREAM_MAX_CHUNK:
            raise ValueError("import stream chunk too large")
        yield _read_exact(read, ln)


def _decode_header(data: bytes, magic: bytes = _FRAME_MAGIC) -> dict:
    """Raises ValueError on ANY malformation (bad magic, truncated or
    undecodable header) so transport layers surface a clean protocol
    error — never a stack trace — and HTTP maps it to 400."""
    if data[:4] != magic:
        raise ValueError(f"bad frame magic (want {magic!r})")
    try:
        (hlen,) = struct.unpack_from("<I", data, 4)
        if 8 + hlen > len(data):
            raise ValueError("truncated frame header")
        header = json.loads(data[8:8 + hlen].decode())
    except (struct.error, UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"malformed frame header: {e!r}") from e
    if not isinstance(header, dict):
        raise ValueError("frame header is not an object")
    return header


def _split_blobs(data: bytes, header: dict) -> list[bytes]:
    (hlen,) = struct.unpack_from("<I", data, 4)
    off = 8 + hlen
    blobs = []
    for ln in header["blobs"]:
        if not isinstance(ln, int) or ln < 0 or off + ln > len(data):
            raise ValueError("truncated frame body")
        blobs.append(data[off:off + ln])
        off += ln
    return blobs


def _read_arr(meta: dict, blobs: list[bytes]) -> np.ndarray:
    a = np.frombuffer(blobs[meta["blob"]], dtype=np.dtype(meta["dtype"]))
    if len(a) != meta["n"]:
        raise ValueError("frame array length mismatch")
    return a


def decode_frames(data: bytes) -> list[Any]:
    """Raises ValueError on any malformed frame, like decode_import."""
    header = _decode_header(data)
    from pilosa_tpu import native
    try:
        blobs = _split_blobs(data, header)
        out: list[Any] = []
        for m in header["results"]:
            t = m.get("t")
            if t == "row_frame":
                # Batched device scatter: the leg's roaring positions
                # upload once and every shard's word block builds in a
                # single program (host fallback under the threshold).
                from pilosa_tpu.exec import device_reduce
                row = device_reduce.row_from_columns(
                    native.decode_roaring(blobs[m["blob"]]))
                row.attrs = m.get("attrs") or {}
                out.append(row)
            elif t == "pairs_frame":
                ids = _read_arr(m["ids"], blobs)
                counts = _read_arr(m["counts"], blobs)
                if len(ids) != len(counts):
                    raise ValueError("pairs frame id/count mismatch")
                keys = m.get("keys")
                if keys is not None and len(keys) != len(ids):
                    raise ValueError("pairs frame key mismatch")
                out.append([Pair(id=int(i), count=int(c),
                                 key=keys[j] if keys else "")
                            for j, (i, c) in enumerate(zip(ids, counts))])
            elif t == "groupcounts_frame":
                fields = m["fields"]
                n = m["n"]
                rows = _read_arr(m["rows"], blobs)
                counts = _read_arr(m["counts"], blobs)
                if len(counts) != n or len(rows) != n * len(fields):
                    raise ValueError("groupcounts frame shape mismatch")
                d = len(fields)
                # row_key stays off the wire by design — see the
                # decode_result groupcounts branch.
                out.append([
                    GroupCount(group=[FieldRow(field=f,  # analysis: ignore[wire-symmetry]
                                               row_id=int(rows[i * d + j]))
                                      for j, f in enumerate(fields)],
                               count=int(counts[i]))
                    for i in range(n)])
            elif t == "rowids_frame":
                out.append([int(x) for x in _read_arr(m["ids"], blobs)])
            elif t == "valcount_frame":
                vc = _read_arr(m["vc"], blobs)
                if len(vc) != 2:
                    raise ValueError("valcount frame shape mismatch")
                out.append(ValCount(int(vc[0]), int(vc[1])))
            elif t == "hll_frame":
                regs = _read_arr(m["regs"], blobs)
                if len(regs) != (1 << int(m["p"])):
                    raise ValueError("hll frame register length mismatch")
                out.append(HLLSketch(p=int(m["p"]),
                                     regs=regs.astype(np.uint8)))
            elif t == "distinct_frame":
                out.append(DistinctValues(
                    values=_read_arr(m["vals"], blobs).astype(np.int64)))
            elif t == "simpartial_frame":
                ids = _read_arr(m["ids"], blobs).astype(np.uint64)
                overlap = _read_arr(m["overlap"], blobs)
                selfcnt = _read_arr(m["selfcnt"], blobs)
                if len(overlap) != len(ids) or len(selfcnt) != len(ids):
                    raise ValueError("simpartial frame shape mismatch")
                out.append(SimPartial(ids=ids,
                                      overlap=overlap.astype(np.int64),
                                      selfcnt=selfcnt.astype(np.int64),
                                      filtcnt=int(m["filtcnt"])))
            else:
                out.append(decode_result(m))
        return out
    except ValueError:
        raise
    except (struct.error, KeyError, IndexError, TypeError,
            AttributeError) as e:
        raise ValueError(f"malformed result frame: {e!r}") from e


def decode_frames_meta(data: bytes) -> tuple[list[Any], dict]:
    """(results, header) — the header exposes response-level metadata
    (``shardEpochs``, ``profile``) alongside the decoding bookkeeping.
    Routed through
    the module-level ``decode_frames`` so call-site instrumentation
    (tests patch it to assert the frame path was taken) still observes
    every decode."""
    return decode_frames(data), _decode_header(data)


# -- multiplexed peer channel (batch envelope) ------------------------------
#
# Under concurrent load a coordinator used to open one HTTP request per
# peer PER QUERY; the peer channel coalesces concurrent outbound legs
# to the same peer into one request. Layout mirrors the frame format:
#
#   "PTM1" | u32 header_len | header JSON [| response blobs]
#
# request header  = {"v": 1, "legs": [{"index", "query", "shards"?,
#                    "timeoutMs"?, "trace"?}, ...]}
# response header = {"v": 1, "legs": [{"blob": k} | {"status": s,
#                    "error": msg, "retryAfter": secs?}],
#                    "blobs": [len0, ...]}
#
# Each response blob is a complete PTF1 frame (per-leg shardEpochs and
# all), so per-leg semantics — deadline, epoch stamps, quarantine 503s,
# shed retries — survive the batching. The envelope is VERSIONED: an
# old peer 404s the route (or 400s the magic) and the client falls back
# to per-query requests, so mixed-version clusters keep working.


def encode_mux_request(legs: list[dict]) -> bytes:
    header = json.dumps({"v": MUX_VERSION, "legs": legs}).encode()
    return b"".join([_MUX_MAGIC, struct.pack("<I", len(header)), header])


def decode_mux_request(data: bytes) -> list[dict]:
    """Raises ValueError on malformed/unknown-version envelopes (HTTP
    maps it to 400 — the signal an old-version client needs)."""
    header = _decode_header(data, magic=_MUX_MAGIC)
    try:
        if header["v"] != MUX_VERSION:
            raise ValueError(f"unsupported mux version {header['v']!r}")
        legs = header["legs"]
        if not isinstance(legs, list) or not all(
                isinstance(leg, dict) and "index" in leg and "query" in leg
                for leg in legs):
            raise ValueError("malformed mux legs")
        return legs
    except (KeyError, TypeError) as e:
        raise ValueError(f"malformed mux request: {e!r}") from e


def encode_mux_response(outcomes: list[dict]) -> bytes:
    """``outcomes``: per leg either {"frame": <PTF1 bytes>} or
    {"status": int, "error": str, "retryAfter": float|None}."""
    blobs: list[bytes] = []
    metas: list[dict] = []
    for o in outcomes:
        if "frame" in o:
            metas.append({"blob": len(blobs)})
            blobs.append(o["frame"])
        else:
            metas.append({"status": int(o["status"]),
                          "error": o.get("error", ""),
                          "retryAfter": o.get("retryAfter")})
    header = json.dumps({"v": MUX_VERSION, "legs": metas,
                         "blobs": [len(b) for b in blobs]}).encode()
    return b"".join([_MUX_MAGIC, struct.pack("<I", len(header)), header]
                    + blobs)


def decode_mux_response(data: bytes) -> list[dict]:
    """Inverse of encode_mux_response; ValueError on malformation."""
    header = _decode_header(data, magic=_MUX_MAGIC)
    try:
        blobs = _split_blobs(data, header)
        out = []
        for m in header["legs"]:
            if "blob" in m:
                out.append({"frame": blobs[m["blob"]]})
            else:
                out.append({"status": int(m["status"]),
                            "error": m.get("error", ""),
                            "retryAfter": m.get("retryAfter")})
        return out
    except ValueError:
        raise
    except (KeyError, IndexError, TypeError) as e:
        raise ValueError(f"malformed mux response: {e!r}") from e
