"""Typed wire codec for internal (node-to-node) query results.

Reference: encoding/proto/proto.go:29 — the protobuf Serializer used for
``remote=true`` query responses (QueryResponse with typed Row/Pairs/
ValCount/GroupCounts payloads). Here: a tagged-JSON envelope with the
same type fidelity; the coordinator decodes back to internal result
objects before reducing.
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

from pilosa_tpu.core.row import Row
from pilosa_tpu.exec.result import FieldRow, GroupCount, Pair, ValCount

#: binary frame response for remote queries (see encode_frames).
FRAMES_CONTENT_TYPE = "application/x-pilosa-frames"
_FRAME_MAGIC = b"PTF1"


def encode_result(r: Any) -> dict:
    if isinstance(r, Row):
        return {"t": "row", "columns": [int(c) for c in r.columns()],
                "attrs": r.attrs}
    if isinstance(r, ValCount):
        return {"t": "valcount", "val": r.val, "count": r.count}
    if isinstance(r, Pair):
        return {"t": "pair", "id": r.id, "count": r.count, "key": r.key}
    if isinstance(r, list):
        if r and isinstance(r[0], Pair):
            return {"t": "pairs",
                    "items": [[p.id, p.count] for p in r]}
        if r and isinstance(r[0], GroupCount):
            return {"t": "groupcounts",
                    "items": [{"group": [[fr.field, fr.row_id]
                                         for fr in gc.group],
                               "count": gc.count} for gc in r]}
        return {"t": "rowids", "items": [int(x) for x in r]}
    if isinstance(r, bool) or isinstance(r, int) or r is None:
        return {"t": "scalar", "v": r}
    raise TypeError(f"unencodable internal result {type(r)}")


def decode_result(d: dict) -> Any:
    t = d.get("t")
    if t == "row":
        row = Row.from_columns(d["columns"])
        row.attrs = d.get("attrs") or {}
        return row
    if t == "valcount":
        return ValCount(d["val"], d["count"])
    if t == "pair":
        return Pair(id=d["id"], count=d["count"], key=d.get("key", ""))
    if t == "pairs":
        return [Pair(id=i, count=c) for i, c in d["items"]]
    if t == "groupcounts":
        return [GroupCount(group=[FieldRow(field=f, row_id=rid)
                                  for f, rid in item["group"]],
                           count=item["count"])
                for item in d["items"]]
    if t == "rowids":
        return list(d["items"])
    if t == "scalar":
        return d["v"]
    raise TypeError(f"undecodable internal result {d!r}")


# -- binary frames (reference encoding/proto/proto.go:29) -------------------
#
# A distributed Row() result is a bitmap; as a JSON int list a 1M-bit row
# costs ~8 MB of text. The frame format keeps the tagged-JSON envelope
# for small typed results but carries each Row as SERIALIZED ROARING
# BYTES (the codec both ends already share) in a length-prefixed binary
# section:
#
#   "PTF1" | u32 header_len | header JSON | blob 0 | blob 1 | ...
#
# header = {"results": [...], "blobs": [len0, len1, ...]} where a Row
# appears as {"t": "row_frame", "blob": k, "attrs": {...}}.


def encode_frames(results: list, extra: dict | None = None) -> bytes:
    """``extra`` merges response-level metadata (e.g. ``shardEpochs``,
    the serving node's pre-execution epoch vector) into the frame
    header; decoders that don't know the keys ignore them."""
    blobs: list[bytes] = []
    metas: list[dict] = []
    from pilosa_tpu import native
    for r in results:
        if isinstance(r, Row):
            cols = np.asarray(r.columns(), dtype=np.uint64)
            metas.append({"t": "row_frame", "blob": len(blobs),
                          "attrs": r.attrs})
            blobs.append(native.encode_roaring(cols))
        else:
            metas.append(encode_result(r))
    head = {"results": metas, "blobs": [len(b) for b in blobs]}
    if extra:
        head.update(extra)
    header = json.dumps(head).encode()
    return b"".join([_FRAME_MAGIC, struct.pack("<I", len(header)), header]
                    + blobs)


#: binary body for /internal/import (forwarded shard-routed imports).
#: JSON int lists cost ~11 bytes/value to encode plus a Python-level
#: json walk of millions of ints; raw little-endian arrays are ~8
#: bytes/value and microseconds to produce (reference analog: protobuf
#: ImportRequest, encoding/proto/proto.go — binary on the wire, not
#: JSON). Layout: "PTI1" | u32 header_len | header JSON | blob0 | ...
#: where header = {"fields": {...scalars...}, "arrays": {name:
#: {"blob": k, "dtype": "<u8", "n": N}}, "blobs": [len0, ...]}.
#: Single-row batches (the bulk-load shape) collapse rowIDs to a
#: rowConst scalar instead of shipping N identical values.
_IMPORT_MAGIC = b"PTI1"
_IMPORT_ARRAYS = (("rowIDs", np.uint64), ("columnIDs", np.uint64),
                  ("values", np.int64))


def encode_import(req: dict) -> bytes:
    blobs: list[bytes] = []
    arrays: dict = {}
    fields = {k: v for k, v in req.items()
              if k not in ("rowIDs", "columnIDs", "values")}
    for name, dtype in _IMPORT_ARRAYS:
        v = req.get(name)
        if v is None:
            continue
        a = np.ascontiguousarray(v, dtype=dtype)
        if name == "rowIDs" and len(a) and (a == a[0]).all():
            fields["rowConst"] = int(a[0])
            fields["rowN"] = len(a)
            continue
        # Ids that fit 32 bits ship as u32 (halves the common case:
        # column ids under 4B columns); the header's dtype restores the
        # width on decode.
        if dtype is np.uint64 and len(a) and int(a.max()) < (1 << 32):
            a = a.astype(np.uint32)
        arrays[name] = {"blob": len(blobs),
                        "dtype": a.dtype.str, "n": len(a)}
        blobs.append(a.tobytes())
    header = json.dumps({"fields": fields, "arrays": arrays,
                         "blobs": [len(b) for b in blobs]}).encode()
    return b"".join([_IMPORT_MAGIC, struct.pack("<I", len(header)), header]
                    + blobs)


def is_import_frame(data: bytes) -> bool:
    return data[:4] == _IMPORT_MAGIC


def decode_import(data: bytes) -> dict:
    """Raises ValueError on ANY malformed frame (truncated header,
    missing keys, bad blob indexes) so the HTTP layer maps it to 400
    like malformed JSON, not a 500."""
    if not is_import_frame(data):
        raise ValueError("bad import frame magic")
    try:
        (hlen,) = struct.unpack_from("<I", data, 4)
        header = json.loads(data[8:8 + hlen].decode())
        off = 8 + hlen
        blobs = []
        for ln in header["blobs"]:
            blobs.append(data[off:off + ln])
            off += ln
        req = dict(header["fields"])
        for name, meta in header["arrays"].items():
            a = np.frombuffer(blobs[meta["blob"]],
                              dtype=np.dtype(meta["dtype"]))
            if len(a) != meta["n"]:
                raise ValueError(f"import frame: {name} length mismatch")
            if name in ("rowIDs", "columnIDs"):
                a = a.astype(np.uint64)  # restore width (and writability)
            req[name] = a
        if "rowConst" in req:
            req["rowIDs"] = np.full(req.pop("rowN"), req.pop("rowConst"),
                                    dtype=np.uint64)
        return req
    except (struct.error, KeyError, IndexError, TypeError,
            UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"malformed import frame: {e!r}") from e


def _decode_header(data: bytes) -> dict:
    if data[:4] != _FRAME_MAGIC:
        raise ValueError("bad frame magic")
    (hlen,) = struct.unpack_from("<I", data, 4)
    return json.loads(data[8:8 + hlen].decode())


def decode_frames(data: bytes) -> list[Any]:
    header = _decode_header(data)
    (hlen,) = struct.unpack_from("<I", data, 4)
    off = 8 + hlen
    blobs = []
    for ln in header["blobs"]:
        blobs.append(data[off:off + ln])
        off += ln
    from pilosa_tpu import native
    out: list[Any] = []
    for m in header["results"]:
        if m.get("t") == "row_frame":
            row = Row.from_columns(native.decode_roaring(blobs[m["blob"]]))
            row.attrs = m.get("attrs") or {}
            out.append(row)
        else:
            out.append(decode_result(m))
    return out


def decode_frames_meta(data: bytes) -> tuple[list[Any], dict]:
    """(results, header) — the header exposes response-level metadata
    (``shardEpochs``) alongside the decoding bookkeeping. Routed through
    the module-level ``decode_frames`` so call-site instrumentation
    (tests patch it to assert the frame path was taken) still observes
    every decode."""
    return decode_frames(data), _decode_header(data)
