"""Node server: API facade + HTTP transport.

Reference: api.go (API :42, the complete public method surface
:135-1323), http/handler.go (router :274), http/client.go (InternalClient
impl :37), server.go (Server orchestration :46).
"""

from pilosa_tpu.server.api import API
from pilosa_tpu.server.httpd import HTTPServer
from pilosa_tpu.server.httpclient import HTTPInternalClient

__all__ = ["API", "HTTPServer", "HTTPInternalClient"]
