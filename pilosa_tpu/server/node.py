"""ServerNode — one full pilosa-tpu node process.

Reference: server.go (Server :46 wires holder+cluster+executor,
receiveMessage :569-663) and server/server.go (Command :60, SetupServer
:222). Assembles Holder + Cluster + Executor(+MeshPlanner) + API +
HTTPServer, wires the control-plane message and import handlers, and
runs the anti-entropy ticker.
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.cluster.cluster import STATE_NORMAL, Cluster
from pilosa_tpu.cluster.event import EVENT_UPDATE
from pilosa_tpu.cluster.harness import handle_cluster_message
from pilosa_tpu.cluster.node import URI, Node
from pilosa_tpu.cluster.sync import HolderSyncer
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.server.api import API
from pilosa_tpu.server.httpclient import HTTPInternalClient
from pilosa_tpu.server.httpd import HTTPServer


class ServerNode:
    """A runnable node (reference `pilosa server`, cmd/server.go:64)."""

    #: default repair cadence, seconds (VERDICT r2 #10: repair must be ON
    #: by default — a killed-and-restarted node converges with no
    #: operator action). The reference's default is 10 minutes
    #: (server.go antiEntropyInterval); ours is short because repairs
    #: are cheap host diffs.
    DEFAULT_ANTI_ENTROPY_INTERVAL = 10.0
    #: failure-detector sweep cadence, seconds (reference: memberlist's
    #: SWIM probes + confirmNodeDown cluster.go:1724).
    DEFAULT_CHECK_NODES_INTERVAL = 5.0
    #: buffer-pool top-up check cadence, seconds (imports adopt pool
    #: chunks as permanent fragment storage; the pool re-faults the
    #: deficit in the background).
    POOL_TOPUP_INTERVAL = 30.0
    #: background scrub cadence, seconds: re-verify on-disk snapshot
    #: CRCs and repair quarantined fragments from replica consensus.
    #: Longer than anti-entropy — a scrub re-reads every snapshot file.
    DEFAULT_SCRUB_INTERVAL = 60.0

    def __init__(self, bind: str = "127.0.0.1:10101",
                 peers: list[str] | None = None,
                 replica_n: int = 1,
                 use_planner: bool = True,
                 anti_entropy_interval: float | None = None,
                 check_nodes_interval: float | None = None,
                 scrub_interval: float | None = None,
                 backup_interval: float = 0.0,
                 archive_url: str | None = None,
                 backup_full_every: int = 8,
                 backup_keep_chains: int = 2,
                 max_op_n: int | None = None,
                 join: str | None = None,
                 data_dir: str | None = None,
                 tls_cert: str | None = None,
                 tls_key: str | None = None,
                 tls_ca_cert: str | None = None,
                 tls_skip_verify: bool | None = None,
                 trace_endpoint: str | None = None,
                 import_pool_mb: int = 0,
                 qos_max_concurrent: int = 0,
                 qos_max_queue: int = 64,
                 qos_internal_reserve: int = 4,
                 qos_class_weights: dict[str, int] | None = None,
                 qos_default_deadline: float = 0.0,
                 qos_slow_query_ms: float = 500.0,
                 qos_warmup: str = "",
                 qos_warmup_shards: str = "1,8,32",
                 quarantine_keep_n: int = 0,
                 qos_adaptive: bool = False,
                 qos_tenant_rate: float = 0.0,
                 qos_tenant_burst: float = 0.0,
                 breaker_threshold: int = 0,
                 breaker_cooldown: float = 5.0,
                 hedge: bool = False,
                 hedge_delay_ms: float = 0.0,
                 hedge_budget_pct: float = 5.0,
                 chaos_faults: bool = False,
                 fence_stale_reads: bool = False,
                 compile_cache_dir: str | None = None,
                 plan_buckets: str = "pow2",
                 result_cache_mb: int = 64,
                 result_cache_ttl: float = 0.0,
                 device_reduce: str = "auto",
                 multiplex: bool = True,
                 ingest_transpose: str = "auto",
                 wal_group_commit_ms: float = 0.0,
                 ingest_max_inflight_mb: int = 0,
                 dispatch_fuse: str = "auto",
                 dispatch_coalesce: str = "auto",
                 dispatch_coalesce_us: float = 150.0,
                 inline_transfer: str = "auto",
                 residency_packed: str = "auto",
                 prefetch: str = "on",
                 translate_planes: str = "auto",
                 sketch_precision: int = 12,
                 sketch_exact_threshold: int = 1024,
                 profile_ring_n: int = 64,
                 profile_queries: bool = True):
        host, _, port = bind.partition(":")
        self.host, self.port = host or "127.0.0.1", int(port or 10101)
        # Node identity IS the address — member ids are built the same
        # way, so local_id always matches its ring entry.
        self.id = f"{self.host}:{self.port}"
        self.data_dir = data_dir
        #: address of a running cluster member to join on open()
        #: (dynamic membership: the coordinator runs a ResizeJob and
        #: broadcasts the new topology back to us).
        self.join_addr = join

        # Membership: boot peer list (each "host:port" becomes a Node);
        # joins/leaves after boot go through the coordinator's resize
        # flow (handle_join / resize below). A TLS node assumes a
        # uniformly-TLS cluster (the reference's model too): every peer
        # URI gets the https scheme and internal RPC skips verification
        # (operators deploying internal CAs can front their own certs).
        scheme = "https" if tls_cert else "http"
        members = []
        all_addrs = sorted(set((peers or []) + [f"{self.host}:{self.port}"]))
        for i, addr in enumerate(all_addrs):
            h, _, p = addr.partition(":")
            members.append(Node(id=addr,
                                uri=URI(scheme=scheme, host=h, port=int(p)),
                                is_coordinator=(i == 0 and join is None)))
        self.cluster = None
        if len(members) > 1 or join is not None:
            self.cluster = Cluster(local_id=self.id, nodes=members,
                                   replica_n=replica_n,
                                   client=HTTPInternalClient(
                                       ca_cert=tls_ca_cert,
                                       skip_verify=tls_skip_verify))
            self.cluster.set_state(STATE_NORMAL)
            if join is not None:
                # A fresh joiner owns NO topology: start BELOW version 0
                # so even a cluster still on its boot ring (version 0 —
                # no resize ever committed) can hand us its status
                # through the strictly-newer adoption gate. Found by the
                # chaos soak: a joiner whose address was already in the
                # boot ring wedged solo because the re-admission status
                # carried version 0 and 0 <= 0 read as stale. A
                # persisted topology (restart of an admitted joiner)
                # overrides this below.
                self.cluster.topology_version = -1
        self._scheme = scheme

        from pilosa_tpu.obs import MemoryStats
        self.stats = MemoryStats()
        from pilosa_tpu.obs.logger import StandardLogger
        self.logger = StandardLogger()
        self.tracer = None
        if trace_endpoint:
            # Concrete exporter behind the Tracer protocol (reference
            # tracing/opentracing Jaeger glue): spans from this node
            # stream to the OTLP collector at the given endpoint.
            from pilosa_tpu.obs import OTLPTracer, set_tracer
            self.tracer = OTLPTracer(endpoint=trace_endpoint,
                                     service_name=f"pilosa-tpu:{self.id}")
            set_tracer(self.tracer)
        self.dirty = None
        index_listener = None
        if self.cluster is not None:
            from pilosa_tpu.cluster.dirty import DirtyBroadcaster
            self.dirty = DirtyBroadcaster(self.cluster)
            index_listener = self.dirty.attach
        self.holder = Holder(fragment_listener=self._broadcast_shard,
                             index_listener=index_listener)
        # Persistent XLA compilation cache: pointed at disk BEFORE the
        # planner exists, so its very first jit compile already reads
        # through the cache — a restarted node reuses every kernel
        # prior runs compiled. None/"" resolves to <data-dir>/
        # compile-cache (nodes without a data dir stay memory-only);
        # "off" disables explicitly.
        if not compile_cache_dir:
            compile_cache_dir = (os.path.join(data_dir, "compile-cache")
                                 if data_dir else "")
        self.compile_cache_dir = "" if compile_cache_dir == "off" \
            else compile_cache_dir
        planner = None
        if use_planner:
            if self.compile_cache_dir:
                from pilosa_tpu.parallel import compile_cache
                compile_cache.enable(self.compile_cache_dir,
                                     stats=self.stats)
            try:
                from pilosa_tpu.parallel import MeshPlanner
                planner = MeshPlanner(self.holder,
                                      bucket_policy=plan_buckets,
                                      stats=self.stats,
                                      coalesce_window_us=dispatch_coalesce_us)
            except Exception:
                planner = None
        # Plan-keyed result cache (pilosa_tpu.cache): byte-bounded,
        # tenant-partitioned, shared by every consumer on this node.
        # <= 0 MB disables (the executor then runs every query).
        self.result_cache = None
        if result_cache_mb > 0:
            from pilosa_tpu.cache import ResultCache
            self.result_cache = ResultCache(
                max_bytes=int(result_cache_mb) << 20,
                ttl=result_cache_ttl, stats=self.stats)
        self.executor = Executor(self.holder, cluster=self.cluster,
                                 node_id=self.id, planner=planner,
                                 stats=self.stats,
                                 result_cache=self.result_cache)
        if self.cluster is not None:
            # Remote legs report their shard-epoch vectors back here
            # (cluster.run_remote → RemoteEpochTable) so coordinator
            # cache stamps stay consistent across nodes.
            self.cluster.epoch_sink = self.executor.remote_epochs.observe
        self.api = API(self.holder, self.executor, cluster=self.cluster)
        # Handler hooks used by the HTTP router's /internal routes.
        self.api.message_handler = self.handle_message
        self.api.import_handler = self.handle_internal_import
        self.api.resize_handler = self.resize
        # QoS front: admission gate + default deadline + slow-query log.
        # max_concurrent=0 (the constructor default) leaves the gate
        # open — metrics/slow-log only — so embedded/test nodes keep the
        # old dispatch behavior unless explicitly configured.
        from pilosa_tpu.qos import (
            AdaptiveLimit,
            AdmissionController,
            SlowQueryLog,
            TenantQuotas,
        )
        adaptive = None
        if qos_adaptive and qos_max_concurrent > 0:
            # qos-max-concurrent becomes the CEILING; the operative
            # limit is measured (probe up / multiplicative back-off).
            adaptive = AdaptiveLimit(ceiling=qos_max_concurrent,
                                     stats=self.stats)
        self.qos = AdmissionController(
            max_concurrent=qos_max_concurrent,
            max_queue=qos_max_queue,
            internal_reserve=qos_internal_reserve,
            weights=qos_class_weights,
            default_deadline=qos_default_deadline,
            stats=self.stats,
            slow_log=SlowQueryLog(threshold_ms=qos_slow_query_ms,
                                  stats=self.stats),
            adaptive=adaptive)
        self.api.qos = self.qos
        # Per-query cost profiles: retain the slowest N at
        # /debug/queries; profile_queries=False limits profiling to
        # explicit ?profile=true requests (the zero-overhead posture —
        # every hook degenerates to one None contextvar read).
        self.profile_ring = None
        if profile_ring_n > 0:
            from pilosa_tpu.obs import ProfileRing
            self.profile_ring = ProfileRing(capacity=profile_ring_n)
        self.api.profile_ring = self.profile_ring
        self.api.profile_default = bool(profile_queries)
        # Per-tenant token buckets above class admission (429 vs the
        # gate's 503: "you are over YOUR limit" vs "I am over mine").
        self.quotas = None
        if qos_tenant_rate > 0:
            self.quotas = TenantQuotas(rate_per_s=qos_tenant_rate,
                                       burst=qos_tenant_burst or None,
                                       stats=self.stats)
        self.api.quotas = self.quotas
        # Overload plumbing on the inter-node path: per-peer circuit
        # breakers in the transport, hedged read legs in map_reduce.
        if self.cluster is not None:
            if breaker_threshold > 0:
                from pilosa_tpu.cluster.breaker import BreakerRegistry
                self.cluster.client.breakers = BreakerRegistry(
                    threshold=breaker_threshold,
                    cooldown=breaker_cooldown,
                    stats=self.stats)
            if hedge and replica_n > 1:
                from pilosa_tpu.cluster.breaker import HedgePolicy
                self.cluster.hedge = HedgePolicy(
                    delay_s=hedge_delay_ms / 1000.0,
                    budget_pct=hedge_budget_pct,
                    stats=self.stats)
        #: chaos/fault hook: injected per-query latency (seconds) on
        #: this node's /query handling — the slow-peer gray failure.
        #: POST /internal/fault can only arm it when the operator
        #: opted in (chaos_faults); the route is not mounted otherwise.
        self.api.fault_slow_s = 0.0
        self.api.chaos_faults = bool(chaos_faults)
        if self.cluster is not None:
            # Quorum fencing knobs + the chaos partition fault table
            # (the table is always present; only the chaos-gated
            # /internal/fault route can arm it).
            self.cluster.fence_stale_reads = bool(fence_stale_reads)
            self.cluster.on_unfence = self._on_unfence
            from pilosa_tpu.cluster.faults import PartitionFaults
            self.cluster.client.faults = PartitionFaults()
        self._qos_warmup = qos_warmup
        self._qos_warmup_shards = qos_warmup_shards
        self.warmup = None
        self.http = HTTPServer(self.api, self.host, self.port,
                               tls_cert=tls_cert, tls_key=tls_key)
        self.port = self.http.port
        # Built AFTER the listener resolves an ephemeral bind port —
        # fragment_nodes on a standalone node must advertise an address
        # a client can actually dial (ADVICE r4 #2).
        self.api.local_node = Node(id=f"{self.host}:{self.port}",
                                   uri=URI(scheme=scheme, host=self.host,
                                           port=self.port),
                                   is_coordinator=True)

        self._import_pool_mb = int(import_pool_mb)
        self._pool_stop = threading.Event()
        self.syncer = None
        self.scrubber = None
        self._sync_timer: threading.Timer | None = None
        self._check_timer: threading.Timer | None = None
        self._scrub_timer: threading.Timer | None = None
        self._backup_timer: threading.Timer | None = None
        self._closed = False
        #: one resize job at a time (reference cluster.go:1447).
        self._resize_gate = threading.Lock()
        if self.cluster is not None:
            self.cluster.subscribe(self._on_node_event)
        self._anti_entropy_interval = (
            self.DEFAULT_ANTI_ENTROPY_INTERVAL
            if anti_entropy_interval is None else anti_entropy_interval)
        self._check_nodes_interval = (
            self.DEFAULT_CHECK_NODES_INTERVAL
            if check_nodes_interval is None else check_nodes_interval)
        self._scrub_interval = (
            self.DEFAULT_SCRUB_INTERVAL
            if scrub_interval is None else scrub_interval)
        #: unattended-DR knobs: with both --backup-interval and
        #: --archive-url set, open() starts a BackupScheduler ticking
        #: periodic incrementals into the archive (scheduler.py).
        self._backup_interval = float(backup_interval or 0.0)
        self._archive_url = archive_url
        self._backup_full_every = int(backup_full_every)
        self._backup_keep_chains = int(backup_keep_chains)
        self.backup_scheduler = None
        self.backup_archive = None
        # Device-side fold of remote bitmap legs (exec/device_reduce);
        # the PILOSA_TPU_DEVICE_REDUCE env var still overrides per-run.
        from pilosa_tpu.exec import device_reduce as _device_reduce
        _device_reduce.set_mode(device_reduce)
        # Device-side BSI bit-plane transpose for bulk value imports
        # (exec/ingest_transpose); PILOSA_TPU_INGEST_TRANSPOSE overrides.
        from pilosa_tpu.exec import ingest_transpose as _ingest_transpose
        _ingest_transpose.set_mode(ingest_transpose)
        # Query-dispatch knobs (README "Query dispatch"): fused one-
        # program-per-query plans, same-plan dispatch coalescing, and
        # inline transfer resolution. Env vars PILOSA_TPU_DISPATCH_FUSE /
        # _DISPATCH_COALESCE / _INLINE_TRANSFER override per-run.
        from pilosa_tpu.exec import fuse as _dispatch_fuse
        _dispatch_fuse.set_mode(dispatch_fuse)
        from pilosa_tpu.parallel import coalesce as _dispatch_coalesce
        _dispatch_coalesce.set_mode(dispatch_coalesce)
        from pilosa_tpu.parallel import batcher as _transfer_batcher
        _transfer_batcher.set_inline_mode(inline_transfer)
        # Device-residency knobs (README "Device residency & prefetch"):
        # container-classed packed leaf stacks and the pipelined async
        # miss path. Env vars PILOSA_TPU_RESIDENCY_PACKED /
        # PILOSA_TPU_PREFETCH override per-run.
        from pilosa_tpu.exec import residency as _residency
        _residency.set_mode(residency_packed)
        from pilosa_tpu.parallel import prefetch as _prefetch
        _prefetch.set_mode(prefetch)
        # Key-translation planes (README "Key translation"); env var
        # PILOSA_TPU_TRANSLATE_PLANES overrides per-run.
        from pilosa_tpu.exec import keyplane as _keyplane
        _keyplane.set_mode(translate_planes)
        # Approximate-analytics knobs (README "Approximate analytics");
        # PILOSA_TPU_SKETCH_PRECISION / _SKETCH_EXACT_THRESHOLD
        # override per-run.
        from pilosa_tpu import sketch as _sketch
        _sketch.set_precision(sketch_precision)
        _sketch.set_exact_threshold(sketch_exact_threshold)
        # In-flight byte budget for the /internal/import-stream pipeline
        # (0 = unbounded); trips 429 + Retry-After, never queues.
        from pilosa_tpu.qos import IngestGate
        self.ingest_gate = IngestGate(
            max_inflight_bytes=int(ingest_max_inflight_mb) << 20)
        self.api.ingest_gate = self.ingest_gate
        if self.cluster is not None:
            self.cluster.stats = self.stats
            self.cluster.client.stats = self.stats
            self.cluster.client.multiplex = multiplex
            self.syncer = HolderSyncer(self.holder, self.cluster,
                                       self.cluster.client)
            # Coordinator-primary key allocation (translate.go:93 model):
            # every keyed allocation routes to the coordinator.
            from pilosa_tpu.cluster.translate_sync import ClusterKeyTranslator
            translator = ClusterKeyTranslator(self.holder, self.cluster,
                                              self.cluster.client)
            self.executor.translator = translator
            self.api.translator = translator

        if data_dir:
            from pilosa_tpu.storage.diskstore import DiskStore
            kw = {} if max_op_n is None else {"max_op_n": max_op_n}
            self.store = DiskStore(data_dir, self.holder, stats=self.stats,
                                   quarantine_keep_n=quarantine_keep_n,
                                   wal_group_window=wal_group_commit_ms
                                   / 1000.0,
                                   **kw)
            self.store.open()
        else:
            self.store = None
        self.api.store = self.store
        if self.store is not None and self.cluster is not None:
            self._wire_topology_persistence(data_dir)
        if self.store is not None:
            from pilosa_tpu.cluster.scrub import (
                Scrubber,
                route_quarantined_to_replicas,
            )
            if self.cluster is not None:
                # Placement must not hand quarantined shards to this
                # node; route their reads to replicas instead.
                self.cluster.blocked_shards_fn = \
                    self.store.quarantine.blocked_shards
                route_quarantined_to_replicas(self.holder, self.cluster,
                                              self.store, stats=self.stats)
            self.scrubber = Scrubber(
                self.holder, self.cluster,
                self.cluster.client if self.cluster is not None else None,
                self.store, stats=self.stats, logger=self.logger,
                admission=self.qos)
        # Backup/restore driver hooks (POST /backup, /restore). One run
        # of each at a time; jobs run off the request thread and
        # /backup/status, /restore/status read their live progress.
        self._backup_gate = threading.Lock()
        self._restore_gate = threading.Lock()
        self._backup_writer = None
        self._restore_job = None
        if self.store is not None:
            self.api.backup_handler = self.handle_backup
            self.api.backup_status_handler = self.backup_status
            self.api.restore_handler = self.handle_restore
            self.api.restore_status_handler = self.restore_status
        self.api.backup_debug_handler = self.backup_debug

    def _wire_topology_persistence(self, data_dir: str) -> None:
        """Durable topology (reference .topology file, cluster.go:1657):
        every committed nodes/version change is written to
        topology.json, and boot resumes from it. Without this, a
        restarted coordinator's in-memory version resets to 0, its next
        commit broadcasts "version 1", and every peer holding a higher
        version silently rejects the committed ring as stale — a forked
        cluster."""
        import json as _json
        import os as _os

        path = _os.path.join(data_dir, "topology.json")
        save_lock = threading.Lock()
        last_saved = [-1]

        def save() -> None:
            with self.cluster._lock:
                doc = {"version": self.cluster.topology_version,
                       "replicaN": self.cluster.replica_n,
                       "partitionN": self.cluster.partition_n,
                       "nodes": [n.to_json() for n in self.cluster.nodes]}
            # Serialize + version-guard the replace: two concurrent
            # savers (a status RPC and a sweep) must not interleave
            # writes in one tmp, and the one holding the OLDER snapshot
            # must not win the replace — a restart would restore the
            # older ring and fork the cluster (the bug this file
            # exists to prevent). Same pattern as DiskStore.save_schema.
            with save_lock:
                if doc["version"] < last_saved[0]:
                    return
                tmp = f"{path}.{_os.getpid()}.{threading.get_ident()}.tmp"
                with open(tmp, "w") as f:
                    _json.dump(doc, f)
                _os.replace(tmp, path)
                last_saved[0] = doc["version"]

        self.cluster.save_hook = save
        # Sweep tmps a crashed saver stranded (see DiskStore.open).
        try:
            for fn in _os.listdir(data_dir):
                if fn.startswith("topology.json.") and fn.endswith(".tmp"):
                    _os.remove(_os.path.join(data_dir, fn))
        except OSError:
            pass
        try:
            with open(path) as f:
                doc = _json.load(f)
            version = int(doc.get("version", 0))
            saved = [Node.from_json(n) for n in doc.get("nodes", [])]
        except Exception:
            # Best-effort restore: a torn/hand-edited file must fall
            # back to the boot peer list, never crash the boot.
            return
        if version <= self.cluster.topology_version or not saved:
            return
        if not any(n.id == self.id for n in saved):
            # The durable ring excludes US: we were removed while down.
            # Keep the boot list; rejoining is the operator's call.
            return
        self.cluster.nodes = sorted(saved, key=lambda n: n.id)
        self.cluster.topology_version = version
        # Settings adopted from broadcasts are part of the ring: a
        # restart that reverted to boot-config replicaN would compute
        # different placement and the cleaner would GC live replicas.
        if doc.get("replicaN"):
            self.cluster.replica_n = int(doc["replicaN"])
        if doc.get("partitionN"):
            self.cluster.partition_n = int(doc["partitionN"])
        last_saved[0] = version

    # -- lifecycle ---------------------------------------------------------

    def open(self) -> None:
        self.http.serve_background()
        if self._import_pool_mb > 0:
            # Fault the import buffer pool off the serving path — boot
            # keeps serving while pages warm (native recycled page pool;
            # the analog of the reference's mmap page cache being warm
            # for re-imported fragments, fragment.go:311). Then keep it
            # topped up: dense imports ADOPT pool-backed block arrays as
            # permanent fragment storage, permanently draining the
            # freelist, so a one-shot reserve would go cold after a few
            # bulk loads. The top-up loop re-faults the deficit in the
            # background whenever the free level falls below half the
            # configured size.
            def _warm(mb: int = self._import_pool_mb) -> None:
                from pilosa_tpu import native
                target = mb << 20
                native.pool_reserve(target)
                while not self._pool_stop.wait(self.POOL_TOPUP_INTERVAL):
                    stats = native.pool_stats()
                    if stats is None:
                        return
                    deficit = target - stats["free_bytes"]
                    if deficit > target // 2:
                        native.pool_reserve(deficit)
            threading.Thread(target=_warm, daemon=True,
                             name="pool-warm").start()
        if self.join_addr is not None:
            self._send_join()
        if self.syncer is not None and self._anti_entropy_interval > 0:
            self._schedule_sync()
        if self.cluster is not None and self._check_nodes_interval > 0:
            self._schedule_check_nodes()
        if self.scrubber is not None and self._scrub_interval > 0:
            self._schedule_scrub()
        if (self._backup_interval > 0 and self._archive_url
                and self.store is not None):
            from pilosa_tpu.backup import BackupScheduler, open_archive
            self.backup_archive = open_archive(self._archive_url,
                                               stats=self.stats)
            self.backup_scheduler = BackupScheduler(
                holder=self.holder, cluster=self.cluster,
                client=(self.cluster.client
                        if self.cluster is not None else None),
                store=self.store, archive=self.backup_archive,
                interval=self._backup_interval, node_id=self.id,
                stats=self.stats, logger=self.logger, admission=self.qos,
                full_every=self._backup_full_every,
                keep_chains=self._backup_keep_chains)
            self._schedule_backup()
        from pilosa_tpu.obs.runtime import RuntimeMonitor
        self.runtime_monitor = RuntimeMonitor(self.stats,
                                              self.executor.planner,
                                              qos=self.qos)
        self.runtime_monitor.start()
        if self._qos_warmup and self.executor.planner is not None:
            # Precompile the canonical kernel shapes in the background
            # (the planner's program cache is structural, so these
            # compiles serve real traffic); node start never blocks on
            # XLA.
            from pilosa_tpu.qos import WarmupService
            kinds = [k.strip() for k in self._qos_warmup.split(",")
                     if k.strip()]
            shard_counts = [int(s) for s in
                            str(self._qos_warmup_shards).split(",")
                            if s.strip()]
            observed, observed_schema = self._load_observed_traffic()
            self.warmup = WarmupService(self.executor.planner, kinds=kinds,
                                        shard_counts=shard_counts,
                                        observed=observed,
                                        observed_schema=observed_schema,
                                        stats=self.stats)
            self.warmup.start()

    #: join announcement retry schedule (seconds between attempts);
    #: after JOIN_RETRIES fast attempts the announcer drops to the slow
    #: cadence but never stops (a solo joiner has no other path in).
    JOIN_RETRY_DELAY = 1.0
    JOIN_RETRIES = 30
    JOIN_SLOW_RETRY_DELAY = 5.0

    def _send_join(self) -> None:
        """Announce to a running member in the background, retrying —
        the seed may still be booting (the reference's gossip join
        retries the same way, gossip/gossip.go:65). The member forwards
        to the coordinator, which resizes us in and broadcasts the
        topology back (cluster.go:1796)."""
        h, _, p = self.join_addr.partition(":")
        seed = Node(id=self.join_addr,
                    uri=URI(scheme=self._scheme, host=h, port=int(p)))

        def announce():
            import sys
            import time
            attempts = 0
            while not self._closed:
                # Success = this node appears in the ring (the topology
                # broadcast landed), NOT merely a delivered announce —
                # the coordinator's resize runs asynchronously and can
                # fail after accepting.
                if len(self.cluster.nodes) > 1:
                    return
                try:
                    self.cluster.client.send_message(
                        seed, {"type": "node-join", "addr": self.id})
                except Exception:
                    # A paused/overloaded seed times out (OSError, not
                    # ConnectionError); ANY failure here must not kill
                    # the announce thread — it is a solo joiner's only
                    # path into the ring.
                    pass
                attempts += 1
                if attempts == self.JOIN_RETRIES:
                    # Never give up outright: a solo joiner has no peers
                    # to discover the ring through, so announcing IS its
                    # only path in (the seed may be mid-resize, paused,
                    # or restarting for minutes). Drop to a slow cadence
                    # and warn.
                    print(f"join: cluster at {self.join_addr} did not "
                          f"admit us after {self.JOIN_RETRIES} attempts; "
                          f"retrying every "
                          f"{self.JOIN_SLOW_RETRY_DELAY:.0f}s",
                          file=sys.stderr)
                time.sleep(self.JOIN_RETRY_DELAY
                           if attempts < self.JOIN_RETRIES
                           else self.JOIN_SLOW_RETRY_DELAY)

        t = threading.Thread(target=announce, name="join-announce",
                             daemon=True)
        t.start()

    def _jitter(self, interval: float) -> float:
        import random
        return interval * random.uniform(0.8, 1.2)

    def _timer_tick_error(self, timer: str, err: BaseException) -> None:
        """A background sweep (anti-entropy, scrub, backup, liveness)
        blew up. The tick must survive — the next one retries — but a
        wedged sweep has to be VISIBLE: silent passes here turn 'the
        failure detector died an hour ago' into an unexplained outage."""
        self.stats.count("node.timerTickError")
        self.logger.printf("%s timer tick failed: %s: %s",
                           timer, type(err).__name__, err)

    def _on_unfence(self) -> None:
        """Fence lifted (the liveness sweep sees a majority again):
        this node just rejoined from a minority partition, so its data
        AND caches may be behind the majority's writes. Kick an
        immediate dirty-sync — schema adoption + fragment anti-entropy
        — and flush epoch-validated result caches, off the sweep
        thread (same shape as the READY-event repair)."""
        if self._closed:
            return
        self.logger.printf("quorum regained: un-fenced, starting "
                           "rejoin dirty-sync")

        def resync():
            try:
                for iname in self.holder.index_names():
                    idx = self.holder.index(iname)
                    if idx is not None:
                        # Local caches validated against pre-partition
                        # epochs would serve stale reads until the next
                        # write; bump first so repaired bits are seen.
                        idx.epoch.bump(notify=False)
                if self.cluster is not None:
                    self._sync_schema()
                if self.syncer is not None:
                    self.syncer.sync_holder()
            except Exception:
                pass  # the anti-entropy ticker retries
        threading.Thread(target=resync, name="unfence-resync",
                         daemon=True).start()

    def _on_node_event(self, ev) -> None:
        """NodeEvent consumer (reference ReceiveEvent, cluster.go:1754):
        count the stream, and when a peer comes BACK, kick an immediate
        repair pass instead of waiting out the anti-entropy ticker."""
        self.stats.with_tags(f"event:{ev.type}").count("nodeEvents")
        if (ev.type == EVENT_UPDATE and ev.state == "READY"
                and self.syncer is not None and not self._closed):
            def repair():
                try:
                    self._sync_schema()
                    self.syncer.sync_holder()
                except Exception:
                    pass  # ticker retries
            threading.Thread(target=repair, name="event-repair",
                             daemon=True).start()
        if (ev.type == EVENT_UPDATE and ev.state == "READY"
                and self.cluster is not None and not self._closed):
            # A rejoined peer missed every index-dirty broadcast while
            # it was (or merely LOOKED) down — its epoch-validated
            # result caches would serve stale reads until the next
            # write. Push it a full invalidation sweep; and flush our
            # own caches too, since the asymmetric case (it was serving
            # writes we never heard about) leaves OUR caches stale.
            def invalidate(node_id=ev.node_id):
                node = self.cluster.node_by_id(node_id)
                for iname in self.holder.index_names():
                    idx = self.holder.index(iname)
                    if idx is not None:
                        idx.epoch.bump(notify=False)
                    if node is None:
                        continue
                    try:
                        self.cluster.client.send_message(
                            node, {"type": "index-dirty", "index": iname})
                    except (ConnectionError, RuntimeError, LookupError):
                        pass  # next sweep's READY flap retries
            threading.Thread(target=invalidate, name="rejoin-invalidate",
                             daemon=True).start()

    def _sync_schema(self) -> None:
        """Adopt any peer schema this node is missing (a restarted
        member without its data dir re-learns indexes/fields before the
        fragment syncer can repair their bits) AND merge peers' shard
        availability — the additive half of the reference's NodeStatus
        merge (server.go:640: schema + availableShards). Without the
        availability half, a node that missed create-shard broadcasts
        while down answers queries without those shards forever (found
        by the chaos soak: permanent undercounts after rejoin)."""
        for node in self.cluster.nodes:
            if node.id == self.id or node.state == "DOWN":
                continue
            try:
                self.holder.apply_schema(self.cluster.client.schema(node))
            except (ConnectionError, RuntimeError, LookupError, KeyError):
                continue
            try:
                avail = self.cluster.client.availability(node)
                for index, fields in (avail or {}).items():
                    idx = self.holder.index(index)
                    if idx is None:
                        continue
                    for field, shards in fields.items():
                        f = idx.field(field)
                        if f is not None and shards:
                            f.add_remote_available_shards(shards)
            except (ConnectionError, RuntimeError, LookupError, KeyError,
                    AttributeError):
                continue

    def _schedule_sync(self) -> None:
        def tick():
            try:
                from pilosa_tpu.cluster.translate_sync import sync_translation
                self._sync_schema()
                applied = sync_translation(self.holder, self.cluster,
                                           self.cluster.client)
                repaired = self.syncer.sync_holder()
                self.clean_holder()  # ownership GC backstop
                if applied:
                    self.stats.count("antiEntropyTranslateApplied", applied)
                if repaired:
                    self.stats.count("antiEntropyRepaired", repaired)
                self.stats.count("antiEntropyPasses")
            except Exception as e:
                # Next tick retries; repairs must never kill the node —
                # but the failure must be visible, not swallowed.
                self._timer_tick_error("anti-entropy", e)
            finally:
                if not self._closed:
                    self._schedule_sync()
        self._sync_timer = threading.Timer(
            self._jitter(self._anti_entropy_interval), tick)
        self._sync_timer.daemon = True
        self._sync_timer.start()

    def _schedule_scrub(self) -> None:
        def tick():
            try:
                res = self.scrubber.scrub_pass()
                if res.get("mismatch"):
                    self.stats.count("integrity.scrubMismatchFragments",
                                     res["mismatch"])
            except Exception as e:
                # Next tick retries; the scrub must never kill the node.
                self._timer_tick_error("scrub", e)
            finally:
                if not self._closed:
                    self._schedule_scrub()
        self._scrub_timer = threading.Timer(
            self._jitter(self._scrub_interval), tick)
        self._scrub_timer.daemon = True
        self._scrub_timer.start()

    def _schedule_backup(self) -> None:
        # Tick at half the backup interval so a missed coordinator
        # handoff costs at most half a cycle; the scheduler's own
        # due/backoff gating makes extra ticks free.
        def tick():
            try:
                if self._backup_gate.acquire(blocking=False):
                    try:
                        self.backup_scheduler.tick()
                    finally:
                        self._backup_gate.release()
            except Exception as e:
                # scheduler.tick never raises; belt and braces.
                self._timer_tick_error("backup", e)
            finally:
                if not self._closed:
                    self._schedule_backup()
        self._backup_timer = threading.Timer(
            self._jitter(max(0.05, self._backup_interval / 2.0)), tick)
        self._backup_timer.daemon = True
        self._backup_timer.start()

    #: membership push/pull piggybacks on every Nth liveness sweep
    #: (full-ring pulls each sweep would double detector traffic).
    DISCOVER_EVERY_N_SWEEPS = 5

    def _schedule_check_nodes(self) -> None:
        def tick():
            try:
                from pilosa_tpu.cluster.resize import check_nodes
                self._sweep_n = getattr(self, "_sweep_n", 0) + 1
                changed = check_nodes(
                    self.cluster, self.cluster.client,
                    discover=(self._sweep_n %
                              self.DISCOVER_EVERY_N_SWEEPS == 0))
                if changed:
                    self.stats.count("checkNodesChanged", len(changed))
            except Exception as e:
                # A dead failure detector is the worst silent failure:
                # DOWN peers never get marked, writes hang on them.
                self._timer_tick_error("check-nodes", e)
            finally:
                if not self._closed:
                    self._schedule_check_nodes()
        self._check_timer = threading.Timer(
            self._jitter(self._check_nodes_interval), tick)
        self._check_timer.daemon = True
        self._check_timer.start()

    def close(self) -> None:
        self._closed = True
        self._pool_stop.set()
        if self.tracer is not None:
            from pilosa_tpu.obs import NopTracer, get_tracer, set_tracer
            if get_tracer() is self.tracer:
                set_tracer(NopTracer())  # don't leave a closed exporter
            self.tracer.close()
        if self.dirty is not None:
            self.dirty.close()
        if self.cluster is not None:
            self.cluster.close()
        # Stop accepting NEW connections first; handler threads are
        # daemons and may outlive this (the batcher resolves
        # synchronously after close for exactly that race).
        self.http.close()
        if self._sync_timer is not None:
            self._sync_timer.cancel()
        if self._check_timer is not None:
            self._check_timer.cancel()
        if self._scrub_timer is not None:
            self._scrub_timer.cancel()
        if self._backup_timer is not None:
            self._backup_timer.cancel()
        if self.backup_archive is not None:
            try:
                self.backup_archive.close()
            except Exception:
                pass
        if getattr(self, "runtime_monitor", None) is not None:
            self.runtime_monitor.close()
        if self.executor.planner is not None:
            self._save_observed_traffic()
            self.executor.planner.close()
        # The compile-cache counter sink holds a reference to our stats
        # object; drop it so short-lived embedded/test nodes don't pile
        # up in the module-level sink list.
        try:
            from pilosa_tpu.parallel import compile_cache
            compile_cache.detach(self.stats)
        except Exception:
            pass
        if self.store is not None:
            self.store.close()

    @property
    def address(self) -> str:
        return self.http.address

    # -- control plane -----------------------------------------------------

    def _broadcast_shard(self, index: str, field: str, view: str, shard: int):
        if self.cluster is None:
            return
        msg = {"type": "create-shard", "index": index, "field": field,
               "shard": shard}
        for node in self.cluster.nodes:
            if node.id == self.id or node.state == "DOWN":
                continue
            try:
                self.cluster.client.send_message(node, msg)
            except (ConnectionError, RuntimeError):
                pass

    def handle_message(self, message: dict) -> None:
        t = message.get("type")
        if t == "resize-instruction" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import handle_resize_instruction
            handle_resize_instruction(self.holder, self.cluster.client,
                                      self.cluster, message, self.id)
        elif t == "resize-instruction-complete":
            from pilosa_tpu.cluster.resize import deliver_completion
            deliver_completion(message)
        elif t == "index-dirty":
            if (self.cluster is not None
                    and not self.cluster.check_fencing_token(message)):
                return  # stale coordinator's dirty coordination
            from pilosa_tpu.cluster.dirty import apply_index_dirty
            apply_index_dirty(self.holder, message,
                              self.executor.remote_epochs)
        elif t == "cluster-status" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import apply_cluster_status
            apply_cluster_status(self.cluster, message["nodes"],
                                 holder=self.holder,
                                 availability=message.get("availability"),
                                 replica_n=message.get("replicaN"),
                                 partition_n=message.get("partitionN"),
                                 version=message.get("version"))
            # Topology changed: GC fragments this node no longer owns
            # (holderCleaner, holder.go:1126) off the RPC thread.
            threading.Thread(target=self.clean_holder,
                             name="holder-cleaner", daemon=True).start()
        elif t == "cluster-state" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import apply_cluster_state
            apply_cluster_state(self.cluster, message["state"])
        elif t == "resize-begin" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import apply_resize_begin
            apply_resize_begin(self.cluster, message)
        elif t == "resize-end" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import apply_resize_end
            apply_resize_end(self.cluster, message)
        elif t == "resize-push" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import handle_resize_push
            return handle_resize_push(self.holder, self.cluster.client,
                                      self.cluster, message)
        elif t == "resize-shard-cutover":
            from pilosa_tpu.cluster.resize import deliver_cutover
            deliver_cutover(message, self.cluster)
        elif t == "resize-dual-write-failed":
            from pilosa_tpu.cluster.resize import deliver_dual_write_failed
            deliver_dual_write_failed(message)
        elif t in ("delete-index", "delete-field", "delete-view"):
            # Apply to the holder (shared handler), then unlink the
            # on-disk tree: a peer that kept the stale files would
            # resurrect the deleted data into a recreated same-name
            # index/field/view on restart.
            handle_cluster_message(self.holder, message)
            if self.store is not None:
                prefix = [message["index"]]
                if t != "delete-index":
                    prefix.append(message["field"])
                if t == "delete-view":
                    prefix.append(message["view"])
                self.store.delete_subtree_files(*prefix)
        elif t == "node-join" and self.cluster is not None:
            self.handle_join(message["addr"])
        else:
            handle_cluster_message(self.holder, message)

    def handle_join(self, addr: str) -> str:
        """A node announced itself. Non-coordinators forward; the
        coordinator runs the add-resize (stream fragments, then commit +
        broadcast the topology — the joiner learns the ring from the
        cluster-status broadcast). Reference: eventReceiver -> nodeJoin
        -> resize job (gossip/gossip.go:364, cluster.go:1796)."""
        coord = self.cluster.coordinator()
        if coord is not None and coord.id == addr:
            # The flagged coordinator is announcing itself as a JOINER:
            # its process restarted without cluster state, so the node
            # every peer would forward this join to is precisely the one
            # that cannot handle it (found by the chaos soak — a
            # leaderless wedge where the solo ex-coordinator announced
            # into a ring that kept forwarding the announce back to it).
            # Deterministic handover: the first surviving member acts,
            # takes the flag, and the commit broadcast carries it.
            survivors = sorted(
                (n for n in self.cluster.nodes if n.id != addr),
                key=lambda n: (n.state == "DOWN", n.id))  # live first
            if not survivors:
                raise RuntimeError(
                    "no surviving member to take over the join")
            coord = survivors[0]
            if coord.id == self.id:
                # The handover is a TOPOLOGY CHANGE, not a local note:
                # bump the version, persist, and broadcast, or peers
                # (whose strictly-newer gate rejects same-version
                # views) would keep forwarding joins to the stateless
                # ex-coordinator and a restart would restore its flag
                # from the old topology.json.
                with self.cluster._lock:
                    for n in self.cluster.nodes:
                        n.is_coordinator = (n.id == self.id)
                    self.cluster.topology_version += 1
                    status = {"type": "cluster-status",
                              "nodes": [n.to_json()
                                        for n in self.cluster.nodes],
                              "replicaN": self.cluster.replica_n,
                              "partitionN": self.cluster.partition_n,
                              "version": self.cluster.topology_version}
                self.cluster.notify_topology()
                for n in self.cluster.nodes:
                    if n.id != self.id and n.state != "DOWN":
                        try:
                            self.cluster.client.send_message(n, status)
                        except Exception:
                            pass  # discovery pulls converge them later
                coord = self.cluster.node_by_id(self.id)
        if coord is None:
            raise RuntimeError("no coordinator to handle join")
        if coord.id != self.id:
            self.cluster.client.send_message(
                coord, {"type": "node-join", "addr": addr})
            return "FORWARDED"
        member = self.cluster.node_by_id(addr)
        if member is not None:
            # Idempotent re-admission: a joiner that is already in OUR
            # ring but keeps announcing missed the commit broadcast (it
            # is sitting solo, and a solo node has no peers to discover
            # the ring through). Re-send the committed topology so a
            # lost commit can never wedge a member outside the ring it
            # belongs to (found by the chaos soak, seed 104).
            from pilosa_tpu.cluster.resize import holder_availability
            status = {"type": "cluster-status",
                      "nodes": [n.to_json() for n in self.cluster.nodes],
                      "replicaN": self.cluster.replica_n,
                      "partitionN": self.cluster.partition_n,
                      "version": self.cluster.topology_version,
                      "availability": holder_availability(self.holder)}
            try:
                self.cluster.client.send_message(member, status)
            except (ConnectionError, RuntimeError):
                pass
            return "ALREADY_MEMBER"
        # Run the (possibly long) data-moving resize OFF the request
        # thread: the joiner's announce would otherwise time out on big
        # transfers and its retry would race a second job. The gate
        # makes duplicate/overlapping announces no-ops.
        if self._resize_gate.locked():
            return "RESIZING"

        def run():
            try:
                self.resize("add", addr=addr)
            except (RuntimeError, ConnectionError, ValueError):
                pass  # joiner keeps announcing; next attempt retries

        threading.Thread(target=run, name="join-resize",
                         daemon=True).start()
        return "STARTED"

    def resize(self, action: str, node_id: str | None = None,
               addr: str | None = None) -> str:
        """Coordinator-driven membership change (api.go RemoveNode :1220;
        node addition = reference's join-triggered resize). ONE job at a
        time (the reference's single-job state machine,
        cluster.go:1447): a second request while one runs is rejected."""
        if self.cluster is None:
            raise RuntimeError("standalone node cannot resize")
        # Resizes RUN on the flagged coordinator: the stuck-RESIZING
        # recovery heuristic consults the coordinator's state as the
        # resize authority, so a job running anywhere else would make
        # that heuristic (a) never recover if this node died mid-job,
        # or (b) falsely reopen peer gates while the job lives.
        # Non-coordinators REFUSE with the coordinator's address, like
        # the reference (cluster.go:1870) — forwarding fire-and-forget
        # would hide failures from the operator, and divergent
        # coordinator views could ping-pong the message forever.
        coord = self.cluster.coordinator()
        if coord is not None and coord.id != self.id:
            raise RuntimeError(
                "node removal requests are only valid on the coordinator "
                f"node: {coord.id}")
        from pilosa_tpu.cluster.node import URI, Node
        from pilosa_tpu.cluster.resize import ResizeJob
        new_nodes = [Node(id=n.id, uri=n.uri, is_coordinator=n.is_coordinator)
                     for n in self.cluster.nodes]
        if action == "remove":
            new_nodes = [n for n in new_nodes if n.id != node_id]
            if new_nodes and not any(n.is_coordinator for n in new_nodes):
                # Never commit a leaderless ring (joins would have no
                # authority to land on): hand the flag to this node —
                # the one running the job — else the first LIVE
                # survivor (a dead coordinator would route every
                # join/resize at a corpse).
                keep = next(
                    (n for n in new_nodes if n.id == self.id),
                    min(new_nodes,
                        key=lambda n: (n.state == "DOWN", n.id)))
                keep.is_coordinator = True
        elif action == "add":
            h, _, p = (addr or "").partition(":")
            new_nodes.append(Node(id=addr,
                                  uri=URI(scheme=self._scheme,
                                          host=h, port=int(p))))
        else:
            raise ValueError(f"unknown resize action {action!r}")
        if not self._resize_gate.acquire(blocking=False):
            raise RuntimeError("resize already in progress")
        try:
            job = ResizeJob(self.cluster, self.holder, self.cluster.client,
                            store=self.store)
            self.api.resize_job = job
            return job.run(new_nodes)
        finally:
            self._resize_gate.release()

    def clean_holder(self) -> int:
        """holderCleaner (holder.go:1126): drop fragments this node no
        longer owns; also runs as an anti-entropy backstop."""
        if self.cluster is None:
            return 0
        from pilosa_tpu.cluster.cleaner import clean_holder
        try:
            n = clean_holder(self.holder, self.cluster, store=self.store)
        except Exception:
            return 0  # GC must never take down the node
        if n:
            self.stats.count("holderCleanerRemoved", n)
        return n

    def handle_internal_import(self, req: dict) -> None:
        """/internal/import payloads: fragment-level (anti-entropy
        diff push) or field-level (routed import). Gated by cluster
        state like the public import surface (reference api.Import
        validates on the RECEIVING node too): a forwarded write must
        not land on a RESIZING owner whose fragments are mid-move.
        internal=True: peer-forwarded writes (replica fan-out legs,
        anti-entropy pushes, dual-apply) must land even on a FENCED
        receiver — they are how a minority heals, and the SENDER's
        fence already gated the client-facing write."""
        self.api._validate("import", internal=True)
        index, field = req["index"], req["field"]
        f = self.holder.field(index, field)
        if f is None:
            raise LookupError(f"field not found: {index}/{field}")
        if req.get("kind") == "fragment":
            v = f.create_view_if_not_exists(req["view"])
            frag = v.create_fragment_if_not_exists(req["shard"])
            frag.bulk_import(req["rowIDs"], req["columnIDs"],
                             clear=req.get("clear", False))
        elif req.get("values") is not None:
            f.import_values(req["columnIDs"], req["values"],
                            clear=req.get("clear", False))
            self.holder.index(index).add_existence(req["columnIDs"])
        else:
            from pilosa_tpu.core import timequantum as tq
            ts = None
            if req.get("timestamps") is not None:
                ts = [tq.parse_time(t) if t else None
                      for t in req["timestamps"]]
            f.import_bits(req["rowIDs"], req["columnIDs"], ts,
                          clear=req.get("clear", False))
            self.holder.index(index).add_existence(req["columnIDs"])

    # -- backup / restore --------------------------------------------------

    def handle_backup(self, req: dict) -> dict:
        """POST /backup: start a cluster backup into the archive named
        in the request (directory path or object-store URL); returns
        the backup id immediately (poll /backup/status)."""
        from pilosa_tpu.backup import (
            BackupError,
            BackupWriter,
            new_backup_id,
            open_archive,
        )
        req = req or {}
        root = req.get("archive")
        if not root:
            raise BackupError(
                "backup: 'archive' (directory path or URL) is required")
        parent = req.get("parent") or None
        archive = open_archive(root, stats=self.stats)
        if parent and not archive.has_manifest(parent):
            raise BackupError(
                f"backup: parent {parent!r} not found in archive")
        if not self._backup_gate.acquire(blocking=False):
            raise BackupError("backup already in progress")
        backup_id = new_backup_id("incremental" if parent else "full")
        writer = BackupWriter(
            self.holder, self.cluster,
            self.cluster.client if self.cluster is not None else None,
            self.store, archive, stats=self.stats, admission=self.qos)
        writer.progress = {"state": "starting", "id": backup_id}
        self._backup_writer = writer

        def run():
            try:
                writer.run(backup_id=backup_id, parent=parent)
            except Exception:
                pass  # progress carries state=failed + the error text
            finally:
                self._backup_gate.release()

        threading.Thread(target=run, name="backup", daemon=True).start()
        return {"id": backup_id, "state": "started"}

    def backup_status(self) -> dict:
        w = self._backup_writer
        return dict(w.progress) if w is not None else {"state": "idle"}

    def handle_restore(self, req: dict) -> dict:
        """POST /restore: rebuild the backed-up indexes onto THIS
        cluster (any size) from the archive; returns immediately (poll
        /restore/status). ``id`` defaults to the newest complete backup;
        ``pitrOps`` caps WAL replay for point-in-time recovery."""
        import time as _time

        from pilosa_tpu.backup import (
            BackupError,
            RestoreJob,
            open_archive,
            select_backup_at,
        )
        req = req or {}
        root = req.get("archive")
        if not root:
            raise BackupError(
                "restore: 'archive' (directory path or URL) is required")
        archive = open_archive(root, stats=self.stats)
        backup_id = req.get("id")
        if not backup_id:
            m = select_backup_at(archive, _time.time())
            if m is None:
                raise BackupError(
                    "restore: no complete backup in archive")
            backup_id = m["id"]
        elif not archive.has_manifest(backup_id):
            raise BackupError(
                f"restore: backup {backup_id!r} not found in archive")
        pitr = req.get("pitrOps")
        if not self._restore_gate.acquire(blocking=False):
            raise BackupError("restore already in progress")
        job = RestoreJob(
            self.holder, self.cluster,
            self.cluster.client if self.cluster is not None else None,
            archive, backup_id, store=self.store, stats=self.stats,
            force=bool(req.get("force")),
            pitr_ops=int(pitr) if pitr is not None else None)
        job.progress = {"state": "starting", "id": backup_id}
        self._restore_job = job

        def run():
            try:
                job.run()
            except Exception:
                pass  # progress carries state=failed + the error text
            finally:
                self._restore_gate.release()

        threading.Thread(target=run, name="restore", daemon=True).start()
        return {"id": backup_id, "state": "started"}

    def restore_status(self) -> dict:
        j = self._restore_job
        return dict(j.progress) if j is not None else {"state": "idle"}

    def backup_debug(self) -> dict:
        """GET /debug/backup: the scheduler's health document, or a
        stub when unattended backups aren't configured on this node."""
        if self.backup_scheduler is None:
            return {"enabled": False}
        doc = self.backup_scheduler.status()
        doc["enabled"] = True
        doc["archive"] = self._archive_url
        return doc

    # -- warmup-from-observed-traffic --------------------------------------

    def _save_observed_traffic(self) -> None:
        """Persist the planner's observed structural query shapes (plus
        the schema they compile against) so the next boot's warmup
        precompiles what THIS node's traffic actually ran."""
        import json as _json
        import os as _os
        planner = self.executor.planner
        if not self.data_dir or planner is None:
            return
        observed = getattr(planner, "observed_traffic", lambda: [])()
        if not observed:
            return
        path = _os.path.join(self.data_dir, "warmup.json")
        try:
            tmp = f"{path}.{_os.getpid()}.tmp"
            with open(tmp, "w") as f:
                _json.dump({"version": 1, "entries": observed,
                            "schema": self.holder.schema()}, f)
            _os.replace(tmp, path)
        except OSError:
            pass  # warmup hints are best-effort; never block shutdown

    def _load_observed_traffic(self) -> tuple[list, list]:
        import json as _json
        import os as _os
        if not self.data_dir:
            return [], []
        try:
            with open(_os.path.join(self.data_dir, "warmup.json")) as f:
                doc = _json.load(f)
            return (list(doc.get("entries", [])),
                    list(doc.get("schema", [])))
        except (OSError, ValueError):
            return [], []
