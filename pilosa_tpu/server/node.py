"""ServerNode — one full pilosa-tpu node process.

Reference: server.go (Server :46 wires holder+cluster+executor,
receiveMessage :569-663) and server/server.go (Command :60, SetupServer
:222). Assembles Holder + Cluster + Executor(+MeshPlanner) + API +
HTTPServer, wires the control-plane message and import handlers, and
runs the anti-entropy ticker.
"""

from __future__ import annotations

import threading

from pilosa_tpu.cluster.cluster import STATE_NORMAL, Cluster
from pilosa_tpu.cluster.harness import handle_cluster_message
from pilosa_tpu.cluster.node import URI, Node
from pilosa_tpu.cluster.sync import HolderSyncer
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.server.api import API
from pilosa_tpu.server.httpclient import HTTPInternalClient
from pilosa_tpu.server.httpd import HTTPServer


class ServerNode:
    """A runnable node (reference `pilosa server`, cmd/server.go:64)."""

    def __init__(self, bind: str = "127.0.0.1:10101",
                 peers: list[str] | None = None,
                 replica_n: int = 1,
                 use_planner: bool = True,
                 anti_entropy_interval: float = 0.0,
                 data_dir: str | None = None):
        host, _, port = bind.partition(":")
        self.host, self.port = host or "127.0.0.1", int(port or 10101)
        # Node identity IS the address — member ids are built the same
        # way, so local_id always matches its ring entry.
        self.id = f"{self.host}:{self.port}"
        self.data_dir = data_dir

        # Membership: static peer list (the gossip-less Static:true mode,
        # cluster.go:212); each peer "host:port" becomes a Node.
        members = []
        all_addrs = sorted(set((peers or []) + [f"{self.host}:{self.port}"]))
        for i, addr in enumerate(all_addrs):
            h, _, p = addr.partition(":")
            members.append(Node(id=addr, uri=URI(host=h, port=int(p)),
                                is_coordinator=(i == 0)))
        self.cluster = None
        if len(members) > 1:
            self.cluster = Cluster(local_id=self.id, nodes=members,
                                   replica_n=replica_n,
                                   client=HTTPInternalClient())
            self.cluster.set_state(STATE_NORMAL)

        from pilosa_tpu.obs import MemoryStats
        self.stats = MemoryStats()
        self.holder = Holder(fragment_listener=self._broadcast_shard)
        planner = None
        if use_planner:
            try:
                from pilosa_tpu.parallel import MeshPlanner
                planner = MeshPlanner(self.holder)
            except Exception:
                planner = None
        self.executor = Executor(self.holder, cluster=self.cluster,
                                 node_id=self.id, planner=planner,
                                 stats=self.stats)
        self.api = API(self.holder, self.executor, cluster=self.cluster)
        # Handler hooks used by the HTTP router's /internal routes.
        self.api.message_handler = self.handle_message
        self.api.import_handler = self.handle_internal_import
        self.api.resize_handler = self.resize
        self.http = HTTPServer(self.api, self.host, self.port)
        self.port = self.http.port

        self.syncer = None
        self._sync_timer: threading.Timer | None = None
        self._anti_entropy_interval = anti_entropy_interval
        if self.cluster is not None:
            self.syncer = HolderSyncer(self.holder, self.cluster,
                                       self.cluster.client)
            # Coordinator-primary key allocation (translate.go:93 model):
            # every keyed allocation routes to the coordinator.
            from pilosa_tpu.cluster.translate_sync import ClusterKeyTranslator
            translator = ClusterKeyTranslator(self.holder, self.cluster,
                                              self.cluster.client)
            self.executor.translator = translator
            self.api.translator = translator

        if data_dir:
            from pilosa_tpu.storage.diskstore import DiskStore
            self.store = DiskStore(data_dir, self.holder)
            self.store.open()
        else:
            self.store = None

    # -- lifecycle ---------------------------------------------------------

    def open(self) -> None:
        self.http.serve_background()
        if self.syncer is not None and self._anti_entropy_interval > 0:
            self._schedule_sync()

    def _schedule_sync(self) -> None:
        def tick():
            try:
                from pilosa_tpu.cluster.translate_sync import sync_translation
                sync_translation(self.holder, self.cluster,
                                 self.cluster.client)
                self.syncer.sync_holder()
            finally:
                self._schedule_sync()
        self._sync_timer = threading.Timer(self._anti_entropy_interval, tick)
        self._sync_timer.daemon = True
        self._sync_timer.start()

    def close(self) -> None:
        if self._sync_timer is not None:
            self._sync_timer.cancel()
        if self.store is not None:
            self.store.close()
        self.http.close()

    @property
    def address(self) -> str:
        return self.http.address

    # -- control plane -----------------------------------------------------

    def _broadcast_shard(self, index: str, field: str, view: str, shard: int):
        if self.cluster is None:
            return
        msg = {"type": "create-shard", "index": index, "field": field,
               "shard": shard}
        for node in self.cluster.nodes:
            if node.id == self.id or node.state == "DOWN":
                continue
            try:
                self.cluster.client.send_message(node, msg)
            except (ConnectionError, RuntimeError):
                pass

    def handle_message(self, message: dict) -> None:
        t = message.get("type")
        if t == "resize-instruction" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import apply_resize_instruction
            apply_resize_instruction(self.holder, self.cluster.client,
                                     self.cluster, message["sources"])
        elif t == "cluster-status" and self.cluster is not None:
            from pilosa_tpu.cluster.resize import apply_cluster_status
            apply_cluster_status(self.cluster, message["nodes"],
                                 holder=self.holder,
                                 availability=message.get("availability"))
        else:
            handle_cluster_message(self.holder, message)

    def resize(self, action: str, node_id: str | None = None,
               addr: str | None = None) -> str:
        """Coordinator-driven membership change (api.go RemoveNode :1220;
        node addition = reference's join-triggered resize)."""
        if self.cluster is None:
            raise RuntimeError("standalone node cannot resize")
        from pilosa_tpu.cluster.node import URI, Node
        from pilosa_tpu.cluster.resize import ResizeJob
        new_nodes = [Node(id=n.id, uri=n.uri, is_coordinator=n.is_coordinator)
                     for n in self.cluster.nodes]
        if action == "remove":
            new_nodes = [n for n in new_nodes if n.id != node_id]
        elif action == "add":
            h, _, p = (addr or "").partition(":")
            new_nodes.append(Node(id=addr, uri=URI(host=h, port=int(p))))
        else:
            raise ValueError(f"unknown resize action {action!r}")
        job = ResizeJob(self.cluster, self.holder, self.cluster.client)
        self.api.resize_job = job
        return job.run(new_nodes)

    def handle_internal_import(self, req: dict) -> None:
        """JSON /internal/import payloads: fragment-level (anti-entropy
        diff push) or field-level (routed import)."""
        index, field = req["index"], req["field"]
        f = self.holder.field(index, field)
        if f is None:
            raise LookupError(f"field not found: {index}/{field}")
        if req.get("kind") == "fragment":
            v = f.create_view_if_not_exists(req["view"])
            frag = v.create_fragment_if_not_exists(req["shard"])
            frag.bulk_import(req["rowIDs"], req["columnIDs"],
                             clear=req.get("clear", False))
        elif req.get("values") is not None:
            f.import_values(req["columnIDs"], req["values"],
                            clear=req.get("clear", False))
            self.holder.index(index).add_existence(req["columnIDs"])
        else:
            from pilosa_tpu.core import timequantum as tq
            ts = None
            if req.get("timestamps") is not None:
                ts = [tq.parse_time(t) if t else None
                      for t in req["timestamps"]]
            f.import_bits(req["rowIDs"], req["columnIDs"], ts,
                          clear=req.get("clear", False))
            self.holder.index(index).add_existence(req["columnIDs"])
