"""HTTP layer: REST router over the API facade.

Reference: http/handler.go (newRouter :274-318 — the public
``/index/...``, ``/query``, ``/schema``, ``/status``, import/export
routes plus the ``/internal/*`` node-to-node RPC). Implemented on the
stdlib ThreadingHTTPServer — no framework dependency; JSON bodies
replace the reference's protobuf on internal routes (documented
deviation; the wire format is an implementation detail of this build).
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from pilosa_tpu.errors import (
    ApiMethodNotAllowedError,
    ClusterFencedError,
    FieldExistsError,
    FieldNotFoundError,
    FragmentNotFoundError,
    IndexExistsError,
    IndexNotFoundError,
    PilosaError,
    QueryError,
)
from pilosa_tpu.pql import ParseError
from pilosa_tpu.cache.tenant import (
    reset_current_tenant,
    set_current_tenant,
)
from pilosa_tpu.qos import (
    CLASS_BATCH,
    CLASS_INTERNAL,
    DeadlineExceededError,
    IngestBackpressureError,
    QueryShedError,
    QuotaExceededError,
    normalize_class,
)
from pilosa_tpu.obs import profile as _profile
from pilosa_tpu.qos import deadline as qos_deadline
from pilosa_tpu.server.api import API
from pilosa_tpu.cluster.cluster import ShardUnavailableError
from pilosa_tpu.storage.quarantine import ShardCorruptError

_CONFLICTS = (IndexExistsError, FieldExistsError)
_NOT_FOUND = (IndexNotFoundError, FieldNotFoundError, FragmentNotFoundError)


class _Server(ThreadingHTTPServer):
    """TLS wraps PER CONNECTION with a deferred handshake: wrapping the
    listening socket would run every handshake inside the single accept
    loop, letting one silent client block the whole server."""

    ssl_ctx = None

    def get_request(self):
        sock, addr = self.socket.accept()
        if self.ssl_ctx is not None:
            sock = self.ssl_ctx.wrap_socket(sock, server_side=True,
                                            do_handshake_on_connect=False)
        return sock, addr


class HTTPServer:
    """One node's HTTP front end (reference http/handler.go:46).

    ``tls_cert``/``tls_key`` wrap the listener in TLS (the reference's
    server/tlsconfig.go; `https://` scheme in .address)."""

    def __init__(self, api: API, host: str = "127.0.0.1", port: int = 10101,
                 tls_cert: str | None = None, tls_key: str | None = None):
        self.api = api
        self.host = host
        self.port = port
        if bool(tls_cert) != bool(tls_key):
            # A half-specified TLS config must never silently serve
            # plaintext while the operator believes TLS is on.
            raise ValueError("tls_cert and tls_key must be set together")
        self.tls = bool(tls_cert)
        # Load the cert BEFORE binding: a bad path must not leak a
        # bound listening socket (retrying supervisors get EADDRINUSE).
        ctx = None
        if tls_cert:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
        handler = _make_handler(api)
        self._httpd = _Server((host, port), handler)
        self._httpd.ssl_ctx = ctx
        self.port = self._httpd.server_address[1]  # resolved if port=0
        self._thread: threading.Thread | None = None

    def serve_background(self) -> None:
        self._serving = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        self._serving = True
        self._httpd.serve_forever()

    def close(self) -> None:
        # socketserver.shutdown() BLOCKS forever if serve_forever never
        # ran (it waits on the flag only the serve loop sets) — closing
        # a constructed-but-never-opened server must not hang.
        if getattr(self, "_serving", False):
            self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def address(self) -> str:
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.host}:{self.port}"


def _make_handler(api: API):
    routes = _build_routes(api)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Nagle + delayed-ACK costs ~40ms per small response (status
        # line, headers, and body are separate writes); node-to-node
        # RPC and every latency-sensitive client pays it otherwise.
        disable_nagle_algorithm = True
        # Bound how long a silent/stalled connection (incl. a deferred
        # TLS handshake) can pin a handler thread.
        timeout = 120

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _dispatch(self, method: str):
            parsed = urlparse(self.path)
            params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            params["_accept"] = self.headers.get("Accept", "")
            params["_qos_class"] = self.headers.get("X-Qos-Class", "")
            params["_api_key"] = self.headers.get("X-API-Key", "")
            if method == "POST" and parsed.path == "/internal/import-stream":
                # Streaming route: decode/apply PER CHUNK while the
                # client is still sending — must run before the
                # whole-body read below.
                return self._handle_import_stream()
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            for pattern, methods in routes:
                m = pattern.match(parsed.path)
                if not m:
                    continue
                fn = methods.get(method)
                if fn is None:
                    continue
                headers = None
                # Join a propagated cross-node trace and deadline.
                from pilosa_tpu.obs import tracing as _tr
                tid = _tr.extract_http_headers(self.headers)
                token = _tr.set_current_trace(tid) if tid else None
                dl = qos_deadline.extract_http_headers(self.headers)
                dtoken = (qos_deadline.set_current_deadline(dl)
                          if dl is not None else None)
                try:
                    out = fn(m.groupdict(), params, body)
                    if len(out) == 3:  # optional extra response headers
                        status, payload, headers = out
                    else:
                        status, payload = out
                except QueryShedError as e:
                    # Load shed: tell the client when to come back
                    # instead of queueing unboundedly.
                    status, payload = 503, {"error": str(e)}
                    headers = {"Retry-After": str(int(e.retry_after))}
                except QuotaExceededError as e:
                    # 429, NOT 503: the TENANT is over its own budget —
                    # the node is fine, so retrying a replica won't help;
                    # slowing down will.
                    status, payload = 429, {"error": str(e)}
                    headers = {"Retry-After":
                               str(max(1, int(e.retry_after + 0.5)))}
                except IngestBackpressureError as e:
                    # Same shape as the quota trip: the import stream
                    # must slow down, the node is otherwise healthy.
                    status, payload = 429, {"error": str(e)}
                    headers = {"Retry-After":
                               str(max(1, int(e.retry_after + 0.5)))}
                except DeadlineExceededError as e:
                    status, payload = 504, {"error": str(e)}
                except _CONFLICTS as e:
                    status, payload = 409, {"error": str(e)}
                except _NOT_FOUND as e:
                    status, payload = 404, {"error": str(e)}
                except ApiMethodNotAllowedError as e:
                    # 405, NOT 400: import clients treat a 400 as "peer
                    # doesn't speak the binary frame format" and re-send
                    # as JSON — a state-gated refusal must stay distinct.
                    status, payload = 405, {"error": str(e)}
                except ShardCorruptError as e:
                    # 503, NOT 400 (must precede the PilosaError
                    # catch-all): the data exists but this node's copy is
                    # quarantined — a server-side condition a replica or
                    # the scrubber will clear, not a bad request.
                    status, payload = 503, {"error": str(e)}
                except ClusterFencedError as e:
                    # 503 + Retry-After (also before the catch-all): the
                    # node fenced itself off a minority partition —
                    # retry-able server-side unavailability, same family
                    # as load shed, NOT a client error.
                    status, payload = 503, {"error": str(e)}
                    headers = {"Retry-After": str(int(e.retry_after))}
                except ShardUnavailableError as e:
                    # Every live owner of some shard is unreachable from
                    # here — transient membership trouble (a partition
                    # the failure detector hasn't fenced yet), not a bad
                    # request: retryable 503, same family as fenced.
                    status, payload = 503, {"error": str(e)}
                    headers = {"Retry-After": "1"}
                except (QueryError, ParseError, ValueError, PilosaError) as e:
                    status, payload = 400, {"error": str(e)}
                except Exception as e:  # pragma: no cover
                    status, payload = 500, {"error": f"internal: {e}"}
                finally:
                    if dtoken is not None:
                        qos_deadline.reset_current_deadline(dtoken)
                    if token is not None:
                        _tr.reset_current_trace(token)
                return self._reply(status, payload, headers)
            return self._reply(404, {"error": "not found"})

        def _handle_import_stream(self):
            """POST /internal/import-stream: length-prefixed PTI1 frames
            (wire.STREAM_CONTENT_TYPE), applied as they arrive — decode,
            WAL append (group-committed), device upload per chunk. Bulk
            work rides the BATCH admission class so interactive queries
            keep their weighted share of the node. On backpressure (the
            ingest gate's byte budget, an admission shed, or a tenant
            quota) the server STOPS APPLYING but keeps draining the
            stream, then answers 429 + Retry-After + how many chunks
            were applied — replying mid-send would just break the pipe
            and mask the signal; the client resumes from ``applied``."""
            from pilosa_tpu.server import wire

            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                read = _chunked_body_reader(self.rfile)
            else:
                read = _bounded_body_reader(
                    self.rfile, int(self.headers.get("Content-Length") or 0))
            server = getattr(api, "import_handler", None)
            if server is None:
                self.close_connection = True
                return self._reply(400, {"error": "no import handler"})
            qos_ctl = getattr(api, "qos", None)
            gate = getattr(api, "ingest_gate", None)
            # Raw route (bypasses _dispatch's params): read the QoS
            # class header directly. Resize fragment migration streams
            # as "internal" so it never starves interactive traffic;
            # user bulk loads stay BATCH.
            hdr = self.headers.get("X-Qos-Class") or ""
            cls = normalize_class(hdr) if hdr else CLASS_BATCH
            applied = 0
            pressure = None
            fatal = None
            try:
                for frame in wire.iter_stream_frames(read):
                    if pressure is not None or fatal is not None:
                        continue  # draining: count nothing, apply nothing
                    try:
                        if gate is not None:
                            with gate.admit(len(frame)):
                                self._apply_import_chunk(
                                    wire.decode_import(frame), server,
                                    qos_ctl, cls)
                        else:
                            self._apply_import_chunk(
                                wire.decode_import(frame), server, qos_ctl,
                                cls)
                        applied += 1
                    except (IngestBackpressureError, QueryShedError,
                            QuotaExceededError) as e:
                        pressure = e
                    except Exception as e:  # bad chunk: drain, then report
                        fatal = e
            except ValueError as e:
                # Malformed stream framing: the tail is unreadable, so
                # the connection can't be reused.
                self.close_connection = True
                return self._reply(400, {"error": str(e),
                                         "applied": applied})
            if fatal is not None:
                status = 404 if isinstance(fatal, _NOT_FOUND + (LookupError,)) \
                    else 400 if isinstance(fatal, (ValueError, KeyError,
                                                   PilosaError)) else 500
                return self._reply(status, {"error": str(fatal),
                                            "applied": applied})
            if pressure is not None:
                return self._reply(
                    429, {"error": str(pressure), "applied": applied},
                    {"Retry-After":
                     str(max(1, int(pressure.retry_after + 0.5)))})
            return self._reply(200, {"applied": applied})

        def _apply_import_chunk(self, req, server, qos_ctl,
                                cls=CLASS_BATCH):
            if qos_ctl is not None:
                with qos_ctl.admit(cls):
                    server(req)
            else:
                server(req)

        def _reply(self, status: int, payload, headers=None):
            if isinstance(payload, (dict, list)):
                data = (json.dumps(payload) + "\n").encode()
                ctype = "application/json"
            elif isinstance(payload, bytes):
                data = payload
                ctype = "application/octet-stream"
            else:
                data = str(payload).encode()
                ctype = "text/plain"
            if headers and "Content-Type" in headers:
                headers = dict(headers)
                ctype = headers.pop("Content-Type")
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def do_DELETE(self):
            self._dispatch("DELETE")

    return Handler


def _bounded_body_reader(rfile, length: int):
    """read(n) over a Content-Length body that never reads past it (the
    socket would block waiting for bytes that aren't coming)."""
    remaining = [length]

    def read(n: int) -> bytes:
        if remaining[0] <= 0:
            return b""
        b = rfile.read(min(n, remaining[0]))
        remaining[0] -= len(b)
        return b

    return read


def _chunked_body_reader(rfile):
    """read(n) over a chunked transfer-encoded body (hex-length lines,
    RFC 9112 §7.1) — what http.client sends for an iterator body, which
    is how the import client pipelines an unbounded stream."""
    state = {"left": 0, "eof": False}

    def read(n: int) -> bytes:
        if state["eof"]:
            return b""
        if state["left"] == 0:
            line = rfile.readline(130)
            if not line:
                state["eof"] = True
                return b""
            try:
                size = int(line.split(b";")[0].strip() or b"0", 16)
            except ValueError:
                state["eof"] = True
                return b""
            if size == 0:
                # consume optional trailers up to the blank line
                while True:
                    t = rfile.readline(1024)
                    if not t or t in (b"\r\n", b"\n"):
                        break
                state["eof"] = True
                return b""
            state["left"] = size
        b = rfile.read(min(n, state["left"]))
        state["left"] -= len(b)
        if state["left"] == 0:
            rfile.read(2)  # chunk-terminating CRLF
        return b

    return read


def _build_routes(api: API):
    """[(compiled_pattern, {method: fn(path_vars, params, body)})] in
    reference route order (http/handler.go:276-318)."""

    def jbody(body: bytes) -> dict:
        if not body:
            return {}
        return json.loads(body)

    def home(pv, params, body):
        return 200, "pilosa-tpu: a TPU-native distributed bitmap index\n"

    def get_indexes(pv, params, body):
        return 200, {"indexes": api.schema()}

    def post_index(pv, params, body):
        opts = jbody(body).get("options", {})
        api.create_index(pv["index"], opts)
        return 200, {}

    def get_index(pv, params, body):
        return 200, api.index_info(pv["index"])

    def delete_index(pv, params, body):
        api.delete_index(pv["index"])
        return 200, {}

    def post_field(pv, params, body):
        opts = jbody(body).get("options", {})
        api.create_field(pv["index"], pv["field"], opts)
        return 200, {}

    def delete_field(pv, params, body):
        api.delete_field(pv["index"], pv["field"])
        return 200, {}

    def post_import(pv, params, body):
        req = jbody(body)
        clear = params.get("clear") in ("1", "true")
        # A typo'd payload (wrong key names) must 400, not silently
        # import nothing (reference: proto unmarshal rejects unknown
        # shapes before api.Import runs, http/handler.go import route).
        known = {"values", "columnIDs", "columnKeys", "rowIDs", "rowKeys",
                 "timestamps"}
        if not (known & req.keys()):
            raise QueryError(
                "import payload needs rowIDs/columnIDs (or values)")
        if "values" in req:
            api.import_values(pv["index"], pv["field"],
                              req.get("columnIDs") or [],
                              req["values"],
                              column_keys=req.get("columnKeys"),
                              clear=clear)
        else:
            api.import_bits(pv["index"], pv["field"],
                            req.get("rowIDs") or [],
                            req.get("columnIDs") or [],
                            timestamps=req.get("timestamps"),
                            row_keys=req.get("rowKeys"),
                            column_keys=req.get("columnKeys"),
                            clear=clear)
        return 200, {}

    def post_import_roaring(pv, params, body):
        # remote=true marks a forwarded replica write: apply locally only.
        if params.get("remote") == "true":
            f = api.holder.field(pv["index"], pv["field"])
            if f is None:
                raise FieldNotFoundError()
            f.import_roaring(int(pv["shard"]), body,
                             clear=params.get("clear") == "true")
        else:
            api.import_roaring(pv["index"], pv["field"], int(pv["shard"]),
                               body, clear=params.get("clear") == "true")
        return 200, {}

    def post_query(pv, params, body):
        shards = None
        if params.get("shards"):
            shards = [int(s) for s in params["shards"].split(",")]
        from pilosa_tpu.server import wire
        remote = params.get("remote") == "true"
        # v2 frames carry aggregate results (TopN pairs, GroupBy tables,
        # rowid lists) as typed array blobs too; a v1 client's bare
        # content type gets the v1 layout (JSON aggregate metas), so a
        # mixed-version cluster keeps interoperating.
        accept = params.get("_accept", "")
        frames: int | bool = False
        if remote and wire.FRAMES_CONTENT_TYPE in accept:
            frames = 2 if "v=2" in accept else True
        # QoS front: classify, apply the node default deadline when the
        # client sent none, gate on admission, and feed the slow log.
        # Shed/deadline errors propagate to _dispatch's 503/504 mapping.
        qos_ctl = getattr(api, "qos", None)
        cls = normalize_class(params.get("qosClass")
                              or params.get("_qos_class"), remote=remote)
        dtoken = None
        if (qos_ctl is not None and qos_ctl.default_deadline > 0
                and qos_deadline.current_deadline() is None):
            dtoken = qos_deadline.set_current_deadline(
                qos_deadline.Deadline(timeout=qos_ctl.default_deadline))
        # Chaos fault hook: a "slow peer" serves every query late but
        # stays alive to membership probes (gray failure; the breaker
        # and hedge layer, not the failure detector, must route around
        # it). Set via POST /internal/fault.
        fault_slow = getattr(api, "fault_slow_s", 0.0)
        if fault_slow > 0:
            time.sleep(fault_slow)
        # Result-cache gate: noCache bypasses explicitly; non-remote
        # INTERNAL-class requests (backups, maintenance sweeps) must not
        # churn interactive tenants' partitions. Remote fan-out legs
        # keep caching — per-node caches are what make repeated
        # cluster dashboards cheap. An explicitly profiled query is
        # exempt too: a cache hit would profile the lookup, not the
        # cost the caller asked to see.
        want_inline_profile = params.get("profile") == "true"
        use_cache = (params.get("noCache") != "true"
                     and not want_inline_profile
                     and (remote or cls != CLASS_INTERNAL))
        # Tenant partition: same identity the quota table charges
        # (X-API-Key, falling back to the index name). Remote legs run
        # under the default tenant — the coordinator already attributed
        # the query once.
        ttoken = set_current_tenant(
            "" if remote else (params.get("_api_key") or pv["index"]))
        # Per-query cost profile: armed by ?profile=true (rides inline
        # in the response; on remote legs api.query sends it home in the
        # frames header) or by the node's always-on slowest-N retention
        # ring. A query with neither pays one dict lookup here and a
        # None contextvar read per downstream hook.
        from pilosa_tpu.obs import tracing as _tr
        ring = getattr(api, "profile_ring", None)
        want_profile = want_inline_profile or (
            not remote and ring is not None
            and getattr(api, "profile_default", True))
        prof = None
        ptoken = trace_token = None
        prof_doc = None
        if want_profile:
            tid = _tr.current_trace_id()
            if not tid:
                tid = _tr.new_trace_id()
                trace_token = _tr.set_current_trace(tid)
            cluster = getattr(api, "cluster", None)
            node_id = (cluster.local_id if cluster is not None
                       else getattr(getattr(api, "local_node", None),
                                    "id", "") or "standalone")
            prof = _profile.QueryProfile(
                tid, query=body.decode(errors="replace"),
                index=pv["index"], node=node_id, qos_class=cls,
                remote=remote)
            ptoken = _profile.activate(prof)
        status = "ok"
        t0 = time.perf_counter()
        try:
            try:
                # An already-expired deadline 504s even when the answer
                # would come free from the query cache: the client has
                # abandoned the request, and answering 200 here would
                # make expiry behavior depend on cache residency.
                qos_deadline.check_current()
                # Per-tenant quota BEFORE admission: an over-budget
                # tenant must not occupy a queue slot. Remote fan-out
                # legs are exempt — the coordinator already charged the
                # tenant once.
                quotas = getattr(api, "quotas", None)
                if quotas is not None and not remote:
                    quotas.check(params.get("_api_key") or pv["index"])
                if qos_ctl is not None:
                    with qos_ctl.admit(cls):
                        resp = api.query(
                            pv["index"], body.decode(),
                            shards=shards,
                            column_attrs=params.get("columnAttrs") == "true",
                            exclude_row_attrs=params.get(
                                "excludeRowAttrs") == "true",
                            exclude_columns=params.get(
                                "excludeColumns") == "true",
                            remote=remote, accept_frames=frames,
                            cache=use_cache)
                else:
                    resp = api.query(
                        pv["index"], body.decode(),
                        shards=shards,
                        column_attrs=params.get("columnAttrs") == "true",
                        exclude_row_attrs=params.get(
                            "excludeRowAttrs") == "true",
                        exclude_columns=params.get(
                            "excludeColumns") == "true",
                        remote=remote, accept_frames=frames,
                        cache=use_cache)
            except _NOT_FOUND + (ApiMethodNotAllowedError,):
                status = "error"
                raise
            except (QueryShedError, DeadlineExceededError,
                    QuotaExceededError) as e:
                if isinstance(e, QueryShedError):
                    status = "shed"
                elif isinstance(e, QuotaExceededError):
                    status = "quota"
                else:
                    status = "deadline"
                raise
            except ShardCorruptError:
                # Re-raise past the PilosaError catch: the dispatch
                # ladder maps this to 503 (quarantined, not a bad query).
                status = "error"
                raise
            except (ClusterFencedError, ShardUnavailableError):
                # Also past the PilosaError catch: the dispatch ladder
                # maps both to 503 + Retry-After (partition-era server
                # unavailability, not a bad query).
                status = "shed"
                raise
            except (QueryError, ParseError, PilosaError, ValueError) as e:
                status = "error"
                return 400, {"error": str(e)}
        finally:
            reset_current_tenant(ttoken)
            if dtoken is not None:
                qos_deadline.reset_current_deadline(dtoken)
            from pilosa_tpu.exec import fuse as _fuse
            if prof is not None:
                _profile.deactivate(ptoken)
                if trace_token is not None:
                    _tr.reset_current_trace(trace_token)
                prof.status = status
                prof.fused_steps = _fuse.fused_steps()
                if not remote:
                    # Remote legs already shipped their ledger home in
                    # the response header (api.query); the coordinator's
                    # ring is the retention point for the whole timeline.
                    prof_doc = prof.finish()
                    if ring is not None:
                        ring.record(prof_doc)
            _stats = getattr(api.executor, "stats", None)
            if (_stats is not None and not remote
                    and status not in ("shed", "quota")):
                # Per-QoS-class service latency (admission wait +
                # execution), exemplar'd with the active trace id —
                # the histogram SLO reports read per-class p50/p99/p999
                # from. Shed/quota rejections never executed, so they
                # don't belong in a service-time distribution; remote
                # legs are the coordinator's cost, counted there.
                _stats.with_tags(f"class:{cls}").timing(
                    "qos.serviceSeconds", time.perf_counter() - t0)
            slow_log = getattr(qos_ctl, "slow_log", None)
            if slow_log is not None and status not in ("shed", "quota"):
                slow_log.observe(pv["index"], body.decode(errors="replace"),
                                 (time.perf_counter() - t0) * 1000.0,
                                 qos_class=cls, status=status,
                                 fused_steps=_fuse.fused_steps(),
                                 trace_id=(prof.trace_id
                                           if prof is not None else ""))
        if isinstance(resp, bytes):
            return 200, resp, {"Content-Type": wire.FRAMES_CONTENT_TYPE}
        if want_inline_profile and prof_doc is not None \
                and isinstance(resp, dict):
            resp["profile"] = prof_doc
        return 200, resp

    def post_query_mux(pv, params, body):
        """Multiplexed peer-leg batch (POST /internal/query-mux): one
        request carrying N independent query legs, answered with N
        binary frames (wire.encode_mux_response). Transport failures
        stay whole-request; everything application-level — shed,
        deadline, quarantine, missing index, parse error — is a per-leg
        outcome inside the envelope, so one sick leg never poisons its
        batch-mates. Each leg restores its own trace id and deadline
        from the envelope: the batch rides one handler thread, but the
        legs may belong to different coordinator queries."""
        from pilosa_tpu.obs import tracing as _tr
        from pilosa_tpu.server import wire
        legs = wire.decode_mux_request(body)  # ValueError -> 400
        qos_ctl = getattr(api, "qos", None)
        cls = normalize_class("", remote=True)
        fault_slow = getattr(api, "fault_slow_s", 0.0)
        outcomes: list[dict] = []
        for leg in legs:
            token = None
            trace = leg.get("trace")
            if trace:
                token = _tr.set_current_trace(trace)
            tms = leg.get("timeoutMs")
            if tms is not None:
                dl = qos_deadline.Deadline(timeout=float(tms) / 1000.0)
            elif qos_ctl is not None and qos_ctl.default_deadline > 0:
                dl = qos_deadline.Deadline(timeout=qos_ctl.default_deadline)
            else:
                dl = None
            dtoken = (qos_deadline.set_current_deadline(dl)
                      if dl is not None else None)
            # Remote legs run under the default tenant — the
            # coordinator already attributed the query once.
            ttoken = set_current_tenant("")
            # A profiled leg ledgers this node's own costs; api.query
            # ships the finished doc home in the leg's frames header.
            # Same cache exemption as ?profile=true on the per-query
            # path: the coordinator asked to see the real cost.
            ptoken = None
            use_cache = not leg.get("profile")
            if leg.get("profile"):
                cluster = getattr(api, "cluster", None)
                node_id = (cluster.local_id if cluster is not None
                           else "standalone")
                ptoken = _profile.activate(_profile.QueryProfile(
                    trace or "", query=leg["query"], index=leg["index"],
                    node=node_id, qos_class=cls, remote=True))
            try:
                if fault_slow > 0:
                    time.sleep(fault_slow)
                qos_deadline.check_current()
                if qos_ctl is not None:
                    with qos_ctl.admit(cls):
                        frame = api.query(
                            leg["index"], leg["query"],
                            shards=leg.get("shards"),
                            remote=True, accept_frames=2,
                            cache=use_cache)
                else:
                    frame = api.query(
                        leg["index"], leg["query"],
                        shards=leg.get("shards"),
                        remote=True, accept_frames=2, cache=use_cache)
                outcomes.append({"frame": frame})
            except QueryShedError as e:
                outcomes.append({"status": 503, "error": str(e),
                                 "retryAfter": float(e.retry_after)})
            except ShardCorruptError as e:
                # str() carries "quarantined" — the client's typed
                # ShardCorruptError mapping keys on it, same as the
                # per-query path's 503 body.
                outcomes.append({"status": 503, "error": str(e)})
            except DeadlineExceededError as e:
                outcomes.append({"status": 504, "error": str(e)})
            except _NOT_FOUND as e:
                outcomes.append({"status": 404, "error": str(e)})
            except (QueryError, ParseError, ValueError, PilosaError) as e:
                outcomes.append({"status": 400, "error": str(e)})
            finally:
                if ptoken is not None:
                    _profile.deactivate(ptoken)
                reset_current_tenant(ttoken)
                if dtoken is not None:
                    qos_deadline.reset_current_deadline(dtoken)
                if token is not None:
                    _tr.reset_current_trace(token)
        return (200, wire.encode_mux_response(outcomes),
                {"Content-Type": wire.MUX_CONTENT_TYPE})

    def get_export(pv, params, body):
        csv = api.export_csv(params["index"], params["field"],
                             int(params["shard"]))
        return 200, csv

    def get_schema(pv, params, body):
        return 200, {"indexes": api.schema()}

    def post_schema(pv, params, body):
        api.apply_schema(jbody(body).get("indexes", []),
                         remote=params.get("remote") == "true")
        return 200, {}

    def get_status(pv, params, body):
        return 200, api.status()

    def get_info(pv, params, body):
        return 200, api.info()

    def get_version(pv, params, body):
        return 200, {"version": api.info()["version"]}

    def get_metrics(pv, params, body):
        from pilosa_tpu.obs import MemoryStats, prometheus_text
        stats = getattr(api.executor, "stats", None)
        if isinstance(stats, MemoryStats):
            return 200, prometheus_text(stats)
        return 200, "# no stats backend configured\n"

    def get_debug_vars(pv, params, body):
        """expvar analog (reference /debug/vars, http/handler.go:281):
        raw counters/gauges as JSON."""
        from pilosa_tpu.obs import MemoryStats
        stats = getattr(api.executor, "stats", None)
        if not isinstance(stats, MemoryStats):
            return 200, {}
        with stats._lock:
            return 200, {
                "counters": {f"{n}{list(t) or ''}": v
                             for (n, t), v in sorted(stats.counters.items())},
                "gauges": {f"{n}{list(t) or ''}": v
                           for (n, t), v in sorted(stats.gauges.items())},
            }

    def get_debug_slow_queries(pv, params, body):
        """The QoS slow-query ring plus an admission snapshot — the
        first stop when a node's latency goes sideways."""
        qos_ctl = getattr(api, "qos", None)
        if qos_ctl is None:
            return 200, {"queries": [], "admission": None}
        slow_log = getattr(qos_ctl, "slow_log", None)
        return 200, {
            "queries": slow_log.entries() if slow_log is not None else [],
            "thresholdMs": (slow_log.threshold_ms
                            if slow_log is not None else None),
            "admission": qos_ctl.snapshot(),
        }

    def get_debug_queries(pv, params, body):
        """Slowest-N retained query profiles (obs.profile.ProfileRing),
        slowest first — the place to go when the slow-query log names a
        trace id and you want the full cost breakdown."""
        ring = getattr(api, "profile_ring", None)
        if ring is None:
            return 200, {"queries": [], "capacity": 0}
        return 200, {"queries": ring.snapshot(), "capacity": ring.capacity}

    def get_debug_query_profile(pv, params, body):
        """One retained profile by trace id — the target of /metrics
        exemplars and slow-query-log ``profile`` pointers.

        Remote fan-out legs never record into the serving node's ring
        (the coordinator retains the whole nested ledger), so a trace
        id scraped off a *remote* node's exemplars would 404 there. On
        a local miss, ask the peers — whichever node coordinated the
        query answers with the full nested profile. ``local=true``
        bounds the search to one hop.
        """
        ring = getattr(api, "profile_ring", None)
        doc = ring.get(pv["trace"]) if ring is not None else None
        if doc is None and params.get("local") != "true":
            doc = _peer_query_profile(pv["trace"])
        if doc is None:
            return 404, {"error": f"no retained profile for {pv['trace']}"}
        return 200, doc

    def _peer_query_profile(trace):
        cluster = getattr(api, "cluster", None)
        if cluster is None:
            return None
        fetch = getattr(getattr(cluster, "client", None),
                        "debug_query_profile", None)
        if fetch is None:
            return None
        me = cluster.local_node
        best = None
        for node in list(cluster.nodes):
            if (me is not None and node.id == me.id) or node.state == "DOWN":
                continue
            try:
                doc = fetch(node, trace)
            except Exception:
                continue
            if not doc:
                continue
            # Prefer the coordinator's copy: it nests every remote leg.
            if best is None or (doc.get("remoteLegs")
                                and not best.get("remoteLegs")):
                best = doc
        return best

    def get_debug_device(pv, params, body):
        """Device telemetry in one view: plane-stack residency bytes and
        generation/eviction/upload counters, compile-cache hits, the
        coalescer's batch-width histogram and queue depth, and the
        TransferBatcher's wave widths and inline-steal count."""
        planner = getattr(api.executor, "planner", None)
        if planner is None or not hasattr(planner, "device_debug"):
            return 200, {"enabled": False}
        out = planner.device_debug()
        out["enabled"] = True
        return 200, out

    def get_debug_translate(pv, params, body):
        """Key-translation telemetry: the device key-plane cache
        (builds, device batches, collision-bucket hits, stale serves,
        async rebuilds) plus per-store sizes and watermarks — the first
        stop when the keyed leg trails the id legs."""
        planes = getattr(api.executor, "keyplanes", None)
        stores = {}
        for name in api.holder.index_names():
            idx = api.holder.index(name)
            if idx is None:
                continue
            targets = [("", idx.translate_store)]
            targets += [(fname, f.translate_store)
                        for fname, f in sorted(idx.fields.items())]
            for fname, store in targets:
                if store.max_id() == 0:
                    continue
                stores[f"{name}/{fname}" if fname else name] = {
                    "maxId": store.max_id(),
                    "watermark": store.replication_watermark(),
                    "version": store.version,
                }
        coord = None
        if api.cluster is not None:
            c = api.cluster.coordinator()
            coord = (c is not None and c.id == api.cluster.local_id)
        return 200, {
            "coordinator": coord,
            "planes": planes.debug() if planes is not None else None,
            "stores": stores,
        }

    def get_debug_overload(pv, params, body):
        """One view of the whole overload-resilience layer: adaptive
        admission limit, per-tenant quota buckets, per-peer breaker
        states, and the hedge budget — the first stop when the cluster
        is shedding or routing around a sick peer."""
        qos_ctl = getattr(api, "qos", None)
        quotas = getattr(api, "quotas", None)
        cluster = getattr(api, "cluster", None)
        breakers = None
        hedge = None
        if cluster is not None:
            breakers = getattr(cluster.client, "breakers", None)
            hedge = getattr(cluster, "hedge", None)
        rcache = getattr(api.executor, "result_cache", None)
        return 200, {
            "admission": qos_ctl.snapshot() if qos_ctl is not None else None,
            "adaptive": (qos_ctl.adaptive.snapshot()
                         if qos_ctl is not None
                         and qos_ctl.adaptive is not None else None),
            "quotas": quotas.snapshot() if quotas is not None else None,
            "breakers": breakers.snapshot() if breakers is not None else None,
            "hedge": hedge.snapshot() if hedge is not None else None,
            # Cache occupancy next to quota state: a tenant whose quota
            # looks idle but whose partition is huge is serving from
            # cache — the two views only make sense together.
            "cache": rcache.snapshot() if rcache is not None else None,
        }

    def get_debug_membership(pv, params, body):
        """One document for 'what does THIS node think of the ring':
        per-peer state with the failure detector's last probe outcome
        and indirect-probe verdicts, per-peer breaker state, and the
        quorum-fence status — the first stop when a partition drill (or
        a real one) leaves nodes disagreeing about who is alive."""
        cluster = getattr(api, "cluster", None)
        if cluster is None:
            return 200, {"cluster": False}
        breakers = getattr(cluster.client, "breakers", None)
        bpeers = (breakers.snapshot().get("peers", {})
                  if breakers is not None else {})
        log = getattr(cluster, "membership_log", {}) or {}
        peers = []
        for n in list(cluster.nodes):
            obs = log.get(n.id, {})
            peers.append({
                "id": n.id,
                "state": n.state,
                "isCoordinator": bool(n.is_coordinator),
                "self": n.id == cluster.local_id,
                "lastProbeOk": obs.get("lastProbeOk"),
                "lastProbeDirect": obs.get("lastProbeDirect"),
                "lastProbeEpoch": obs.get("lastProbeAt"),
                "indirect": obs.get("indirect", {}),
                "breaker": bpeers.get(n.id),
            })
        faults = getattr(cluster.client, "faults", None)
        return 200, {
            "cluster": True,
            "localId": cluster.local_id,
            "state": cluster.state,
            "topologyVersion": cluster.topology_version,
            "fenced": bool(getattr(cluster, "fenced", False)),
            "fenceStaleReads": bool(getattr(cluster, "fence_stale_reads",
                                            False)),
            "fencingToken": cluster.fencing_token(),
            "injectedFaults": (faults.snapshot()
                               if faults is not None else {}),
            "peers": peers,
        }

    def get_debug_cache(pv, params, body):
        """Result-cache snapshot: global byte/entry occupancy, hit and
        eviction counters, per-tenant partition sizes, and the remote
        epoch observations backing cross-node stamps."""
        rcache = getattr(api.executor, "result_cache", None)
        remotes = getattr(api.executor, "remote_epochs", None)
        if rcache is None:
            return 200, {"enabled": False}
        snap = rcache.snapshot()
        snap["enabled"] = True
        if remotes is not None:
            snap["remoteEpochs"] = remotes.snapshot()
        return 200, snap

    def post_fault(pv, params, body):
        """Chaos fault injection. {"slowMs": N} delays every subsequent
        /query on this node by N ms (0 heals); {"partition": {"peers":
        [...ids...], "mode": "drop"|"timeout", "delayMs": N}} cuts this
        node's OUTBOUND links to the named peers (asymmetric by
        construction — the chaos driver faults both sides for a
        symmetric split); {"healPartition": true} clears every link
        fault. Only mounted when the node was started with chaos faults
        enabled (--chaos-faults / PILOSA_TPU_CHAOS_FAULTS) — a
        one-request degradation lever must not ship armed."""
        req = jbody(body)
        if "slowMs" in req:
            api.fault_slow_s = max(0.0, float(req["slowMs"]) / 1000.0)
        cluster = getattr(api, "cluster", None)
        faults = (getattr(cluster.client, "faults", None)
                  if cluster is not None else None)
        part = req.get("partition")
        if part is not None or req.get("healPartition"):
            if faults is None:
                return 400, {"error": "node has no partition fault table "
                                      "(standalone?)"}
            if req.get("healPartition"):
                faults.clear()
            if part is not None:
                mode = part.get("mode", "drop")
                delay_s = float(part.get("delayMs", 0.0)) / 1000.0
                for peer in part.get("peers", []):
                    faults.set_fault(str(peer), mode=mode, delay_s=delay_s)
        return 200, {"slowMs": getattr(api, "fault_slow_s", 0.0) * 1000.0,
                     "partition": (faults.snapshot()
                                   if faults is not None else {})}

    def get_debug_quarantine(pv, params, body):
        """Corruption quarantine view: which fragments failed integrity
        verification, their serving state, and the preserved evidence
        files (`*.quarantine`)."""
        store = getattr(api, "store", None)
        q = getattr(store, "quarantine", None) if store is not None else None
        if q is None:
            return 200, {"entries": [], "count": 0}
        entries = q.entries()
        return 200, {"entries": entries, "count": len(entries)}

    def get_debug_threads(pv, params, body):
        """Thread stack dump — the pprof-goroutine analog for diagnosing
        a stuck node (reference /debug/pprof, http/handler.go:281)."""
        import sys
        import traceback
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for tid, frame in frames.items():
            out.append(f"--- {names.get(tid, '?')} ({tid}) ---\n"
                       + "".join(traceback.format_stack(frame)))
        return 200, "\n".join(out)

    def get_debug_profile(pv, params, body):
        """Whole-process sampling CPU profile for N seconds; the
        response is a pstats-loadable marshal blob (reference
        /debug/pprof/profile, http/handler.go:281)."""
        from pilosa_tpu.obs.profiler import sample_profile
        seconds = min(max(float(params.get("seconds", 2)), 0.1), 60.0)
        blob = sample_profile(seconds)
        return 200, blob, {"Content-Type": "application/octet-stream",
                           "Content-Disposition":
                               'attachment; filename="profile.pstats"'}

    def get_debug_heap(pv, params, body):
        """One-stop memory accounting: tracemalloc top sites + native
        pool + planner HBM cache + per-index host-row bytes (reference
        /debug/pprof heap, http/handler.go:281; VERDICT r4 #3)."""
        from pilosa_tpu.obs.heap import heap_stats
        top_n = min(max(int(params.get("top", 25)), 1), 200)
        return 200, heap_stats(api.holder,
                               planner=getattr(api.executor, "planner",
                                               None),
                               top_n=top_n)

    def post_recalculate(pv, params, body):
        api.recalculate_caches()
        return 200, {}

    def get_shards_max(pv, params, body):
        return 200, {"standard": api.max_shards()}

    def get_availability(pv, params, body):
        """Per-field shard availability for anti-entropy merge (the
        additive NodeStatus half, reference server.go:640)."""
        from pilosa_tpu.cluster.resize import holder_availability
        return 200, holder_availability(api.holder)

    def post_translate_keys(pv, params, body):
        req = jbody(body)
        ids = api.translate_keys(req["index"], req.get("field"),
                                 req.get("keys", []))
        return 200, {"ids": ids}

    def get_translate_entries(pv, params, body):
        entries = api.translate_entries(params["index"],
                                        params.get("field"),
                                        int(params.get("after", 0)))
        return 200, {"entries": [[i, k] for i, k in entries]}

    # internal RPC
    def post_cluster_message(pv, params, body):
        msg = jbody(body)
        server = getattr(api, "message_handler", None)
        if server is not None:
            server(msg)
        return 200, {}

    # (The old GET /internal/fragment/data pull route is gone: resize
    # fragment movement rides the PTS1 import stream — resumable,
    # IngestGate-budgeted, QoS-classed — instead of a bespoke puller.)

    def get_debug_resize(pv, params, body):
        """Live serve-through resize state: the coordinator's job (per-
        shard migrated/in-flight counts, cutover lag) and/or this
        member's migration table. {"job": null, "migration": null} at
        rest — the probe a drill/operator polls while the ring moves."""
        job = getattr(api, "resize_job", None)
        mig = (getattr(api.cluster, "migration", None)
               if api.cluster is not None else None)
        return 200, {
            "job": job.snapshot() if job is not None else None,
            "migration": mig.snapshot() if mig is not None else None,
        }

    def get_debug_backup(pv, params, body):
        """Unattended-backup health: the BackupScheduler's status doc
        (runs/skips/failures, backoff, slowlog, last prune), or
        {"enabled": false} when no scheduler runs on this node."""
        handler = getattr(api, "backup_debug_handler", None)
        if handler is None:
            return 200, {"enabled": False}
        return 200, handler()

    def post_resize_abort(pv, params, body):
        job = getattr(api, "resize_job", None)
        if job is not None:
            job.abort()
        return 200, {}

    def post_resize_remove_node(pv, params, body):
        req = jbody(body)
        handler = getattr(api, "resize_handler", None)
        if handler is None:
            return 400, {"error": "resize not supported on this node"}
        handler("remove", req.get("id"))
        return 200, {}

    def post_set_coordinator(pv, params, body):
        req = jbody(body)
        if api.cluster is not None:
            for n in api.cluster.nodes:
                n.is_coordinator = (n.id == req.get("id"))
            # Persist the handoff: a restart must not resurrect the OLD
            # coordinator flag from topology.json (resizes would consult
            # the wrong node as the resize authority).
            api.cluster.notify_topology()
        return 200, {}

    def get_fragment_blocks(pv, params, body):
        blocks = api.fragment_blocks(params["index"], params["field"],
                                     params["view"], int(params["shard"]))
        return 200, {"blocks": [{"id": b, "checksum": cs.hex()}
                                for b, cs in sorted(blocks.items())]}

    def get_fragment_block_data(pv, params, body):
        rows, cols = api.fragment_block_data(
            params["index"], params["field"], params["view"],
            int(params["shard"]), int(params["block"]))
        return 200, {"rowIDs": [int(r) for r in rows],
                     "columnIDs": [int(c) for c in cols]}

    def get_attr_blocks(pv, params, body):
        blocks = api.attr_blocks(params["index"], params.get("field"))
        return 200, {"blocks": [{"id": b, "checksum": cs.hex()}
                                for b, cs in blocks]}

    def get_attr_block_data(pv, params, body):
        data = api.attr_block_data(params["index"], params.get("field"),
                                   int(params["block"]))
        return 200, {"attrs": {str(i): a for i, a in data.items()}}

    def post_internal_import(pv, params, body):
        from pilosa_tpu.server import wire

        # Binary import frames (wire.encode_import) or legacy JSON —
        # sniffed by magic so mixed-version clusters interoperate.
        if wire.is_import_frame(body):
            req = wire.decode_import(body)
        else:
            req = jbody(body)
        server = getattr(api, "import_handler", None)
        if server is None:
            return 400, {"error": "no import handler"}
        server(req)
        return 200, {}

    def get_nodes(pv, params, body):
        return 200, api.hosts()

    def get_internal_probe(pv, params, body):
        """Probe a third node on a caller's behalf (memberlist indirect
        ping, gossip/gossip.go:43-443): an asymmetric partition between
        the caller and the target must not read as target-down when
        THIS node can still reach it. The target must be a known
        cluster member — probing arbitrary caller-supplied addresses
        would make this node a reachability oracle for its network
        position (memberlist likewise only pings members)."""
        cluster = getattr(api, "cluster", None)
        client = getattr(cluster, "client", None)
        host = params.get("host", "")
        port = str(params.get("port", ""))
        target = None
        if cluster is not None:
            target = next(
                (n for n in cluster.nodes
                 if n.uri.host == host and str(n.uri.port) == port), None)
        if client is None or target is None:
            return 200, {"ok": False}
        try:
            client.probe(target)
            return 200, {"ok": True}
        except (ConnectionError, OSError, RuntimeError):
            return 200, {"ok": False}

    def get_views(pv, params, body):
        return 200, {"views": api.views(pv["index"], pv["field"])}

    def delete_view(pv, params, body):
        api.delete_view(pv["index"], pv["field"], pv["view"])
        return 200, {}

    # backup / restore (operator surface + internal capture RPC)
    def post_backup(pv, params, body):
        handler = getattr(api, "backup_handler", None)
        if handler is None:
            return 400, {"error": "backup not configured on this node "
                                  "(no data dir)"}
        req = jbody(body)
        if params.get("archive"):
            req.setdefault("archive", params["archive"])
        if params.get("parent"):
            req.setdefault("parent", params["parent"])
        return 200, handler(req)

    def get_backup_status(pv, params, body):
        handler = getattr(api, "backup_status_handler", None)
        if handler is None:
            return 200, {"state": "idle"}
        return 200, handler()

    def post_restore(pv, params, body):
        handler = getattr(api, "restore_handler", None)
        if handler is None:
            return 400, {"error": "restore not configured on this node "
                                  "(no data dir)"}
        req = jbody(body)
        if params.get("archive"):
            req.setdefault("archive", params["archive"])
        if params.get("id"):
            req.setdefault("id", params["id"])
        if params.get("force") in ("1", "true"):
            req.setdefault("force", True)
        return 200, handler(req)

    def get_restore_status(pv, params, body):
        handler = getattr(api, "restore_status_handler", None)
        if handler is None:
            return 200, {"state": "idle"}
        return 200, handler()

    def get_backup_keys(pv, params, body):
        """Fragment keys this node holds durable files for (backup
        coordinator enumeration over HTTP)."""
        store = getattr(api, "store", None)
        if store is None:
            return 200, {"keys": []}
        return 200, {"keys": [list(k) for k in store.all_fragment_keys()]}

    def get_backup_fragment(pv, params, body):
        """One fragment's verified (snap, wal) pair, base64-wrapped in
        JSON. ShardCorruptError propagates to the dispatch ladder's 503
        so the coordinator fails over to a replica."""
        store = getattr(api, "store", None)
        if store is None:
            raise FragmentNotFoundError()
        from pilosa_tpu.backup.writer import capture_fragment
        key = (params["index"], params["field"], params["view"],
               int(params["shard"]))
        try:
            pair = capture_fragment(store, key)
        except LookupError:
            raise FragmentNotFoundError() from None
        import base64
        return 200, {
            "snap": (base64.b64encode(pair["snap"]).decode()
                     if pair["snap"] is not None else None),
            "wal": (base64.b64encode(pair["wal"]).decode()
                    if pair["wal"] is not None else None),
            "ops": pair["ops"],
        }

    def get_fragment_nodes(pv, params, body):
        index = params.get("index")
        shard = params.get("shard")
        if index is None or shard is None:
            return 400, {"error": "index and shard params required"}
        return 200, api.fragment_nodes(index, int(shard))

    def delete_remote_available_shard(pv, params, body):
        api.delete_available_shard(pv["index"], pv["field"],
                                   int(pv["shard"]))
        return 200, {}

    table = [
        (r"/", {"GET": home}),
        (r"/index", {"GET": get_indexes}),
        (r"/index/(?P<index>[^/]+)/query", {"POST": post_query}),
        (r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import",
         {"POST": post_import}),
        (r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/import-roaring/"
         r"(?P<shard>[0-9]+)",
         {"POST": post_import_roaring}),
        (r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/views",
         {"GET": get_views}),
        (r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)/view/"
         r"(?P<view>[^/]+)",
         {"DELETE": delete_view}),
        (r"/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)",
         {"POST": post_field, "DELETE": delete_field}),
        (r"/index/(?P<index>[^/]+)",
         {"GET": get_index, "POST": post_index, "DELETE": delete_index}),
        (r"/export", {"GET": get_export}),
        (r"/schema", {"GET": get_schema, "POST": post_schema}),
        (r"/status", {"GET": get_status}),
        (r"/info", {"GET": get_info}),
        (r"/version", {"GET": get_version}),
        (r"/metrics", {"GET": get_metrics}),
        (r"/debug/vars", {"GET": get_debug_vars}),
        (r"/debug/membership", {"GET": get_debug_membership}),
        (r"/debug/queries/(?P<trace>[^/]+)",
         {"GET": get_debug_query_profile}),
        (r"/debug/queries", {"GET": get_debug_queries}),
        (r"/debug/device", {"GET": get_debug_device}),
        (r"/debug/translate", {"GET": get_debug_translate}),
        (r"/debug/slow-queries", {"GET": get_debug_slow_queries}),
        (r"/debug/overload", {"GET": get_debug_overload}),
        (r"/debug/cache", {"GET": get_debug_cache}),
        (r"/debug/quarantine", {"GET": get_debug_quarantine}),
        (r"/debug/threads", {"GET": get_debug_threads}),
        (r"/debug/profile", {"GET": get_debug_profile}),
        (r"/debug/heap", {"GET": get_debug_heap}),
        (r"/recalculate-caches", {"POST": post_recalculate}),
        (r"/backup", {"POST": post_backup}),
        (r"/backup/status", {"GET": get_backup_status}),
        (r"/restore", {"POST": post_restore}),
        (r"/restore/status", {"GET": get_restore_status}),
        (r"/internal/backup/keys", {"GET": get_backup_keys}),
        (r"/internal/backup/fragment", {"GET": get_backup_fragment}),
        (r"/internal/shards/max", {"GET": get_shards_max}),
        (r"/internal/availability", {"GET": get_availability}),
        (r"/internal/translate/keys", {"POST": post_translate_keys}),
        (r"/internal/translate/entries", {"GET": get_translate_entries}),
        (r"/internal/cluster/message", {"POST": post_cluster_message}),
        (r"/internal/fragment/blocks", {"GET": get_fragment_blocks}),
        (r"/internal/fragment/nodes", {"GET": get_fragment_nodes}),
        (r"/debug/resize", {"GET": get_debug_resize}),
        (r"/debug/backup", {"GET": get_debug_backup}),
        (r"/internal/index/(?P<index>[^/]+)/field/(?P<field>[^/]+)"
         r"/remote-available-shards/(?P<shard>[0-9]+)",
         {"DELETE": delete_remote_available_shard}),
        (r"/cluster/resize/abort", {"POST": post_resize_abort}),
        (r"/cluster/resize/remove-node", {"POST": post_resize_remove_node}),
        (r"/cluster/resize/set-coordinator", {"POST": post_set_coordinator}),
        (r"/internal/fragment/block/data", {"GET": get_fragment_block_data}),
        (r"/internal/attr/blocks", {"GET": get_attr_blocks}),
        (r"/internal/attr/data", {"GET": get_attr_block_data}),
        (r"/internal/import", {"POST": post_internal_import}),
        (r"/internal/nodes", {"GET": get_nodes}),
        (r"/internal/probe", {"GET": get_internal_probe}),
        (r"/internal/query-mux", {"POST": post_query_mux}),
    ]
    if getattr(api, "chaos_faults", False):
        table.append((r"/internal/fault", {"POST": post_fault}))
    return [(re.compile("^" + p + "$"), methods) for p, methods in table]
