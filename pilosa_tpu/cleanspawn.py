"""Hermetic CPU-pinned subprocess spawning for the multi-chip dryruns.

The rig that drives this repo pins every Python process to its TPU tunnel
three different ways (VERDICT r4 weak #1):

- ``PYTHONPATH`` carries a directory whose ``sitecustomize.py``
  force-registers the TPU PJRT plugin at interpreter startup, so
  ``JAX_PLATFORMS=cpu`` in the *environment* does not keep the plugin
  from loading; only an in-process ``jax.config.update`` does.  Any jax
  op issued before that update dispatches onto the TPU backend — fatal
  whenever the rig's libtpu client/terminal versions drift (the
  MULTICHIP_r04 failure signature).
- ``JAX_PLATFORMS`` / ``PALLAS_AXON_*`` / ``AXON_*`` select the plugin
  by environment.
- ``TPU_*`` / ``LIBTPU*`` configure the chip itself.

The multi-chip correctness evidence (MULTICHIP_r*.json) must run on the
virtual-device CPU backend, so every subprocess in the dryrun chain is
spawned through this module:

1. ``scrubbed_env`` drops every plugin-selecting variable **and** every
   ``PYTHONPATH`` entry that carries a ``sitecustomize``/``usercustomize``;
2. children run under ``python -I`` (isolated mode: ``PYTHONPATH`` and
   user-site are never consulted, so no sitecustomize can load even if a
   poisoned path survives the scrub);
3. ``assert_cpu_backend`` hard-fails with a diagnostic naming the leak
   before the first real jax op if a TPU backend still won.

Kept import-light (os/sys only — no jax) so the driver process can import
it without initializing a backend of its own.
"""

from __future__ import annotations

import os
import sys

#: environment prefixes that select or configure an accelerator plugin.
SCRUB_PREFIXES = ("TPU_", "LIBTPU", "AXON_", "PALLAS_AXON_", "JAX_",
                  "PJRT_")

#: module names whose presence in a PYTHONPATH entry marks it as a
#: startup-hook directory (imported by ``site`` before any user code).
_SITE_HOOKS = ("sitecustomize.py", "usercustomize.py")


def _is_site_hook_dir(path: str) -> bool:
    for hook in _SITE_HOOKS:
        try:
            os.stat(os.path.join(path, hook))
            return True
        except FileNotFoundError:
            continue
        except OSError:
            return True  # unreadable — treat as hostile
    return False



def scrubbed_env(n_devices: int | None = None) -> dict[str, str]:
    """A copy of ``os.environ`` safe for a CPU-pinned jax child.

    Drops every ``SCRUB_PREFIXES`` variable, removes ``PYTHONPATH``
    entries that contain a site-customization hook, pins
    ``JAX_PLATFORMS=cpu``, and (when ``n_devices``) rewrites
    ``XLA_FLAGS`` with the virtual-device count.
    """
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(SCRUB_PREFIXES)}
    parts = [p for p in env.pop("PYTHONPATH", "").split(os.pathsep)
             if p and not _is_site_hook_dir(p)]
    if parts:
        env["PYTHONPATH"] = os.pathsep.join(parts)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env


def pin_preamble(n_devices: int, repo_dir: str,
                 assert_backend: bool = True) -> str:
    """Source prefix for a ``python -I -c`` child: re-pins the CPU
    backend *inside* the process (a surviving startup hook may have
    rewritten the environment between exec and user code), restores the
    repo on ``sys.path`` (isolated mode cleared it), and optionally
    asserts the backend before any caller op.

    Callers that must run ``jax.distributed.initialize`` pass
    ``assert_backend=False`` and place ``assert_cpu_backend()``
    themselves *after* the initialize (backend init must not precede
    it).
    """
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {repo_dir!r})\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "_flags = [f for f in os.environ.get('XLA_FLAGS', '').split()\n"
        "          if 'xla_force_host_platform_device_count' not in f]\n"
        f"_flags.append('--xla_force_host_platform_device_count"
        f"={n_devices}')\n"
        "os.environ['XLA_FLAGS'] = ' '.join(_flags)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
    )
    if assert_backend:
        code += ("from pilosa_tpu.cleanspawn import assert_cpu_backend\n"
                 "assert_cpu_backend()\n")
    return code


def command(body: str) -> list[str]:
    """argv for an isolated-mode child running ``body``."""
    return [sys.executable, "-I", "-c", body]


def assert_cpu_backend() -> None:
    """Initialize jax's backend and die loudly if it is not CPU.

    Called as the first backend-touching statement of every dryrun
    child: a non-CPU default backend here means an accelerator plugin
    leaked through the scrub, and every subsequent op would ride the
    TPU tunnel — the exact failure MULTICHIP_r04 recorded.  The
    diagnostic names the surviving environment so the leak is
    actionable, not mysterious.
    """
    import jax
    backend = jax.default_backend()
    if backend != "cpu":
        leaks = {k: v for k, v in os.environ.items()
                 if k.startswith(SCRUB_PREFIXES) or k == "PYTHONPATH"}
        raise SystemExit(
            f"dryrun child initialised jax backend {backend!r}, not 'cpu'. "
            f"An accelerator plugin leaked past the scrub "
            f"(isolated={sys.flags.isolated}). Surviving env: {leaks}")
