"""On-disk integrity framing: snapshot footers and checksummed jsonl lines.

The WAL is checksummed per-op (storage/wal.py), but every other durable
artifact — fragment snapshots, translate/attr jsonl stores — was trusted
blindly at boot: a flipped bit was detected only if ``np.load`` happened
to throw, and otherwise served wrong bits forever.  This module gives
each artifact a verifiable frame:

Snapshot footer (appended after the npz payload)::

    magic    4s  = b"PTSF"
    version  u16
    flags    u16 (reserved)
    crc32    u32 of the payload bytes
    len      u64 payload byte length
    rows     u64 row count       (operator-facing, `check`/`inspect`)
    bits     u64 set-bit count
    magic2   4s  = b"FSTP"

The trailing magic makes a complete footer cheap to detect from the file
tail; the LEADING magic catches the crash/corruption shape a trailing
check alone would miss — a file truncated mid-footer still shows the
leading magic in its tail and is flagged corrupt instead of silently
downgrading to "legacy unframed".  Files with neither magic are legacy
(pre-footer) snapshots: still loadable, but flagged unverified.

Jsonl line frame::

    L1 <payload-byte-len> <crc32-hex8> <payload>

Unframed lines (legacy stores) still parse, flagged unverified; a framed
line whose length or CRC disagrees raises ``LineCorruptError`` so the
loader can skip it with a warning instead of crashing the boot.
"""

from __future__ import annotations

import struct
import zlib

SNAP_MAGIC = b"PTSF"
SNAP_MAGIC_END = b"FSTP"
SNAP_VERSION = 1

_FOOTER_BODY = struct.Struct("<HHIQQQ")
FOOTER_SIZE = len(SNAP_MAGIC) + _FOOTER_BODY.size + len(SNAP_MAGIC_END)

LINE_PREFIX = "L1 "


class SnapshotCorruptError(Exception):
    """A framed snapshot failed verification (CRC/length/torn footer)."""


class LineCorruptError(Exception):
    """A framed jsonl line failed verification."""


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


# -- snapshot footer -------------------------------------------------------

def snapshot_footer(payload: bytes, rows: int, bits: int) -> bytes:
    """Footer bytes for an npz payload about to be published."""
    return (SNAP_MAGIC
            + _FOOTER_BODY.pack(SNAP_VERSION, 0, _crc(payload),
                                len(payload), rows, bits)
            + SNAP_MAGIC_END)


def split_snapshot(data: bytes) -> tuple[bytes, dict | None]:
    """Split raw snapshot file bytes into (payload, meta).

    meta is None for a legacy unframed file.  Raises
    ``SnapshotCorruptError`` when a footer is present but wrong (CRC or
    length mismatch) or torn (leading magic without the trailing one).
    """
    if (len(data) >= FOOTER_SIZE
            and data.endswith(SNAP_MAGIC_END)
            and data[-FOOTER_SIZE:-FOOTER_SIZE + 4] == SNAP_MAGIC):
        version, _flags, crc, plen, rows, bits = _FOOTER_BODY.unpack(
            data[-FOOTER_SIZE + 4:-4])
        payload = data[:-FOOTER_SIZE]
        if plen != len(payload):
            raise SnapshotCorruptError(
                f"footer length mismatch: footer says {plen}, "
                f"file holds {len(payload)}")
        if _crc(payload) != crc:
            raise SnapshotCorruptError(
                f"payload crc mismatch: footer {crc:#010x}, "
                f"payload {_crc(payload):#010x}")
        return payload, {"version": version, "rows": rows, "bits": bits,
                         "crc": crc}
    # A leading magic in the tail without a trailing one is a footer cut
    # mid-write/mid-truncation — corrupt, not legacy.
    if SNAP_MAGIC in data[-(FOOTER_SIZE - 1):]:
        raise SnapshotCorruptError("truncated snapshot footer")
    return data, None


# -- jsonl line frame ------------------------------------------------------

def frame_line(payload: str) -> str:
    """Frame one jsonl payload (no trailing newline)."""
    data = payload.encode("utf-8")
    return f"{LINE_PREFIX}{len(data)} {_crc(data):08x} {payload}"


def parse_line(line: str) -> tuple[str, bool]:
    """(payload, verified). Unframed legacy lines come back unverified;
    a framed line that fails its check raises ``LineCorruptError``."""
    if not line.startswith(LINE_PREFIX):
        return line, False
    parts = line.split(" ", 3)
    if len(parts) != 4:
        raise LineCorruptError("truncated line frame")
    try:
        n = int(parts[1])
        crc = int(parts[2], 16)
    except ValueError as e:
        raise LineCorruptError(f"bad line frame header: {e}") from e
    payload = parts[3]
    data = payload.encode("utf-8")
    if len(data) != n:
        raise LineCorruptError(
            f"line length mismatch: frame says {n}, line holds {len(data)}")
    if _crc(data) != crc:
        raise LineCorruptError(
            f"line crc mismatch: frame {crc:#010x}, payload "
            f"{_crc(data):#010x}")
    return payload, True
