"""DiskStore — the durability engine bound to a Holder.

Reference: holder.go Open (:137, data-dir walk → Index.Open → Field.Open
→ view.open → fragment.Open with mmap + op-log replay), the background
snapshot queue (fragment.go:187-239, holder.go:163: depth-100 queue, 2
workers), snapshot write (fragment.go:2337-2393: temp file + rename),
and per-object meta persistence (.meta / .available.shards / attr and
translate stores).

Layout under ``data_dir``::

    schema.json
    <index>/column_attrs.jsonl
    <index>/translate.jsonl
    <index>/<field>/row_attrs.jsonl
    <index>/<field>/translate.jsonl
    <index>/<field>/<view>/<shard>.snap   # npz: row ids + positions
    <index>/<field>/<view>/<shard>.wal    # binary op log
"""

from __future__ import annotations

import io
import json
import os
import queue
import threading

import numpy as np

from pilosa_tpu.config import MAX_OP_N
from pilosa_tpu.core.attrs import AttrStore
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.hostrow import HostRow
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.obs.logger import StandardLogger
from pilosa_tpu.obs.stats import NopStats
from pilosa_tpu.storage.integrity import (
    SnapshotCorruptError,
    snapshot_footer,
    split_snapshot,
)
from pilosa_tpu.storage.quarantine import (
    BLOCKED_STATES,
    STATE_DEGRADED,
    STATE_UNAVAILABLE,
    QuarantineRegistry,
)
from pilosa_tpu.storage.wal import (
    OP_ADD,
    OP_CLEAR_ROW,
    OP_REMOVE,
    OP_SET_ROW,
    WalReader,
    WalWriter,
    scan_wal,
)


def read_snapshot(path: str):
    """Read + verify one snapshot file.

    Returns ``(arrays, meta, status)`` with status one of ``"ok"``
    (framed, CRC verified), ``"legacy"`` (pre-footer file, unverified),
    or ``"bad"`` (corrupt — arrays is None and meta carries the error).
    """
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError as e:
        return None, {"error": str(e)}, "bad"
    try:
        payload, meta = split_snapshot(data)
    except SnapshotCorruptError as e:
        return None, {"error": str(e)}, "bad"
    try:
        with np.load(io.BytesIO(payload)) as z:
            arrays = {k: z[k] for k in ("row_ids", "offsets", "positions")}
    except Exception as e:
        return None, {"error": f"unreadable payload: {e}"}, "bad"
    return arrays, meta, ("ok" if meta is not None else "legacy")


class DiskStore:
    """Snapshot + WAL persistence for every fragment of a holder."""

    def __init__(self, data_dir: str, holder: Holder,
                 max_op_n: int = MAX_OP_N, snapshot_workers: int = 2,
                 fsync_appends: bool = False, stats=None, logger=None,
                 quarantine_keep_n: int = 0, wal_group_window: float = 0.0):
        self.data_dir = data_dir
        self.holder = holder
        self.max_op_n = max_op_n
        #: group-commit flush window (seconds) handed to every WalWriter;
        #: only meaningful with fsync_appends (see wal.WalWriter).
        self.wal_group_window = wal_group_window
        #: cap on accumulated ``*.quarantine`` evidence files per
        #: fragment, pruned oldest-first after a successful scrub repair;
        #: 0 keeps everything (the historical behaviour).
        self.quarantine_keep_n = quarantine_keep_n
        #: fsync every WAL record (strict durability; default matches the
        #: reference's buffered op-log writes).
        self.fsync_appends = fsync_appends
        self.stats = stats if stats is not None else NopStats()
        self.logger = logger if logger is not None else StandardLogger()
        self.quarantine = QuarantineRegistry(stats=self.stats,
                                             logger=self.logger)
        os.makedirs(data_dir, exist_ok=True)
        self._writers: dict[tuple, WalWriter] = {}
        #: tombstones: fragments the holderCleaner removed. A snapshot
        #: worker racing the deletion must not resurrect their files;
        #: re-creating the fragment (re-ownership) clears the tombstone
        #: via _op_writer_factory.
        self._deleted: set[tuple] = set()
        self._lock = threading.Lock()
        self._schema_lock = threading.Lock()
        # Background snapshot queue (holder.go:163: depth 100, 2 workers).
        self._snap_q: "queue.Queue[tuple | None]" = queue.Queue(maxsize=100)
        self._snap_pending: set[tuple] = set()
        self._workers = [threading.Thread(target=self._snapshot_worker,
                                          daemon=True)
                         for _ in range(snapshot_workers)]

    # -- paths -------------------------------------------------------------

    def _frag_dir(self, index: str, field: str, view: str) -> str:
        return os.path.join(self.data_dir, index, field, view)

    def _snap_path(self, key: tuple) -> str:
        index, field, view, shard = key
        return os.path.join(self._frag_dir(index, field, view), f"{shard}.snap")

    def _wal_path(self, key: tuple) -> str:
        index, field, view, shard = key
        return os.path.join(self._frag_dir(index, field, view), f"{shard}.wal")

    # -- open / reload (holder.go:137) -------------------------------------

    def open(self) -> None:
        self.holder.op_writer_factory = self._op_writer_factory
        # Let the executor consult the quarantine without a store import
        # cycle (exec checks getattr(holder, "quarantine", None)).
        self.holder.quarantine = self.quarantine
        # Finish any deletion a crash interrupted: subtrees are detached
        # by rename before their slow recursive unlink.
        import shutil
        for fn in os.listdir(self.data_dir):
            if fn.startswith(".trash-"):
                shutil.rmtree(os.path.join(self.data_dir, fn),
                              ignore_errors=True)
            elif fn.startswith("schema.json.") and fn.endswith(".tmp"):
                # A crash between tmp write and replace strands a
                # uniquely-named tmp; sweep them or they accumulate.
                try:
                    os.remove(os.path.join(self.data_dir, fn))
                except OSError:
                    pass
        schema_path = os.path.join(self.data_dir, "schema.json")
        if os.path.exists(schema_path):
            with open(schema_path) as f:
                self.holder.apply_schema(json.load(f))
        self._attach_stores()
        self._load_fragments()
        for w in self._workers:
            w.start()

    def _attach_stores(self) -> None:
        """Swap in path-backed attr/translate stores (boltdb/ analog).
        Every swapped-in store keeps the index's mutation epoch: attr
        and key-translation writes on a durable node must invalidate
        epoch-stamped caches exactly like they do on a memory node."""
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            idir = os.path.join(self.data_dir, iname)
            idx.column_attr_store = AttrStore(
                os.path.join(idir, "column_attrs.jsonl"), epoch=idx.epoch)
            idx.translate_store = TranslateStore(
                os.path.join(idir, "translate.jsonl"), epoch=idx.epoch)
            for fname, f in idx.fields.items():
                fdir = os.path.join(idir, fname)
                f.row_attr_store = AttrStore(
                    os.path.join(fdir, "row_attrs.jsonl"), epoch=idx.epoch)
                f.translate_store = TranslateStore(
                    os.path.join(fdir, "translate.jsonl"), epoch=idx.epoch)

    def _load_fragments(self) -> None:
        """Walk the data dir; rebuild fragments from snapshot + WAL."""
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            idir = os.path.join(self.data_dir, iname)
            if not os.path.isdir(idir):
                continue
            for fname, f in list(idx.fields.items()):
                fdir = os.path.join(idir, fname)
                if not os.path.isdir(fdir):
                    continue
                for view_name in sorted(os.listdir(fdir)):
                    vdir = os.path.join(fdir, view_name)
                    if not os.path.isdir(vdir):
                        continue
                    shards = set()
                    for fn in os.listdir(vdir):
                        if fn.endswith((".snap", ".wal")):
                            shards.add(int(fn.rsplit(".", 1)[0]))
                    if not shards:
                        # An EMPTY view dir is deletion debris (a racing
                        # snapshot's makedirs after delete_subtree_files'
                        # rmtree); recreating the view from it would
                        # resurrect a deleted view in the schema.
                        continue
                    view = f.create_view_if_not_exists(view_name)
                    for shard in sorted(shards):
                        frag = view.create_fragment_if_not_exists(shard)
                        self._load_fragment(frag, (iname, fname, view_name,
                                                   shard))

    def _load_fragment(self, frag, key: tuple) -> None:
        saved_writer = frag.op_writer
        frag.op_writer = None  # don't re-log replayed ops
        snap_corrupt = False
        wal_corrupt = False
        replayed = 0
        try:
            snap = self._snap_path(key)
            if os.path.exists(snap):
                arrays, meta, status = read_snapshot(snap)
                if status == "bad":
                    snap_corrupt = True
                    self.stats.count("integrity.snapshotCorrupt")
                    self.quarantine.quarantine_file(
                        key, snap, reason=f"snapshot: {meta['error']}")
                else:
                    if status == "legacy":
                        self.stats.count("integrity.snapshotUnverified")
                    row_ids = arrays["row_ids"]
                    offsets = arrays["offsets"]
                    positions = arrays["positions"]
                    for i, rid in enumerate(row_ids.tolist()):
                        lo, hi = int(offsets[i]), int(offsets[i + 1])
                        frag.rows[rid] = HostRow.from_positions(
                            positions[lo:hi])
                    frag._invalidate()
            wal_path = self._wal_path(key)
            wal_info = scan_wal(wal_path)
            wal_corrupt = wal_info["corrupt"]
            base = frag.shard * _shard_width()
            # Replay the valid prefix BEFORE any quarantine rename below
            # — the prefix ops live only in this file.
            for code, rows, cols in WalReader(wal_path):
                replayed += 1
                if code == OP_ADD:
                    frag.bulk_import(rows.tolist(), cols.tolist())
                elif code == OP_REMOVE:
                    frag.bulk_import(rows.tolist(), cols.tolist(), clear=True)
                elif code == OP_SET_ROW:
                    rid = int(rows[0]) if len(rows) else 0
                    frag.rows[rid] = HostRow.from_positions(
                        (cols - np.uint64(base)))
                    frag._invalidate()
                elif code == OP_CLEAR_ROW:
                    rid = int(rows[0]) if len(rows) else 0
                    frag.rows.pop(rid, None)
                    frag._invalidate()
            if wal_corrupt:
                # Mid-file damage: every op past the damage point is
                # silently gone, so the replayed state is NOT the full
                # acknowledged history — unlike a torn tail, which is
                # the normal crash shape and stays un-quarantined.
                self.stats.count("integrity.walCorrupt")
                self.quarantine.quarantine_file(
                    key, wal_path,
                    reason="wal: corrupt record mid-file "
                           f"({wal_info['ops']} ops salvaged)",
                    state=STATE_DEGRADED)
        finally:
            frag.op_writer = saved_writer
        if snap_corrupt or wal_corrupt:
            # Final serving state: any salvaged data (snapshot or WAL
            # prefix) leaves the fragment degraded-but-servable on a
            # standalone node; no data at all makes the shard
            # unavailable until a replica or repair steps in.
            has_data = replayed > 0 or (wal_corrupt and not snap_corrupt)
            self.quarantine.set_state(
                key, STATE_DEGRADED if has_data else STATE_UNAVAILABLE)
            if snap_corrupt and replayed > 0:
                self.stats.count("integrity.walReplayFallback")
            # The surviving state exists only in memory now (the bad
            # files were renamed aside): persist it as soon as the
            # snapshot workers start.
            self._enqueue_snapshot(key)

    # -- WAL wiring --------------------------------------------------------

    def _op_writer_factory(self, index: str, field: str, view: str,
                           shard: int):
        key = (index, field, view, shard)
        with self._lock:
            self._deleted.discard(key)  # fragment (re)created: live again

        def op_writer(op: str, rows, cols):
            w = self._writer(key)
            if w is None:
                return  # fragment GC'd; orphan writes must not recreate
                # the WAL file (stale bits would replay on restart)
            if op == "setRow":
                w.append("setRow", rows[:1], cols)
            else:
                w.append(op, rows, cols)
            if w.op_n > self.max_op_n:
                self._enqueue_snapshot(key)
        return op_writer

    def _writer(self, key: tuple) -> WalWriter | None:
        with self._lock:
            if key in self._deleted:
                return None
            w = self._writers.get(key)
            if w is None:
                w = self._writers[key] = WalWriter(
                    self._wal_path(key), fsync_appends=self.fsync_appends,
                    group_window=self.wal_group_window)
            return w

    def wal_fsyncs(self) -> int:
        """Total fsync() calls across every live WAL writer (the
        group-commit amortization gauge)."""
        with self._lock:
            return sum(w.fsyncs for w in self._writers.values())

    def delete_fragment_files(self, key: tuple) -> None:
        """Remove a fragment's snapshot + WAL (holderCleaner's disk
        half, holder.go:1170): tombstone the key, close its writer,
        unlink both files — all under the store lock so a racing
        snapshot worker can neither resurrect the files nor re-register
        a writer (its publish step re-checks the tombstone under the
        same lock)."""
        with self._lock:
            self._deleted.add(key)
            w = self._writers.pop(key, None)
            self._snap_pending.discard(key)
            if w is not None:
                w.close()
            for path in (self._snap_path(key), self._wal_path(key)):
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
            index, field, view, _ = key
            try:
                _fsync_dir(self._frag_dir(index, field, view))
            except OSError:
                pass

    def delete_subtree_files(self, index: str, field: str | None = None,
                             view: str | None = None) -> None:
        """Disk half of index/field/view deletion: tombstone and unlink
        every fragment under the prefix, then remove its directory.
        Without this, deleting a field and recreating the name would
        RESURRECT the deleted data on the next restart (the reloader is
        schema-driven and would find the stale .snap/.wal files).
        Reference: Index.DeleteField/deleteView remove the path trees
        (field.go:905, index.go:471)."""
        import shutil
        import uuid

        prefix = tuple(p for p in (index, field, view) if p is not None)
        plen = len(prefix)
        subdir = os.path.join(self.data_dir, *prefix)
        # Enumerate on-disk keys OUTSIDE the lock (the walk can be
        # slow); only the tombstone/writer bookkeeping needs mutual
        # exclusion. The holder entries are already gone, so no new
        # writers appear for the prefix while we walk — and any
        # straggler is caught by the snapshot identity check.
        disk_keys: set[tuple] = set()
        if os.path.isdir(subdir):
            for root, _dirs, files in os.walk(subdir):
                rel = os.path.relpath(root, self.data_dir)
                parts = tuple(rel.split(os.sep))
                if len(parts) != 3:  # index/field/view level only
                    continue
                for fn in files:
                    if fn.endswith((".snap", ".wal")):
                        disk_keys.add(parts + (int(fn.rsplit(".", 1)[0]),))
        trash = None
        with self._lock:
            keys = {k for k in self._writers if k[:plen] == prefix}
            keys |= {k for k in self._snap_pending if k[:plen] == prefix}
            keys |= disk_keys
            for key in keys:
                self._deleted.add(key)
                self._snap_pending.discard(key)
                w = self._writers.pop(key, None)
                if w is not None:
                    w.close()
            # Atomically detach the subtree INSIDE the lock (a rename is
            # O(1)); the slow recursive unlink happens outside it. A
            # same-name recreation racing the deletion then lands in a
            # FRESH directory instead of the doomed one — an rmtree of
            # the live path could silently destroy the recreated
            # field's brand-new WAL/snapshot files.
            if os.path.isdir(subdir):
                trash = os.path.join(
                    self.data_dir, f".trash-{uuid.uuid4().hex}")
                try:
                    os.rename(subdir, trash)
                except OSError:
                    trash = None  # fall back to in-place rmtree below
        if trash is not None:
            shutil.rmtree(trash, ignore_errors=True)
        else:
            shutil.rmtree(subdir, ignore_errors=True)
        self.save_schema()

    # -- snapshots (fragment.go:187-239, :2337-2393) -----------------------

    def _enqueue_snapshot(self, key: tuple) -> None:
        with self._lock:
            if key in self._snap_pending:
                return
            self._snap_pending.add(key)
        try:
            self._snap_q.put_nowait(key)
        except queue.Full:
            with self._lock:
                self._snap_pending.discard(key)

    def _snapshot_worker(self) -> None:
        while True:
            key = self._snap_q.get()
            if key is None:
                return
            try:
                self.snapshot_fragment(key)
            except Exception:
                # A failed snapshot (ENOSPC, I/O error) must not kill
                # the worker: the WAL still holds every op, the next
                # trigger retries, and close() relies on live workers
                # to drain the queue.
                pass
            finally:
                with self._lock:
                    self._snap_pending.discard(key)

    def snapshot_fragment(self, key: tuple) -> None:
        """Write <shard>.snap.tmp, fsync-rename, truncate the WAL."""
        index, field, view, shard = key
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            return  # deleted (cleaner / delete-field): nothing to write
        e = self.quarantine.get(key)
        if e is not None and e["state"] in BLOCKED_STATES:
            # A blocked fragment's memory is NOT the truth (empty or
            # partial); snapshotting it would launder the corruption
            # into a "clean" file a restart then trusts. The scrubber
            # flips the state to degraded after repairing, then
            # snapshots and releases.
            return
        with frag._lock:
            snap_rows = frag.rows_snapshot()
            row_ids = np.asarray([r for r, _ in snap_rows], dtype=np.uint64)
            parts = [p for _, p in snap_rows]
            offsets = np.zeros(len(parts) + 1, dtype=np.int64)
            for i, p in enumerate(parts):
                offsets[i + 1] = offsets[i] + len(p)
            positions = (np.concatenate(parts) if parts
                         else np.empty(0, np.uint64))
            path = self._snap_path(key)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            buf = io.BytesIO()
            np.savez_compressed(buf, row_ids=row_ids, offsets=offsets,
                                positions=positions)
            payload = buf.getvalue()
            with open(tmp, "wb") as fh:
                fh.write(payload)
                fh.write(snapshot_footer(payload, rows=len(row_ids),
                                         bits=len(positions)))
                fh.flush()
                os.fsync(fh.fileno())
            # Publish under the store lock, mutually exclusive with the
            # deleters' tombstone-and-unlink. Abort on fragment
            # IDENTITY, not just the tombstone: if the holder's current
            # fragment is no longer the object we snapshotted, a
            # deletion (and possibly a same-name recreation) happened
            # mid-write and publishing would resurrect dead data. If it
            # IS still the live object, any tombstone left from a prior
            # same-key generation is stale — the recreated fragment is
            # legitimately persisting — so clear it.
            with self._lock:
                if self.holder.fragment(index, field, view, shard) is not frag:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
                    return
                self._deleted.discard(key)
                os.replace(tmp, path)
            # The slow directory fsync runs OUTSIDE the store lock — it
            # would otherwise stall every concurrent WAL append (all go
            # through _writer() on the same lock) for a disk flush. The
            # outer FRAGMENT lock is still held, so no append to THIS
            # fragment can land before the truncate below.
            _fsync_dir(os.path.dirname(path))
            with self._lock:
                if self.holder.fragment(index, field, view, shard) is not frag:
                    # Deleted between publish and fsync: the subtree
                    # rename already carried our file away; nothing to
                    # truncate (the writer was closed by the deleter).
                    return
                # Snapshot is durable; only now may the WAL be
                # discarded. The outer fragment lock keeps the WAL
                # truncation atomic with the snapshot (no append may
                # land between them).
                w = self._writers.get(key)
                if w is None:
                    w = self._writers[key] = WalWriter(
                        self._wal_path(key),
                        fsync_appends=self.fsync_appends)
                # Truncate INSIDE the store lock: a racing
                # delete_fragment_files would otherwise close this
                # writer between fetch and truncate.
                w.truncate()

    def snapshot_all(self) -> None:
        for key in self._all_keys():
            self.snapshot_fragment(key)

    def verify_snapshot(self, key: tuple) -> str:
        """Re-verify one on-disk snapshot without loading it into the
        holder (scrubber's disk walk). Returns "ok" / "legacy" / "bad"
        / "missing"."""
        path = self._snap_path(key)
        if not os.path.exists(path):
            return "missing"
        _arrays, _meta, status = read_snapshot(path)
        return status

    def _all_keys(self):
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            for fname, f in idx.fields.items():
                for vname, v in f.views.items():
                    for shard in v.fragments:
                        yield (iname, fname, vname, shard)

    def all_fragment_keys(self) -> list[tuple]:
        """Every (index, field, view, shard) this node holds — the
        public enumeration the backup coordinator walks."""
        return sorted(self._all_keys())

    def prune_quarantine_evidence(self, key: tuple) -> int:
        """Enforce ``quarantine_keep_n`` on one fragment's accumulated
        ``*.quarantine`` evidence files, oldest (by mtime) first. Called
        after a successful scrub repair — while an entry is still open
        the evidence is live forensics and is never touched. Returns the
        number of files removed; 0 when unlimited (keep_n == 0)."""
        if self.quarantine_keep_n <= 0:
            return 0
        import glob
        files = []
        for base in (self._snap_path(key), self._wal_path(key)):
            files.extend(glob.glob(glob.escape(base) + ".quarantine*"))
        excess = len(files) - self.quarantine_keep_n
        if excess <= 0:
            return 0
        files.sort(key=lambda p: (os.path.getmtime(p), p))
        pruned = 0
        for path in files[:excess]:
            try:
                os.remove(path)
                pruned += 1
            except OSError:
                continue
        if pruned:
            self.stats.count("integrity.evidencePruned", pruned)
            self.logger.printf(
                "integrity: pruned %d quarantine evidence file(s) for "
                "%s (keep-n=%d)", pruned,
                "/".join(str(p) for p in key), self.quarantine_keep_n)
        return pruned

    # -- flush / close -----------------------------------------------------

    def save_schema(self) -> None:
        path = os.path.join(self.data_dir, "schema.json")
        # Serialize snapshot+replace: two concurrent savers could
        # otherwise interleave so the one holding the OLDER holder
        # snapshot wins the replace, resurrecting a just-deleted field
        # in schema.json. The unique tmp name guards a crashed saver's
        # leftovers (swept at open) from being replaced mid-write.
        with self._schema_lock:
            tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "w") as f:
                json.dump(self.holder.schema(), f)
            os.replace(tmp, path)

    def flush(self) -> None:
        self.save_schema()
        self._attach_paths_for_new_objects()
        self.snapshot_all()
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            idx.column_attr_store.save()
            idx.translate_store.save()
            for f in idx.fields.values():
                f.row_attr_store.save()
                f.translate_store.save()

    def _attach_paths_for_new_objects(self) -> None:  # analysis: ignore[epoch-audit]
        """Objects created after open() need their stores path-bound.

        The ``store._attrs = ...`` writes below rebind a fresh
        path-bound AttrStore to the SAME live dict the old store held —
        contents are bit-identical before and after, so no epoch-visible
        state changes and no bump is owed (pragma above)."""
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            idir = os.path.join(self.data_dir, iname)
            if idx.column_attr_store.path is None:
                store = AttrStore(os.path.join(idir, "column_attrs.jsonl"))
                store._attrs = idx.column_attr_store._attrs
                idx.column_attr_store = store
            if idx.translate_store.path is None:
                idx.translate_store.path = os.path.join(idir, "translate.jsonl")
            for fname, f in idx.fields.items():
                fdir = os.path.join(idir, fname)
                if f.row_attr_store.path is None:
                    store = AttrStore(os.path.join(fdir, "row_attrs.jsonl"))
                    store._attrs = f.row_attr_store._attrs
                    f.row_attr_store = store
                if f.translate_store.path is None:
                    f.translate_store.path = os.path.join(fdir,
                                                          "translate.jsonl")

    def close(self) -> None:
        # Stop the snapshot workers and WAIT for them: a worker
        # mid-snapshot would otherwise keep truncating WALs after the
        # writers below are closed (and after the data dir is handed to
        # a successor process). Workers catch their own exceptions, so
        # sentinels land once the queue drains; the timeouts below are
        # backstops, not the plan.
        for _ in self._workers:
            try:
                self._snap_q.put(None, timeout=35)
            except queue.Full:
                break
        for t in self._workers:
            t.join(timeout=30)
        if any(t.is_alive() for t in self._workers):
            # A straggler is still snapshotting: leave the writers OPEN
            # so its lock-held snapshot+truncate stays valid, and warn —
            # closing them under it could lose acknowledged ops.
            self.logger.printf("diskstore.close: snapshot worker still "
                               "running; leaving WAL writers open")
            self.flush()
            return
        self.flush()
        with self._lock:
            for w in self._writers.values():
                w.close()
            self._writers.clear()


def _fsync_dir(path: str) -> None:
    """Make a rename durable by fsyncing the containing directory."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _shard_width() -> int:
    from pilosa_tpu.config import SHARD_WIDTH
    return SHARD_WIDTH
