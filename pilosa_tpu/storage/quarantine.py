"""Corruption quarantine: evidence preservation + degraded routing state.

A durable artifact that fails verification at load (snapshot footer CRC,
mid-file WAL corruption) is renamed to ``<name>.quarantine`` — never
deleted, the operator may want the evidence — and its fragment key is
registered here.  The registry is the single source of truth for what a
node may NOT serve locally:

- ``degraded``   — the fragment serves partial local data (WAL-only
                   replay after a corrupt snapshot); legal only when no
                   replica can serve the full truth (standalone nodes).
- ``routed``     — a cluster peer owns a clean replica; the local copy
                   was dropped and queries must not land here (see
                   ``cluster.scrub.route_quarantined_to_replicas``).
- ``unavailable``— no local data survives (snapshot corrupt AND the WAL
                   empty) and no replica is known; queries over the
                   shard fail with ``ShardCorruptError`` instead of
                   silently serving zeros.

Entries leave the registry only through ``release`` — after the scrubber
has repaired the fragment from replica consensus and a clean checksummed
snapshot is back on disk.
"""

from __future__ import annotations

import os
import threading
import time

from pilosa_tpu.errors import PilosaError

#: entry states
STATE_DEGRADED = "degraded"
STATE_ROUTED = "routed"
STATE_UNAVAILABLE = "unavailable"

#: states under which the local node must not serve the shard
BLOCKED_STATES = (STATE_ROUTED, STATE_UNAVAILABLE)


class ShardCorruptError(PilosaError):
    """Distinct from ShardUnavailableError (a membership problem): the
    shard's local data is quarantined and no clean replica is reachable."""

    message = "shard data quarantined: no clean copy available"


class QuarantineRegistry:
    """Tracks quarantined fragment keys and their preserved files."""

    def __init__(self, stats=None, logger=None):
        self._stats = stats
        self._logger = logger
        self._entries: dict[tuple, dict] = {}
        self._lock = threading.Lock()

    # -- intake ------------------------------------------------------------

    def quarantine_file(self, key: tuple, path: str, reason: str,
                        state: str = STATE_UNAVAILABLE) -> str | None:
        """Rename ``path`` aside (never delete) and register ``key``.
        Returns the quarantined path, or None when the rename failed.

        Repeat quarantines of the same file take numbered suffixes
        (``.quarantine.1``, ``.quarantine.2`` …) so later evidence never
        clobbers earlier evidence; the store's ``--quarantine-keep-n``
        pruner is what bounds the accumulation."""
        qpath = path + ".quarantine"
        n = 0
        while os.path.exists(qpath):
            n += 1
            qpath = f"{path}.quarantine.{n}"
        try:
            os.replace(path, qpath)
        except OSError:
            qpath = None
        with self._lock:
            e = self._entries.setdefault(key, {
                "key": key, "files": [], "reasons": [],
                "state": state, "since": time.time(),
            })
            if qpath is not None:
                e["files"].append(qpath)
            e["reasons"].append(reason)
            # Never upgrade: unavailable beats degraded.
            if state == STATE_UNAVAILABLE or e["state"] == STATE_UNAVAILABLE:
                e["state"] = STATE_UNAVAILABLE
        if self._stats is not None:
            self._stats.count("integrity.quarantined")
        if self._logger is not None:
            self._logger.printf(
                "integrity: quarantined %s (%s): %s",
                "/".join(str(p) for p in key), state, reason)
        return qpath

    def set_state(self, key: tuple, state: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e["state"] = state

    def release(self, key: tuple) -> bool:
        """Drop the entry after a verified repair + clean re-snapshot.
        The ``*.quarantine`` files stay on disk."""
        with self._lock:
            e = self._entries.pop(key, None)
        if e is None:
            return False
        if self._stats is not None:
            self._stats.count("integrity.released")
        if self._logger is not None:
            self._logger.printf("integrity: released %s after repair",
                                "/".join(str(p) for p in key))
        return True

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: tuple) -> dict | None:
        with self._lock:
            e = self._entries.get(key)
            return dict(e) if e is not None else None

    def keys(self) -> list[tuple]:
        with self._lock:
            return sorted(self._entries)

    def entries(self) -> list[dict]:
        """JSON-able view for /debug/quarantine and `check`."""
        with self._lock:
            out = []
            for (index, field, view, shard), e in sorted(
                    self._entries.items()):
                out.append({"index": index, "field": field, "view": view,
                            "shard": shard, "state": e["state"],
                            "files": list(e["files"]),
                            "reasons": list(e["reasons"]),
                            "since": e["since"]})
            return out

    def blocked_shards(self, index: str) -> set[int]:
        """Shards of ``index`` the local node must not serve."""
        with self._lock:
            return {k[3] for k, e in self._entries.items()
                    if k[0] == index and e["state"] in BLOCKED_STATES}
