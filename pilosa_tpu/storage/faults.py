"""Test-only fault injection: deterministic file corruption.

Models the disk-corruption classes of Bairavasundaram et al. ("An
Analysis of Data Corruption in the Storage Stack"): silent bit flips,
truncation (lost writes at the tail), and whole-file loss.  Used by the
crash-recovery tests and the chaos soak's corruption action; production
code never imports this module.
"""

from __future__ import annotations

import os
import random

from pilosa_tpu.storage.diskstore import DiskStore

FAULT_MODES = ("bitflip", "truncate", "unlink")


def corrupt_file(path: str, mode: str = "bitflip",
                 rng: random.Random | None = None) -> None:
    """Damage ``path`` in place. ``bitflip`` flips one bit mid-file,
    ``truncate`` cuts the tail, ``unlink`` removes the file."""
    rng = rng or random.Random(0)
    if mode == "unlink":
        os.remove(path)
        return
    size = os.path.getsize(path)
    if mode == "truncate":
        # Cut into the tail: for a framed snapshot this lands mid-footer
        # (the crash shape split_snapshot must flag, not misread as
        # legacy); for a WAL it tears the last record.
        keep = max(0, size - rng.randrange(1, 24))
        with open(path, "r+b") as f:
            f.truncate(keep)
        return
    if mode == "bitflip":
        if size == 0:
            return
        off = rng.randrange(size)
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ (1 << rng.randrange(8))]))
        return
    raise ValueError(f"unknown fault mode {mode!r}")


class FaultyDiskStore(DiskStore):
    """DiskStore whose next snapshot publish is followed by injected
    corruption of the published file — the "disk lied after the fsync"
    scenario recovery tests need to stage without racing real writers."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        #: next fault mode to inject, or None (one-shot; tests re-arm).
        self.fault_next_snapshot: str | None = None
        self.faults_injected = 0
        self._fault_rng = random.Random(42)

    def snapshot_fragment(self, key: tuple) -> None:
        super().snapshot_fragment(key)
        mode, self.fault_next_snapshot = self.fault_next_snapshot, None
        if mode is None:
            return
        path = self._snap_path(key)
        if os.path.exists(path):
            corrupt_file(path, mode, rng=self._fault_rng)
            self.faults_injected += 1
