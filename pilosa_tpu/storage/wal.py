"""Binary write-ahead log, one file per fragment.

Reference: the op-log appended to each fragment's data file
(roaring.go:4650 opType add/remove/addBatch/removeBatch, op.WriteTo
:4694 with per-op checksum, replayed on open via op.apply :4671).

Record format (little-endian):
  magic   u16 = 0x504C ("PL")
  op      u8   (1=add 2=remove 3=set_row 4=clear_row)
  n_rows  u32
  n_cols  u32
  crc32   u32  of the payload
  payload n_rows*u64 rows ++ n_cols*u64 cols
Row and column counts are independent so one-row ops (set_row/clear_row)
keep their row id even with zero columns. Torn tails (crash mid-append)
are detected by magic/crc and truncated, exactly the recovery contract
of the reference's checksummed ops.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib

import numpy as np

_MAGIC = 0x504C
_HEADER = struct.Struct("<HBIII")

OP_ADD = 1
OP_REMOVE = 2
OP_SET_ROW = 3
OP_CLEAR_ROW = 4

_OP_CODES = {"add": OP_ADD, "addBatch": OP_ADD,
             "remove": OP_REMOVE, "removeBatch": OP_REMOVE,
             "setRow": OP_SET_ROW, "clearRow": OP_CLEAR_ROW}


class WalWriter:
    """Appender with op counting (MaxOpN snapshot trigger).

    ``fsync_appends=False`` (default) matches the reference's op-log
    durability (user+OS buffered writes, crash may lose the tail);
    True fsyncs for strict durability at a write-latency cost.

    ``group_window`` (seconds, used only with ``fsync_appends``) turns
    per-record fsyncs into GROUP COMMIT: concurrent appenders elect a
    leader that sleeps the window, then issues ONE fsync covering every
    record flushed so far; followers just wait for a sync whose sequence
    covers theirs (the leader-drain shape of httpclient's peer channel).
    Appends hit the file in strict sequence order, and an fsync makes a
    strict prefix durable — so crash recovery sees exactly the torn-tail
    semantics of the per-record mode, never a gap.
    """

    def __init__(self, path: str, fsync_appends: bool = False,
                 group_window: float = 0.0):
        self.path = path
        self.fsync_appends = fsync_appends
        self.group_window = group_window
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")
        self.op_n = 0
        #: fsync() calls issued (bench: fsyncs per Mval imported).
        self.fsyncs = 0
        self._lock = threading.Lock()
        self._sync_cv = threading.Condition(self._lock)
        self._seq = 0          # records written + flushed
        self._seq_synced = 0   # records covered by an fsync
        self._flusher_busy = False

    def append(self, op: str, rows, cols) -> None:
        code = _OP_CODES[op]
        r = np.asarray(rows, dtype=np.uint64)
        c = np.asarray(cols, dtype=np.uint64)
        if code in (OP_ADD, OP_REMOVE) and len(r) != len(c):
            raise ValueError("row/col length mismatch in WAL append")
        if code in (OP_SET_ROW, OP_CLEAR_ROW) and len(r) != 1:
            raise ValueError(f"{op} requires exactly one row id")
        payload = r.tobytes() + c.tobytes()
        with self._lock:
            self._f.write(_HEADER.pack(_MAGIC, code, len(r), len(c),
                                       zlib.crc32(payload) & 0xFFFFFFFF))
            self._f.write(payload)
            self._f.flush()
            self.op_n += 1
            self._seq += 1
            my_seq = self._seq
        if self.fsync_appends:
            if self.group_window > 0:
                self._group_sync(my_seq)
            else:
                os.fsync(self._f.fileno())
                with self._lock:
                    self.fsyncs += 1
                    if my_seq > self._seq_synced:
                        self._seq_synced = my_seq

    def _group_sync(self, my_seq: int) -> None:
        """Block until an fsync covers record ``my_seq``, becoming the
        flush leader if none is active."""
        with self._sync_cv:
            while True:
                if self._seq_synced >= my_seq:
                    return
                if not self._flusher_busy:
                    self._flusher_busy = True
                    break
                self._sync_cv.wait()
        # Leader, outside the lock: let concurrent appenders pile onto
        # this commit, then fsync once for all of them.
        if self.group_window > 0:
            time.sleep(self.group_window)
        with self._lock:
            cover = self._seq  # everything written so far is flushed
        try:
            os.fsync(self._f.fileno())
        finally:
            with self._sync_cv:
                self.fsyncs += 1
                if cover > self._seq_synced:
                    self._seq_synced = cover
                self._flusher_busy = False
                self._sync_cv.notify_all()

    def sync(self) -> None:
        """Flush user+OS buffers so appended records survive a crash."""
        with self._lock:
            self._f.flush()
        os.fsync(self._f.fileno())
        with self._lock:
            self.fsyncs += 1
            if self._seq > self._seq_synced:
                self._seq_synced = self._seq

    def truncate(self) -> None:
        """Called after a snapshot subsumes the log (fragment.go:2393).

        Callers must make the snapshot durable (fsync file + dir) BEFORE
        truncating, or a crash in between loses the fragment.
        """
        with self._lock:
            self._f.seek(0)
            self._f.truncate()
            self._f.flush()
            os.fsync(self._f.fileno())
            self.fsyncs += 1
            self.op_n = 0
            # Truncation subsumes every appended record: release any
            # group-commit waiter still parked on an old sequence.
            self._seq_synced = self._seq
            self._sync_cv.notify_all()

    def close(self) -> None:
        self._f.close()


def scan_wal(path: str) -> dict:
    """Integrity scan distinguishing the two failure shapes a replay
    cannot: a TORN TAIL (crash mid-append; the invalid bytes are the
    file's last record and nothing valid follows) and MID-FILE
    CORRUPTION (a damaged record with intact records after it — replay
    silently drops every op past the damage, so the fragment must be
    quarantined, not trusted).

    Returns ``{"ops", "valid_bytes", "total_bytes", "torn", "corrupt"}``.
    """
    if not os.path.exists(path):
        return {"ops": 0, "valid_bytes": 0, "total_bytes": 0,
                "torn": False, "corrupt": False}
    with open(path, "rb") as f:
        data = f.read()

    def _valid_at(off: int) -> int | None:
        """End offset of a valid record starting at ``off``, else None."""
        if off + _HEADER.size > len(data):
            return None
        magic, _code, n_rows, n_cols, crc = _HEADER.unpack_from(data, off)
        end = off + _HEADER.size + 8 * (n_rows + n_cols)
        if magic != _MAGIC or end > len(data):
            return None
        if (zlib.crc32(data[off + _HEADER.size:end]) & 0xFFFFFFFF) != crc:
            return None
        return end

    ops = 0
    off = 0
    while True:
        end = _valid_at(off)
        if end is None:
            break
        ops += 1
        off = end
    torn = off < len(data)
    corrupt = False
    if torn:
        # Any valid record past the damage proves mid-file corruption
        # (appends are strictly sequential, so bytes after a real torn
        # tail can only be garbage).
        magic_bytes = _MAGIC.to_bytes(2, "little")
        pos = data.find(magic_bytes, off + 1)
        while pos != -1:
            if _valid_at(pos) is not None:
                corrupt = True
                break
            pos = data.find(magic_bytes, pos + 1)
    return {"ops": ops, "valid_bytes": off, "total_bytes": len(data),
            "torn": torn, "corrupt": corrupt}


def iter_wal_records(data: bytes):
    """Yield ``(code, rows, cols)`` from raw WAL bytes, stopping cleanly
    at a torn tail — the shared decode loop behind WalReader and the
    backup subsystem's archived-segment replay (restore/PITR run it over
    bytes fetched from an archive, where no file path exists)."""
    off = 0
    while off + _HEADER.size <= len(data):
        magic, code, n_rows, n_cols, crc = _HEADER.unpack_from(data, off)
        body_len = 8 * (n_rows + n_cols)
        end = off + _HEADER.size + body_len
        if magic != _MAGIC or end > len(data):
            break  # torn tail
        payload = data[off + _HEADER.size: end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break
        rows = np.frombuffer(payload[: 8 * n_rows], dtype=np.uint64)
        cols = np.frombuffer(payload[8 * n_rows:], dtype=np.uint64)
        yield code, rows, cols
        off = end


class WalReader:
    """Replays records; stops cleanly at a torn tail."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        yield from iter_wal_records(data)
