"""Durability: per-fragment snapshot + WAL, schema/attr/translate
persistence, holder reload.

Reference: the op-log + snapshot cycle (roaring.go:4650-4790 op records,
fragment.go:84 MaxOpN, :2296 enqueueSnapshot, :2337-2393 snapshot temp +
rename; holder.go:137 Open walks the data dir). Here the WAL is a binary
record stream per fragment and snapshots are compressed position arrays —
the host-side truth the device stacks are rebuilt from on boot.
"""

from pilosa_tpu.storage.diskstore import DiskStore
from pilosa_tpu.storage.wal import WalReader, WalWriter

__all__ = ["DiskStore", "WalReader", "WalWriter"]
