"""Durability: per-fragment snapshot + WAL, schema/attr/translate
persistence, holder reload, integrity framing + corruption quarantine.

Reference: the op-log + snapshot cycle (roaring.go:4650-4790 op records,
fragment.go:84 MaxOpN, :2296 enqueueSnapshot, :2337-2393 snapshot temp +
rename; holder.go:137 Open walks the data dir). Here the WAL is a binary
record stream per fragment and snapshots are compressed position arrays —
the host-side truth the device stacks are rebuilt from on boot.

Exports resolve lazily (PEP 562): core.attrs/core.translate import the
integrity framing from this package, and an eager diskstore import here
would close the cycle diskstore → core.attrs → storage.
"""

_EXPORTS = {
    "DiskStore": "pilosa_tpu.storage.diskstore",
    "read_snapshot": "pilosa_tpu.storage.diskstore",
    "WalReader": "pilosa_tpu.storage.wal",
    "WalWriter": "pilosa_tpu.storage.wal",
    "scan_wal": "pilosa_tpu.storage.wal",
    "SnapshotCorruptError": "pilosa_tpu.storage.integrity",
    "LineCorruptError": "pilosa_tpu.storage.integrity",
    "snapshot_footer": "pilosa_tpu.storage.integrity",
    "split_snapshot": "pilosa_tpu.storage.integrity",
    "frame_line": "pilosa_tpu.storage.integrity",
    "parse_line": "pilosa_tpu.storage.integrity",
    "QuarantineRegistry": "pilosa_tpu.storage.quarantine",
    "ShardCorruptError": "pilosa_tpu.storage.quarantine",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(mod), name)
