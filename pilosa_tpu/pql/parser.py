"""Recursive-descent PQL parser.

Implements the grammar in the reference's pql/pql.peg (84 lines) without a
parser generator. Ordered-choice semantics are kept where they matter:
special call forms (Set/SetRowAttrs/.../Range) are tried first and fall
back to the generic ``IDENT(allargs)`` rule, exactly like PEG backtracking.
"""

from __future__ import annotations

import re
from typing import Any, Callable, NoReturn

from pilosa_tpu.pql.ast import (
    BETWEEN, EQ, GT, GTE, LT, LTE, NEQ, Call, Condition, Query,
)


class ParseError(Exception):
    def __init__(self, msg: str, pos: int = -1) -> None:
        super().__init__(f"parse error at {pos}: {msg}" if pos >= 0 else msg)
        self.pos = pos


class SemanticError(ParseError):
    """A definitive error (e.g. duplicate argument) that backtracking must
    not swallow — PEG ordered choice only retries on *syntax* failure."""


_IDENT_RE = re.compile(r"[A-Za-z][A-Za-z0-9]*")
_FIELD_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_RESERVED_RE = re.compile(r"_row|_col|_start|_end|_timestamp|_field")
_UINT_RE = re.compile(r"0|[1-9][0-9]*")
_INT_RE = re.compile(r"-?(?:0|[1-9][0-9]*)")
_NUM_RE = re.compile(r"-?(?:[0-9]+(?:\.[0-9]*)?|\.[0-9]+)")
_TIMESTAMP_RE = re.compile(r"[0-9]{4}-[01][0-9]-[0-3][0-9]T[0-9]{2}:[0-9]{2}")
_BARESTR_RE = re.compile(r"[A-Za-z0-9\-_:]+")
_COND_RE = re.compile(r"><|<=|>=|==|!=|<|>")
_COND_OPS = {"><": BETWEEN, "<=": LTE, ">=": GTE, "==": EQ, "!=": NEQ,
             "<": LT, ">": GT}

DUPLICATE_ARG_ERROR = "duplicate argument provided"


class _Parser:
    def __init__(self, src: str) -> None:
        self.src = src
        self.pos = 0

    # -- low-level ---------------------------------------------------------

    def error(self, msg: str) -> NoReturn:
        raise ParseError(msg, self.pos)

    def eof(self) -> bool:
        return self.pos >= len(self.src)

    def peek(self) -> str:
        return self.src[self.pos] if self.pos < len(self.src) else ""

    def sp(self) -> None:
        while self.pos < len(self.src) and self.src[self.pos] in " \t\n\r":
            self.pos += 1

    def lit(self, s: str) -> bool:
        if self.src.startswith(s, self.pos):
            self.pos += len(s)
            return True
        return False

    def expect(self, s: str) -> None:
        if not self.lit(s):
            self.error(f"expected {s!r}")

    def rx(self, pattern: re.Pattern[str]) -> str | None:
        m = pattern.match(self.src, self.pos)
        if m is None:
            return None
        self.pos = m.end()
        return m.group(0)

    def comma(self) -> bool:
        save = self.pos
        self.sp()
        if self.lit(","):
            self.sp()
            return True
        self.pos = save
        return False

    def open(self) -> None:
        self.expect("(")
        self.sp()

    def close(self) -> None:
        self.sp()
        self.expect(")")

    def _quoted(self, quote: str) -> str:
        """Body of a quoted string with backslash escapes."""
        out = []
        while True:
            c = self.peek()
            if c == "":
                self.error("unterminated string")
            if c == "\\":
                nxt = self.src[self.pos + 1 : self.pos + 2]
                if nxt in (quote, "\\"):
                    out.append(nxt)
                    self.pos += 2
                    continue
            if c == quote:
                self.pos += 1
                return "".join(out)
            out.append(c)
            self.pos += 1

    # -- grammar -----------------------------------------------------------

    def parse(self) -> Query:
        q = Query()
        self.sp()
        while not self.eof():
            q.calls.append(self.call())
            self.sp()
        return q

    def call(self) -> Call:
        save = self.pos
        name = self.rx(_IDENT_RE)
        if name is None:
            self.error("expected call name")
        special: Callable[[], Call] | None = getattr(
            self, f"_call_{name}", None)
        if special is not None:
            try:
                return special()
            except SemanticError:
                raise
            except ParseError:
                self.pos = save + len(name)  # fall back to generic form
        return self._call_generic(name)

    def _call_generic(self, name: str) -> Call:
        call = Call(name)
        self.open()
        self.allargs(call)
        self.comma()  # optional trailing comma
        self.close()
        return call

    # - special forms (ordered before generic, as in the PEG) -

    def _call_Set(self) -> Call:
        call = Call("Set")
        self.open()
        self._pos_col(call)
        if not self.comma():
            self.error("expected ','")
        self.args(call)
        if self.comma():
            ts = self._timestampfmt()
            if ts is None:
                self.error("expected timestamp")
            call.args["_timestamp"] = ts
        self.close()
        return call

    def _call_SetRowAttrs(self) -> Call:
        call = Call("SetRowAttrs")
        self.open()
        f = self.rx(_FIELD_RE)
        if f is None:
            self.error("expected field")
        call.args["_field"] = f
        if not self.comma():
            self.error("expected ','")
        self._pos_row(call)
        if not self.comma():
            self.error("expected ','")
        self.args(call)
        self.close()
        return call

    def _call_SetColumnAttrs(self) -> Call:
        call = Call("SetColumnAttrs")
        self.open()
        self._pos_col(call)
        if not self.comma():
            self.error("expected ','")
        self.args(call)
        self.close()
        return call

    def _call_Clear(self) -> Call:
        call = Call("Clear")
        self.open()
        self._pos_col(call)
        if not self.comma():
            self.error("expected ','")
        self.args(call)
        self.close()
        return call

    def _call_ClearRow(self) -> Call:
        call = Call("ClearRow")
        self.open()
        self.arg(call)
        self.close()
        return call

    def _call_Store(self) -> Call:
        call = Call("Store")
        self.open()
        call.children.append(self.call())
        if not self.comma():
            self.error("expected ','")
        self.arg(call)
        self.close()
        return call

    def _call_TopN(self) -> Call:
        return self._posfield_call("TopN")

    def _call_SimilarTopN(self) -> Call:
        return self._posfield_call("SimilarTopN")

    def _call_Rows(self) -> Call:
        return self._posfield_call("Rows")

    def _posfield_call(self, name: str) -> Call:
        call = Call(name)
        self.open()
        f = self.rx(_FIELD_RE)
        if f is None:
            self.error("expected field")
        call.args["_field"] = f
        if self.comma():
            self.allargs(call)
        self.close()
        return call

    def _call_Range(self) -> Call:
        """Time-range form: Range(f=1, from=ts, to=ts). The condition form
        Range(f > 5) backtracks to the generic rule."""
        call = Call("Range")
        self.open()
        f = self.rx(_FIELD_RE) or self.rx(_RESERVED_RE)
        if f is None:
            self.error("expected field")
        self.sp()
        self.expect("=")
        self.sp()
        call.args[f] = self.value()
        if not self.comma():
            self.error("expected ','")
        self.lit("from=")
        ts = self._timestampfmt()
        if ts is None:
            self.error("expected timestamp")
        call.args["from"] = ts
        if not self.comma():
            self.error("expected ','")
        self.lit("to=")
        self.sp()
        ts = self._timestampfmt()
        if ts is None:
            self.error("expected timestamp")
        call.args["to"] = ts
        self.close()
        return call

    # - positional helpers -

    def _pos_col(self, call: Call) -> None:
        self._pos_arg(call, "_col")

    def _pos_row(self, call: Call) -> None:
        self._pos_arg(call, "_row")

    def _pos_arg(self, call: Call, key: str) -> None:
        u = self.rx(_UINT_RE)
        if u is not None:
            call.args[key] = int(u)
            return
        if self.lit("'"):
            call.args[key] = self._quoted("'")
            return
        if self.lit('"'):
            call.args[key] = self._quoted('"')
            return
        self.error(f"expected {key}")

    def _timestampfmt(self) -> str | None:
        save = self.pos
        if self.lit('"'):
            ts = self.rx(_TIMESTAMP_RE)
            if ts is not None and self.lit('"'):
                return ts
            self.pos = save
            return None
        if self.lit("'"):
            ts = self.rx(_TIMESTAMP_RE)
            if ts is not None and self.lit("'"):
                return ts
            self.pos = save
            return None
        return self.rx(_TIMESTAMP_RE)

    # - args -

    def allargs(self, call: Call) -> None:
        """allargs <- Call (comma Call)* (comma args)? / args / sp"""
        save = self.pos
        m = _IDENT_RE.match(self.src, self.pos)
        if m is not None:
            # A child call iff the ident is followed by '(' — otherwise it's
            # an arg key (e.g. `field=...`) or bare value.
            after = self.src[m.end() : m.end() + 1]
            look = m.end()
            while after in (" ", "\t", "\n"):
                look += 1
                after = self.src[look : look + 1]
            if after == "(":
                call.children.append(self.call())
                while True:
                    save2 = self.pos
                    if not self.comma():
                        return
                    m2 = _IDENT_RE.match(self.src, self.pos)
                    is_call = False
                    if m2 is not None:
                        look = m2.end()
                        nxt = self.src[look : look + 1]
                        while nxt in (" ", "\t", "\n"):
                            look += 1
                            nxt = self.src[look : look + 1]
                        is_call = nxt == "("
                    if is_call:
                        call.children.append(self.call())
                    else:
                        self.pos = save2
                        if self.comma():
                            self.sp()
                            if self.peek() in (")", ""):
                                self.pos = save2  # trailing comma: caller's
                            else:
                                self.args(call)
                        return
        self.pos = save
        self.sp()
        if self.peek() not in (")", ""):
            self.args(call)

    def args(self, call: Call) -> None:
        """args <- arg (comma args)? sp"""
        self.arg(call)
        while True:
            save = self.pos
            if not self.comma():
                break
            self.sp()
            if self.peek() in (")", ""):
                self.pos = save
                break
            # Trailing comma before close is handled by caller.
            try:
                self.arg(call)
            except SemanticError:
                raise
            except ParseError:
                self.pos = save
                break
        self.sp()

    def arg(self, call: Call) -> None:
        # conditional: int <(=) field <(=) int
        save = self.pos
        cond = self._try_conditional()
        if cond is not None:
            field, c = cond
            self._set_arg(call, field, c)
            return
        self.pos = save
        field = self.rx(_FIELD_RE) or self.rx(_RESERVED_RE)
        if field is None:
            self.error("expected argument")
        self.sp()
        if self.lit("="):
            # Guard against '==' which is a COND.
            if self.peek() == "=":
                self.pos -= 1
            else:
                self.sp()
                self._set_arg(call, field, self.value())
                return
        op = self.rx(_COND_RE)
        if op is None:
            self.error("expected '=' or comparison operator")
        self.sp()
        val = self.value()
        self._set_arg(call, field, Condition(_COND_OPS[op], val))

    def _try_conditional(self) -> tuple[str, Condition] | None:
        """conditional <- condint condLT condfield condLT condint
        e.g. ``4 < f <= 10`` → f: BETWEEN [5, 10] (bounds normalized
        inclusive, reference ast.go endConditional)."""
        lo_s = self.rx(_INT_RE)
        if lo_s is None:
            return None
        self.sp()
        op1 = "<=" if self.lit("<=") else ("<" if self.lit("<") else None)
        if op1 is None:
            return None
        self.sp()
        field = self.rx(_FIELD_RE)
        if field is None:
            return None
        self.sp()
        op2 = "<=" if self.lit("<=") else ("<" if self.lit("<") else None)
        if op2 is None:
            return None
        self.sp()
        hi_s = self.rx(_INT_RE)
        if hi_s is None:
            return None
        self.sp()
        low, high = int(lo_s), int(hi_s)
        if op1 == "<":
            low += 1
        if op2 == "<":
            high -= 1
        return field, Condition(BETWEEN, [low, high])

    def _set_arg(self, call: Call, key: str, value: Any) -> None:
        if key in call.args:
            raise SemanticError(f"{DUPLICATE_ARG_ERROR}: {key}", self.pos)
        call.args[key] = value

    # - values -

    def value(self) -> Any:
        if self.lit("["):
            self.sp()
            items: list[Any] = []
            self.sp()
            if not self.src.startswith("]", self.pos):
                items.append(self.item())
                while self.comma():
                    items.append(self.item())
            self.sp()
            self.expect("]")
            self.sp()
            return items
        return self.item()

    def _at_item_boundary(self) -> bool:
        save = self.pos
        self.sp()
        c = self.peek()
        self.pos = save
        return c in (",", ")", "]", "")

    def item(self) -> Any:
        # Keyword literals, only when followed by a boundary.
        for kw, val in (("null", None), ("true", True), ("false", False)):
            if self.src.startswith(kw, self.pos):
                save = self.pos
                self.pos += len(kw)
                if self._at_item_boundary():
                    return val
                self.pos = save
        ts = self._timestampfmt()
        if ts is not None:
            return ts
        num = self.rx(_NUM_RE)
        if num is not None:
            # Bare strings like 1-2-3 must not half-match as a number.
            if self.peek() not in "" and _BARESTR_RE.match(self.peek()):
                self.pos -= len(num)
            else:
                return float(num) if "." in num else int(num)
        # Nested call?
        m = _IDENT_RE.match(self.src, self.pos)
        if m is not None:
            look = m.end()
            nxt = self.src[look : look + 1]
            while nxt in (" ", "\t", "\n"):
                look += 1
                nxt = self.src[look : look + 1]
            if nxt == "(":
                return self.call()
        bare = self.rx(_BARESTR_RE)
        if bare is not None:
            return bare
        if self.lit('"'):
            return self._quoted('"')
        if self.lit("'"):
            return self._quoted("'")
        self.error("expected value")  # noqa: RET503 - error() is NoReturn


def parse(src: str) -> Query:
    """Parse a PQL string into a Query (reference pql.NewParser(...).Parse())."""
    return _Parser(src).parse()
