"""PQL AST: Query → Call tree with typed args.

Reference: pql/ast.go (Query :27, Call :263, Condition :482, token ops
pql/token.go). Values in ``Call.args`` are Python natives: int, float,
bool, None, str, list, nested ``Call``, or ``Condition``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# Condition operator tokens (reference pql/token.go ILLEGAL..BETWEEN).
EQ = "=="
NEQ = "!="
LT = "<"
LTE = "<="
GT = ">"
GTE = ">="
BETWEEN = "><"

#: the reference's writeCallN set (ast.go) — kept for its exact parity.
_WRITE_CALLS = frozenset({"Set", "Clear", "SetRowAttrs", "SetColumnAttrs"})
#: every call that mutates state, for cacheability decisions.
WRITE_CALLS = frozenset({"Set", "Clear", "ClearRow", "Store",
                         "SetRowAttrs", "SetColumnAttrs"})


def is_reserved_arg(name: str) -> bool:
    """Reference IsReservedArg (ast.go:283): leading '_' or from/to."""
    return name.startswith("_") or name in ("from", "to")


@dataclass
class Condition:
    """A comparison bound to an arg: ``field >< [1, 10]`` etc.
    Reference: pql/ast.go:482."""

    op: str
    value: Any

    def int_slice_value(self) -> list[int]:
        if not isinstance(self.value, list):
            raise ValueError(f"unexpected condition value {self.value!r}")
        return [int(v) for v in self.value]

    def __str__(self) -> str:
        return f"{self.op} {format_value(self.value)}"


@dataclass
class Call:
    """One function call. Reference: pql/ast.go:263."""

    name: str
    args: dict[str, Any] = field(default_factory=dict)
    children: list["Call"] = field(default_factory=list)

    # -- typed arg accessors (reference ast.go:270-460) --------------------

    def field_arg(self) -> str:
        """The single non-reserved arg key, e.g. the f in Set(1, f=2)."""
        for k in self.args:
            if not is_reserved_arg(k):
                return k
        raise ValueError("no field argument specified")

    def bool_arg(self, key: str) -> tuple[bool, bool]:
        if key not in self.args:
            return False, False
        v = self.args[key]
        if not isinstance(v, bool):
            raise ValueError(f"could not convert {v!r} to bool")
        return v, True

    def uint_arg(self, key: str) -> tuple[int, bool]:
        if key not in self.args:
            return 0, False
        v = self.args[key]
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(f"could not convert {v!r} to uint64")
        if v < 0:
            raise ValueError(f"value for '{key}' must be positive, but got {v}")
        return v, True

    def int_arg(self, key: str) -> tuple[int, bool]:
        if key not in self.args:
            return 0, False
        v = self.args[key]
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(f"could not convert {v!r} to int64")
        return v, True

    def uint_slice_arg(self, key: str) -> tuple[list[int] | None, bool]:
        if key not in self.args:
            return None, False
        v = self.args[key]
        if not isinstance(v, list):
            raise ValueError(f"unexpected type in uint_slice_arg: {v!r}")
        return [int(x) for x in v], True

    def call_arg(self, key: str) -> tuple["Call | None", bool]:
        if key not in self.args:
            return None, False
        v = self.args[key]
        if not isinstance(v, Call):
            raise ValueError(f"could not convert {v!r} to Call")
        return v, True

    def string_arg(self, key: str) -> tuple[str | None, bool]:
        if key not in self.args:
            return None, False
        v = self.args[key]
        if not isinstance(v, str):
            raise ValueError(f"could not convert {v!r} to string")
        return v, True

    def has_condition_arg(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def clone(self) -> "Call":
        # Deep-clone Call-valued args (and Calls nested inside list args):
        # translation rewrites arg values in place, so a shallow copy would
        # let one index's translated ids leak into the parse-cached tree.
        def _clone_val(v: Any) -> Any:
            if isinstance(v, Call):
                return v.clone()
            if isinstance(v, list):
                return [_clone_val(x) for x in v]
            if isinstance(v, Condition):
                return Condition(op=v.op, value=_clone_val(v.value))
            return v

        return Call(
            name=self.name,
            args={k: _clone_val(v) for k, v in self.args.items()},
            children=[c.clone() for c in self.children],
        )

    def __str__(self) -> str:
        parts = [str(c) for c in self.children]
        for key in sorted(self.args):
            v = self.args[key]
            if isinstance(v, Condition):
                parts.append(f"{key} {v}")
            else:
                parts.append(f"{key}={format_value(v)}")
        return f"{self.name or '!UNNAMED'}({', '.join(parts)})"


@dataclass
class Query:
    """A parsed PQL query: one or more top-level calls (ast.go:27)."""

    calls: list[Call] = field(default_factory=list)

    def write_call_n(self) -> int:
        return sum(1 for c in self.calls if c.name in _WRITE_CALLS)

    def has_writes(self) -> bool:
        """True if ANY call anywhere in the tree mutates state (writes
        can hide under wrappers like Options(...))."""
        def walk(c: "Call") -> bool:
            return c.name in WRITE_CALLS or any(walk(ch)
                                                for ch in c.children)
        return any(walk(c) for c in self.calls)

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)


def format_value(v: Any) -> str:
    if isinstance(v, str):
        return f'"{v}"'
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, list):
        return "[" + ",".join(format_value(x) for x in v) + "]"
    return str(v)
