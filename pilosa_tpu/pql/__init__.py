"""PQL — the Pilosa Query Language.

Reference: pql/ (grammar pql/pql.peg, AST pql/ast.go, generated PEG parser
pql/pql.peg.go). Here the grammar is implemented as a hand-written
tokenizer + recursive-descent parser (parser.py) producing the same Call
tree shape (ast.py); there is no code generation step.
"""

from pilosa_tpu.pql.ast import (
    BETWEEN,
    EQ,
    GT,
    GTE,
    LT,
    LTE,
    NEQ,
    Call,
    Condition,
    Query,
    is_reserved_arg,
)
from pilosa_tpu.pql.parser import ParseError, parse

__all__ = [
    "BETWEEN", "EQ", "GT", "GTE", "LT", "LTE", "NEQ",
    "Call", "Condition", "Query", "is_reserved_arg",
    "ParseError", "parse",
]
