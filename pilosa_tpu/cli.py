"""Command-line interface.

Reference: cmd/ (cobra tree, cmd/root.go:28 — server, import, export,
check, inspect, config, generate-config) with command bodies in ctl/.
Config precedence matches the reference (cmd/root.go:46-60):
flags > env (PILOSA_TPU_*) > TOML file.

Usage::

    python -m pilosa_tpu.cli server --bind 127.0.0.1:10101 --data-dir ./data
    python -m pilosa_tpu.cli import --host ... <index> <field> rows.csv
    python -m pilosa_tpu.cli export --host ... <index> <field>
    python -m pilosa_tpu.cli check ./data
    python -m pilosa_tpu.cli inspect ./data
    python -m pilosa_tpu.cli config | generate-config
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

_DEFAULTS = {
    "bind": "127.0.0.1:10101",
    "data_dir": "",
    "peers": "",
    "replica_n": 1,
    "anti_entropy_interval": 10.0,
    "check_nodes_interval": 5.0,
    # Quorum fencing: a fenced minority node refuses all external
    # traffic with 503. True opts queries/exports out of the fence
    # (stale reads stay available; writes and schema stay fenced).
    "fence_stale_reads": False,
    # Background integrity scrub: re-verify snapshot CRCs + repair
    # quarantined fragments from replicas (0 disables).
    "scrub_interval": 60.0,
    # Unattended backups: every backup_interval seconds the coordinator
    # captures an incremental into archive_url (a directory path or an
    # s3-style http(s)://host:port/bucket[/prefix] URL; empty disables),
    # opening a fresh full chain every backup_full_every runs and
    # pruning superseded chains down to backup_keep_chains.
    "backup_interval": 0.0,
    "archive_url": "",
    "backup_full_every": 8,
    "backup_keep_chains": 2,
    # WAL records per fragment before a background snapshot triggers
    # (reference MaxOpN, fragment.go:84).
    "max_op_n": 10_000,
    # Cap on preserved *.quarantine evidence files per fragment; the
    # oldest are pruned after a successful scrub repair (0 keeps all).
    "quarantine_keep_n": 0,
    "join": "",
    "tls_cert": "",
    "tls_key": "",
    "tls_ca_cert": "",
    "tls_skip_verify": "",
    "trace_endpoint": "",
    "planner": True,
    # Buffer-pool pre-fault at boot, MB (native recycled page pool; see
    # roaring_codec.cpp). Imports allocate block/staging buffers from
    # recycled fault-warm pages instead of paying first-touch faults —
    # the classic database buffer-pool reserve.
    "import_pool_mb": 512,
    # QoS (pilosa_tpu.qos): concurrency gate on query dispatch, bounded
    # admission queue (excess load sheds with 503 + Retry-After), and a
    # default per-query deadline (seconds; 0 = none). The gate is ON
    # for the CLI server — set qos_max_concurrent = 0 to disable.
    "qos_max_concurrent": 32,
    "qos_max_queue": 64,
    "qos_internal_reserve": 4,
    "qos_default_deadline": 0.0,
    "qos_slow_query_ms": 500.0,
    # Kernel warmup at node start: comma-separated kernel families
    # ("count,topn,bsi"; "" disables) compiled for each shard-count
    # bucket, so steady traffic never pays the cold XLA compile.
    "qos_warmup": "count,topn,bsi",
    "qos_warmup_shards": "1,8,32",
    # Overload resilience. Adaptive concurrency: qos_max_concurrent is
    # the CEILING; the operative limit is measured (AIMD over admitted
    # queue-wait/latency). Per-tenant token buckets (req/s per API key
    # or index; 0 disables; rejections are 429 + Retry-After, distinct
    # from the gate's 503 shed).
    "qos_adaptive": True,
    "qos_tenant_rate": 0.0,
    "qos_tenant_burst": 0.0,
    # Per-peer circuit breakers on the inter-node client: this many
    # consecutive connection failures / deadline overruns open the
    # breaker (0 disables); after the cooldown one half-open probe
    # re-closes it.
    "breaker_threshold": 5,
    "breaker_cooldown": 5.0,
    # Hedged reads on replicated legs: a backup request to the next
    # replica after hedge_delay_ms (0 = measured p95), first success
    # wins, bounded to ~hedge_budget_pct% of primary legs.
    "hedge": True,
    "hedge_delay_ms": 0.0,
    "hedge_budget_pct": 5.0,
    # Chaos fault injection (POST /internal/fault): OFF unless the
    # operator opts in — the route lets any client that can reach the
    # port inject per-query latency, so it must never ship armed.
    "chaos_faults": False,
    # Persistent XLA compilation cache directory. "" resolves to
    # <data-dir>/compile-cache (memory-only when no data dir); "off"
    # disables. A restarted node reloads every kernel compiled by
    # prior runs instead of paying the cold trace+compile.
    "compile_cache_dir": "",
    # Plan-shape bucketing policy: "pow2" rounds stack heights up to
    # power-of-two buckets (zero-padded, bit-identical results) so a
    # never-seen shard count dispatches into an already-compiled
    # kernel; "none" pads only to the device-mesh multiple.
    "plan_buckets": "pow2",
    # Plan-keyed result cache budget, MB (0 disables) and TTL backstop,
    # seconds (0 = epoch-invalidation only). The TTL exists for the
    # cross-node staleness window (a lost index-dirty broadcast), not
    # as the primary invalidation mechanism.
    "result_cache_mb": 64,
    "result_cache_ttl": 0.0,
    # Device-side fold of remote bitmap legs: "auto" picks host vs
    # device by a measured size crossover; "on"/"off" force a side
    # (results are bit-identical either way).
    "device_reduce": "auto",
    # Coalesce concurrent outbound legs to one peer into a single
    # multiplexed request (POST /internal/query-mux). Peers that don't
    # speak the envelope automatically get per-query requests.
    "multiplex": True,
    # Device-side BSI bit-plane transpose for bulk value imports:
    # "auto" picks host vs device by batch size (bit-identical).
    "ingest_transpose": "auto",
    # WAL group commit: fsync window in ms when fsync-per-append is on
    # (0 = one fsync per append; concurrent appends share one fsync).
    "wal_group_commit_ms": 0.0,
    # Import-stream in-flight byte budget, MB (0 = unbounded); over
    # budget trips 429 + Retry-After instead of queueing.
    "ingest_max_inflight_mb": 0,
    # Query-dispatch pipeline (README "Query dispatch"). Fuse: hot read
    # plans (Count trees, BSI Sum/Min/Max) trace to ONE jitted device
    # program per query ("auto" resolves to on; "off" restores the
    # stepped path, bit-identical). Coalesce: concurrent dispatches of
    # the same plan signature batch into one launch within a sub-ms
    # window ("auto" batches only while a same-plan launch is in
    # flight; "on" always waits the window). Inline transfer: a solo
    # waiter steals its own device->host wave instead of hopping
    # through the resolver thread ("auto" steals only when the queue
    # has a single entry).
    "dispatch_fuse": "auto",
    "dispatch_coalesce": "auto",
    "dispatch_coalesce_us": 150.0,
    "inline_transfer": "auto",
    # Device residency: packed [S, K] index stacks for low-cardinality
    # rows ("auto" packs only rows at least 8x smaller than the dense
    # plane; bit-identical) and the pipelined async upload path for
    # non-resident leaf stacks.
    "residency_packed": "auto",
    "prefetch": "on",
    # Device key planes (pilosa_tpu/exec/keyplane): forward key
    # translation via a resident sorted-hash plane for large keyed
    # batches ("auto" probes on device only for batches of 256+ keys;
    # "off" keeps the lock-free host snapshot path only).
    "translate_planes": "auto",
    # Approximate analytics (pilosa_tpu/sketch): HLL precision for
    # Count(Distinct(...)) — 2^p registers, ~1.04/sqrt(2^p) relative
    # error — and the estimated cardinality below which the answer is
    # computed exactly instead (0 disables the exact fallback).
    "sketch_precision": 12,
    "sketch_exact_threshold": 1024,
    # Per-query cost profiles: retain the slowest N at /debug/queries
    # (0 disables the ring). profile_queries=False limits profiling to
    # explicit ?profile=true requests.
    "profile_ring_n": 64,
    "profile_queries": True,
}


def _load_config(path: str | None) -> dict:
    cfg = dict(_DEFAULTS)
    if path:
        import tomllib
        with open(path, "rb") as f:
            for k, v in tomllib.load(f).items():
                cfg[k.replace("-", "_")] = v
    for k in cfg:
        env = os.environ.get(f"PILOSA_TPU_{k.upper()}")
        if env is not None:
            cur = cfg[k]
            if isinstance(cur, bool):
                cfg[k] = env.lower() in ("1", "true", "yes")
            elif isinstance(cur, int):
                cfg[k] = int(env)
            elif isinstance(cur, float):
                cfg[k] = float(env)
            else:
                cfg[k] = env
    return cfg


def cmd_server(args) -> int:
    cfg = _load_config(args.config)
    if args.bind:
        cfg["bind"] = args.bind
    if args.data_dir:
        cfg["data_dir"] = args.data_dir
    if args.peers:
        cfg["peers"] = args.peers
    if args.replica_n:
        cfg["replica_n"] = args.replica_n
    if args.no_planner:
        cfg["planner"] = False
    if args.join:
        cfg["join"] = args.join
    if args.tls_cert:
        cfg["tls_cert"] = args.tls_cert
    if args.tls_key:
        cfg["tls_key"] = args.tls_key
    if args.tls_ca_cert:
        cfg["tls_ca_cert"] = args.tls_ca_cert
    if args.tls_skip_verify:
        cfg["tls_skip_verify"] = "true"
    if args.trace_endpoint:
        cfg["trace_endpoint"] = args.trace_endpoint
    if args.import_pool_mb is not None:
        cfg["import_pool_mb"] = args.import_pool_mb
    if args.qos_max_concurrent is not None:
        cfg["qos_max_concurrent"] = args.qos_max_concurrent
    if args.qos_max_queue is not None:
        cfg["qos_max_queue"] = args.qos_max_queue
    if args.qos_default_deadline is not None:
        cfg["qos_default_deadline"] = args.qos_default_deadline
    if args.qos_warmup is not None:
        cfg["qos_warmup"] = args.qos_warmup
    if args.scrub_interval is not None:
        cfg["scrub_interval"] = args.scrub_interval
    if args.backup_interval is not None:
        cfg["backup_interval"] = args.backup_interval
    if args.archive_url is not None:
        cfg["archive_url"] = args.archive_url
    if args.backup_full_every is not None:
        cfg["backup_full_every"] = args.backup_full_every
    if args.backup_keep_chains is not None:
        cfg["backup_keep_chains"] = args.backup_keep_chains
    if args.max_op_n is not None:
        cfg["max_op_n"] = args.max_op_n
    if args.quarantine_keep_n is not None:
        cfg["quarantine_keep_n"] = args.quarantine_keep_n
    if args.qos_adaptive is not None:
        cfg["qos_adaptive"] = args.qos_adaptive == "on"
    if args.qos_tenant_rate is not None:
        cfg["qos_tenant_rate"] = args.qos_tenant_rate
    if args.qos_tenant_burst is not None:
        cfg["qos_tenant_burst"] = args.qos_tenant_burst
    if args.breaker_threshold is not None:
        cfg["breaker_threshold"] = args.breaker_threshold
    if args.breaker_cooldown is not None:
        cfg["breaker_cooldown"] = args.breaker_cooldown
    if args.hedge is not None:
        cfg["hedge"] = args.hedge == "on"
    if args.hedge_delay_ms is not None:
        cfg["hedge_delay_ms"] = args.hedge_delay_ms
    if args.hedge_budget_pct is not None:
        cfg["hedge_budget_pct"] = args.hedge_budget_pct
    if args.chaos_faults:
        cfg["chaos_faults"] = True
    if args.fence_stale_reads:
        cfg["fence_stale_reads"] = True
    if args.compile_cache_dir is not None:
        cfg["compile_cache_dir"] = args.compile_cache_dir
    if args.plan_buckets is not None:
        cfg["plan_buckets"] = args.plan_buckets
    if args.result_cache_mb is not None:
        cfg["result_cache_mb"] = args.result_cache_mb
    if args.result_cache_ttl is not None:
        cfg["result_cache_ttl"] = args.result_cache_ttl
    if args.device_reduce is not None:
        cfg["device_reduce"] = args.device_reduce
    if args.multiplex is not None:
        cfg["multiplex"] = args.multiplex == "on"
    if args.ingest_transpose is not None:
        cfg["ingest_transpose"] = args.ingest_transpose
    if args.wal_group_commit_ms is not None:
        cfg["wal_group_commit_ms"] = args.wal_group_commit_ms
    if args.ingest_max_inflight_mb is not None:
        cfg["ingest_max_inflight_mb"] = args.ingest_max_inflight_mb
    if args.dispatch_fuse is not None:
        cfg["dispatch_fuse"] = args.dispatch_fuse
    if args.dispatch_coalesce is not None:
        cfg["dispatch_coalesce"] = args.dispatch_coalesce
    if args.dispatch_coalesce_us is not None:
        cfg["dispatch_coalesce_us"] = args.dispatch_coalesce_us
    if args.inline_transfer is not None:
        cfg["inline_transfer"] = args.inline_transfer
    if args.residency_packed is not None:
        cfg["residency_packed"] = args.residency_packed
    if args.prefetch is not None:
        cfg["prefetch"] = args.prefetch
    if args.translate_planes is not None:
        cfg["translate_planes"] = args.translate_planes
    if args.sketch_precision is not None:
        cfg["sketch_precision"] = args.sketch_precision
    if args.sketch_exact_threshold is not None:
        cfg["sketch_exact_threshold"] = args.sketch_exact_threshold
    if args.profile_ring is not None:
        cfg["profile_ring_n"] = args.profile_ring
    if args.profile_queries is not None:
        cfg["profile_queries"] = args.profile_queries

    from pilosa_tpu.server.node import ServerNode
    node = ServerNode(
        bind=cfg["bind"],
        peers=[p for p in str(cfg["peers"]).split(",") if p],
        replica_n=int(cfg["replica_n"]),
        use_planner=bool(cfg["planner"]),
        anti_entropy_interval=float(cfg["anti_entropy_interval"]),
        check_nodes_interval=float(cfg["check_nodes_interval"]),
        scrub_interval=float(cfg["scrub_interval"]),
        backup_interval=float(cfg["backup_interval"]),
        archive_url=str(cfg["archive_url"]) or None,
        backup_full_every=int(cfg["backup_full_every"]),
        backup_keep_chains=int(cfg["backup_keep_chains"]),
        max_op_n=int(cfg["max_op_n"]),
        join=str(cfg["join"]) or None,
        data_dir=cfg["data_dir"] or None,
        tls_cert=str(cfg["tls_cert"]) or None,
        tls_key=str(cfg["tls_key"]) or None,
        tls_ca_cert=str(cfg["tls_ca_cert"]) or None,
        tls_skip_verify=(str(cfg["tls_skip_verify"]).lower()
                         in ("1", "true", "yes")
                         if str(cfg["tls_skip_verify"]) else None),
        trace_endpoint=str(cfg["trace_endpoint"]) or None,
        import_pool_mb=int(cfg["import_pool_mb"]),
        qos_max_concurrent=int(cfg["qos_max_concurrent"]),
        qos_max_queue=int(cfg["qos_max_queue"]),
        qos_internal_reserve=int(cfg["qos_internal_reserve"]),
        qos_default_deadline=float(cfg["qos_default_deadline"]),
        qos_slow_query_ms=float(cfg["qos_slow_query_ms"]),
        qos_warmup=str(cfg["qos_warmup"]),
        qos_warmup_shards=str(cfg["qos_warmup_shards"]),
        quarantine_keep_n=int(cfg["quarantine_keep_n"]),
        qos_adaptive=bool(cfg["qos_adaptive"]),
        qos_tenant_rate=float(cfg["qos_tenant_rate"]),
        qos_tenant_burst=float(cfg["qos_tenant_burst"]),
        breaker_threshold=int(cfg["breaker_threshold"]),
        breaker_cooldown=float(cfg["breaker_cooldown"]),
        hedge=bool(cfg["hedge"]),
        hedge_delay_ms=float(cfg["hedge_delay_ms"]),
        hedge_budget_pct=float(cfg["hedge_budget_pct"]),
        chaos_faults=bool(cfg["chaos_faults"]),
        fence_stale_reads=(str(cfg["fence_stale_reads"]).lower()
                           in ("1", "true", "yes", "on")),
        compile_cache_dir=str(cfg["compile_cache_dir"]) or None,
        plan_buckets=str(cfg["plan_buckets"]) or "pow2",
        result_cache_mb=int(cfg["result_cache_mb"]),
        result_cache_ttl=float(cfg["result_cache_ttl"]),
        device_reduce=str(cfg["device_reduce"]) or "auto",
        multiplex=(str(cfg["multiplex"]).lower()
                   in ("1", "true", "yes", "on")),
        ingest_transpose=str(cfg["ingest_transpose"]) or "auto",
        wal_group_commit_ms=float(cfg["wal_group_commit_ms"]),
        ingest_max_inflight_mb=int(cfg["ingest_max_inflight_mb"]),
        dispatch_fuse=str(cfg["dispatch_fuse"]) or "auto",
        dispatch_coalesce=str(cfg["dispatch_coalesce"]) or "auto",
        dispatch_coalesce_us=float(cfg["dispatch_coalesce_us"]),
        inline_transfer=str(cfg["inline_transfer"]) or "auto",
        residency_packed=str(cfg["residency_packed"]) or "auto",
        prefetch=str(cfg["prefetch"]) or "on",
        translate_planes=str(cfg["translate_planes"]) or "auto",
        sketch_precision=int(cfg["sketch_precision"]),
        sketch_exact_threshold=int(cfg["sketch_exact_threshold"]),
        profile_ring_n=int(cfg["profile_ring_n"]),
        profile_queries=(str(cfg["profile_queries"]).lower()
                         in ("1", "true", "yes", "on")),
    )
    node.open()  # starts the (single) serve loop in the background
    print(f"pilosa-tpu serving at {node.address}", file=sys.stderr)
    # Orchestrators stop nodes with SIGTERM; without a handler the
    # process dies before node.close() can flush schema.json + final
    # snapshots, turning every rolling restart into a WAL-less schema
    # loss. SIGINT (ctrl-C) keeps its KeyboardInterrupt path.
    import signal
    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()  # block until SIGTERM or ctrl-C
    except KeyboardInterrupt:
        pass
    finally:
        node.close()
    # Interpreter teardown after jax has run aborts (XLA's C++ worker
    # threads hit std::terminate); everything durable was flushed by
    # node.close(), so skip teardown and report the clean exit.
    os._exit(0)


def _base_url(host: str, tls: bool = False) -> str:
    """Client base URL: honor an explicit scheme in --host, else pick
    one from --tls (ADVICE r4 #3: a TLS-enabled server aborted imports
    at the schema fetch because the scheme was hardcoded http)."""
    if "://" in host:
        return host.rstrip("/")
    return ("https://" if tls else "http://") + host


def _ssl_ctx(args):
    if getattr(args, "tls_skip_verify", False):
        import ssl
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx
    return None


def _tls_args(args) -> tuple[bool, object]:
    """(tls, ssl_context) for a client command; --tls-skip-verify
    unambiguously signals TLS intent, so it implies --tls rather than
    silently degrading the connection to plaintext."""
    tls = bool(getattr(args, "tls", False)
               or getattr(args, "tls_skip_verify", False))
    return tls, _ssl_ctx(args)


def _post(host: str, path: str, body: bytes, tls: bool = False,
          ctx=None) -> dict:
    req = urllib.request.Request(f"{_base_url(host, tls)}{path}", data=body,
                                 method="POST")
    with urllib.request.urlopen(req, timeout=60, context=ctx) as resp:
        return json.loads(resp.read() or b"{}")


def _import_modes(host: str, index: str, field: str, tls: bool = False,
                  ctx=None) -> tuple[bool, bool, bool]:
    """(value_mode, row_keys, column_keys) from the server's schema —
    the reference's bufferers pick the import mode the same way
    (ctl/import.go:125-140: field.Options.Type / Keys)."""
    # A failed schema fetch must ABORT the import, not guess the mode:
    # posting an int field's (col,value) CSV as rowIDs/columnIDs would
    # silently write garbage bits instead of BSI values.
    with urllib.request.urlopen(f"{_base_url(host, tls)}/schema",
                                timeout=30, context=ctx) as resp:
        schema = json.load(resp).get("indexes") or []
    for idx in schema:
        if idx.get("name") != index:
            continue
        col_keys = bool((idx.get("options") or {}).get("keys"))
        for f in idx.get("fields") or []:
            if f.get("name") == field:
                opts = f.get("options") or {}
                return (opts.get("type") == "int",
                        bool(opts.get("keys")), col_keys)
        return False, False, col_keys
    return False, False, False


def cmd_import(args) -> int:
    """CSV -> batched imports, like ctl/import.go: parse, buffer, send
    per batch. The mode follows the target field's schema: set/time
    fields take (row,col[,timestamp]) rows, int fields take
    (col,value), and keyed indexes/fields accept string keys in place
    of ids (reference ctl/import.go:125-140 + ImportK)."""
    tls, ctx = _tls_args(args)
    try:
        value_mode, row_keys, col_keys = _import_modes(
            args.host, args.index, args.field, tls=tls, ctx=ctx)
    except Exception as e:
        print(f"import: cannot read schema from {args.host}: {e}",
              file=sys.stderr)
        return 1
    rows, cols, vals, stamps = [], [], [], []
    has_ts = False

    def flush():
        nonlocal rows, cols, vals, stamps
        if not cols:
            return
        body: dict = {}
        if value_mode:
            body["values"] = vals
        else:
            body["rowKeys" if row_keys else "rowIDs"] = rows
            if has_ts:
                body["timestamps"] = stamps
        body["columnKeys" if col_keys else "columnIDs"] = cols
        _post(args.host, f"/index/{args.index}/field/{args.field}/import"
                         + ("?clear=1" if args.clear else ""),
              json.dumps(body).encode(), tls=tls, ctx=ctx)
        rows, cols, vals, stamps = [], [], [], []

    def parse_id(tok: str, keyed: bool):
        return tok if keyed else int(tok)

    for path in args.files:
        f = sys.stdin if path == "-" else open(path)
        try:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                parts = line.split(",")
                if value_mode:
                    cols.append(parse_id(parts[0], col_keys))
                    vals.append(int(parts[1]))
                else:
                    rows.append(parse_id(parts[0], row_keys))
                    cols.append(parse_id(parts[1], col_keys))
                    if len(parts) > 2:
                        has_ts = True
                        stamps.append(parts[2])
                    else:
                        stamps.append(None)
                if len(cols) >= args.buffer_size:
                    flush()
        finally:
            if f is not sys.stdin:
                f.close()
    flush()
    return 0


def cmd_export(args) -> int:
    tls, ctx = _tls_args(args)
    base = _base_url(args.host, tls)
    shards = [args.shard] if args.shard is not None else None
    if shards is None:
        with urllib.request.urlopen(
                f"{base}/internal/shards/max", timeout=60, context=ctx) as r:
            mx = json.loads(r.read())["standard"].get(args.index, 0)
        shards = list(range(mx + 1))
    for shard in shards:
        url = (f"{base}/export?index={args.index}"
               f"&field={args.field}&shard={shard}")
        try:
            with urllib.request.urlopen(url, timeout=60, context=ctx) as r:
                sys.stdout.write(r.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                continue  # sparse shard with no fragment
            raise
    return 0


def cmd_check(args) -> int:
    """Offline integrity check of a data dir (ctl/check.go:30): verify
    snapshot footer CRCs, WAL op checksums (torn tail vs mid-file
    corruption), and jsonl line frames; report quarantined evidence
    files. ``--repair`` sweeps stale ``*.tmp`` crash leftovers.
    ``--archive`` additionally (or instead) verifies a backup archive
    (directory or object-store URL) end to end. Exits non-zero when
    anything is BAD."""
    from pilosa_tpu.storage.integrity import LineCorruptError, parse_line
    from pilosa_tpu.storage.wal import scan_wal
    if not args.data_dir and not getattr(args, "archive", None):
        print("check: a data dir or --archive is required", file=sys.stderr)
        return 1
    bad = 0
    if getattr(args, "archive", None):
        from pilosa_tpu.backup import verify_archive
        res = verify_archive(args.archive)
        for prob in res["problems"]:
            print(f"BAD archive {prob}")
            bad += 1
        if res["ok"]:
            print(f"ok archive {args.archive} ({res['checked']} files, "
                  f"{res.get('backups', 0)} backup(s) verified)")
    for root, _, files in os.walk(args.data_dir or ""):
        for fn in sorted(files):
            p = os.path.join(root, fn)
            if fn.endswith(".wal"):
                info = scan_wal(p)
                if info["corrupt"]:
                    print(f"BAD wal  {p}: corrupt record mid-file "
                          f"({info['ops']} ops salvageable, "
                          f"{info['total_bytes'] - info['valid_bytes']} "
                          f"bytes damaged)")
                    bad += 1
                elif info["torn"]:
                    print(f"ok wal   {p} ({info['ops']} ops; torn tail of "
                          f"{info['total_bytes'] - info['valid_bytes']} "
                          f"bytes — normal crash shape, replay truncates)")
                else:
                    print(f"ok wal   {p} ({info['ops']} ops)")
            elif fn.endswith(".snap"):
                from pilosa_tpu.storage.diskstore import read_snapshot
                arrays, meta, status = read_snapshot(p)
                if status == "bad":
                    print(f"BAD snap {p}: {meta['error']}")
                    bad += 1
                elif status == "legacy":
                    print(f"ok snap  {p} ({len(arrays['row_ids'])} rows; "
                          f"legacy unframed — re-snapshot to checksum)")
                else:
                    print(f"ok snap  {p} ({meta['rows']} rows, "
                          f"{meta['bits']} bits, crc verified)")
            elif fn.endswith(".jsonl"):
                n_ok = n_legacy = n_bad = 0
                with open(p) as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if not line.strip():
                            continue
                        try:
                            _, verified = parse_line(line)
                            if verified:
                                n_ok += 1
                            else:
                                n_legacy += 1
                        except LineCorruptError:
                            n_bad += 1
                if n_bad:
                    print(f"BAD jsonl {p}: {n_bad} corrupt line(s) "
                          f"({n_ok} verified, {n_legacy} unframed)")
                    bad += 1
                else:
                    print(f"ok jsonl {p} ({n_ok} verified, "
                          f"{n_legacy} unframed)")
            elif fn.endswith(".quarantine") or ".quarantine." in fn:
                print(f"quarantined {p} (preserved corruption evidence)")
            elif fn.endswith(".tmp"):
                if getattr(args, "repair", False):
                    try:
                        os.remove(p)
                        print(f"repaired {p} (stale tmp removed)")
                    except OSError as e:
                        print(f"BAD tmp  {p}: cannot remove: {e}")
                        bad += 1
                else:
                    print(f"stale tmp {p} (crash leftover; "
                          f"--repair removes)")
    return 1 if bad else 0


def _get(host: str, path: str, tls: bool = False, ctx=None) -> dict:
    with urllib.request.urlopen(f"{_base_url(host, tls)}{path}",
                                timeout=60, context=ctx) as resp:
        return json.loads(resp.read() or b"{}")


def _poll_job(host: str, status_path: str, tls, ctx, what: str) -> int:
    """Follow a background backup/restore to completion via its status
    endpoint; prints the final status JSON and exits non-zero on
    failure."""
    import time
    st = {}
    while True:
        st = _get(host, status_path, tls=tls, ctx=ctx)
        state = st.get("state")
        if state in ("done", "failed", "idle"):
            break
        print(f"\r{what}: {state} {st.get('doneFragments', 0)}"
              f"/{st.get('totalFragments', 0)} fragments",
              end="", file=sys.stderr)
        time.sleep(0.2)
    print(file=sys.stderr)
    if state != "done":
        print(f"{what} {st.get('id', '')} failed: "
              f"{st.get('error', 'unknown error')}", file=sys.stderr)
        return 1
    print(json.dumps(st, indent=2))
    return 0


def cmd_backup(args) -> int:
    """Drive a cluster backup through a node's /backup endpoint and
    wait for completion. The archive path is resolved on the SERVER, so
    point it at a directory the node can write (shared mount etc.)."""
    tls, ctx = _tls_args(args)
    body: dict = {"archive": args.archive}
    if args.parent:
        body["parent"] = args.parent
    try:
        resp = _post(args.host, "/backup", json.dumps(body).encode(),
                     tls=tls, ctx=ctx)
    except urllib.error.HTTPError as e:
        print(f"backup: {e.read().decode(errors='replace')}",
              file=sys.stderr)
        return 1
    print(f"backup {resp.get('id')} started", file=sys.stderr)
    return _poll_job(args.host, "/backup/status", tls, ctx, "backup")


def cmd_restore(args) -> int:
    """Restore a backup onto the cluster behind --host (any size) and
    wait for completion; --pitr-ops caps WAL replay for point-in-time
    recovery and --force overwrites clashing live indexes."""
    tls, ctx = _tls_args(args)
    body: dict = {"archive": args.archive}
    if args.id:
        body["id"] = args.id
    if args.force:
        body["force"] = True
    if args.pitr_ops is not None:
        body["pitrOps"] = args.pitr_ops
    try:
        resp = _post(args.host, "/restore", json.dumps(body).encode(),
                     tls=tls, ctx=ctx)
    except urllib.error.HTTPError as e:
        print(f"restore: {e.read().decode(errors='replace')}",
              file=sys.stderr)
        return 1
    print(f"restore of {resp.get('id')} started", file=sys.stderr)
    return _poll_job(args.host, "/restore/status", tls, ctx, "restore")


def cmd_backup_verify(args) -> int:
    """Offline end-to-end verification of a backup archive (directory
    or object-store URL): manifests, parent chains, per-file CRCs,
    snapshot footers, WAL records, and meta line frames. Exits 1 on
    any damage."""
    from pilosa_tpu.backup import verify_archive
    res = verify_archive(args.archive, backup_id=args.id)
    for prob in res["problems"]:
        print(f"BAD {prob}")
    verdict = "ok" if res["ok"] else f"{len(res['problems'])} problem(s)"
    print(f"{args.archive}: {res['checked']} file(s) in "
          f"{res.get('backups', 1)} backup(s): {verdict}")
    return 0 if res["ok"] else 1


def cmd_inspect(args) -> int:
    """Per-fragment stats of a data dir (ctl/inspect.go analog)."""
    import numpy as np
    for root, _, files in os.walk(args.data_dir):
        for fn in sorted(files):
            if not fn.endswith(".snap"):
                continue
            p = os.path.join(root, fn)
            with np.load(p) as z:
                rows = len(z["row_ids"])
                bits = len(z["positions"])
            rel = os.path.relpath(p, args.data_dir)
            print(f"{rel}: rows={rows} bits={bits}")
    return 0


def cmd_config(args) -> int:
    print(json.dumps(_load_config(args.config), indent=2))
    return 0


def cmd_generate_config(args) -> int:
    print('bind = "127.0.0.1:10101"\n'
          'data-dir = ""\n'
          'peers = ""\n'
          'join = ""\n'
          'replica-n = 1\n'
          'anti-entropy-interval = 10.0\n'
          'check-nodes-interval = 5.0\n'
          '# serve stale reads while quorum-fenced (writes stay fenced)\n'
          'fence-stale-reads = false\n'
          '# background integrity scrub cadence, seconds (0 disables)\n'
          'scrub-interval = 60.0\n'
          '# unattended backups: cadence (0 disables) + archive\n'
          '# (a directory or http(s)://host:port/bucket object store)\n'
          'backup-interval = 0.0\n'
          'archive-url = ""\n'
          'backup-full-every = 8\n'
          'backup-keep-chains = 2\n'
          '# WAL records per fragment before a snapshot triggers\n'
          'max-op-n = 10000\n'
          '# preserved *.quarantine evidence files per fragment '
          '(0 keeps all)\n'
          'quarantine-keep-n = 0\n'
          'tls-cert = ""\n'
          'tls-key = ""\n'
          'tls-ca-cert = ""\n'
          '# trace-endpoint = "http://127.0.0.1:4318/v1/traces"\n'
          '# tls-skip-verify = false\n'
          'planner = true\n'
          '# QoS: admission gate + shedding (0 disables the gate)\n'
          'qos-max-concurrent = 32\n'
          'qos-max-queue = 64\n'
          'qos-internal-reserve = 4\n'
          'qos-default-deadline = 0.0\n'
          'qos-slow-query-ms = 500.0\n'
          '# kernel warmup at boot ("" disables)\n'
          'qos-warmup = "count,topn,bsi"\n'
          'qos-warmup-shards = "1,8,32"\n'
          '# adaptive concurrency: qos-max-concurrent is the ceiling,\n'
          '# the operative limit is measured (AIMD)\n'
          'qos-adaptive = true\n'
          '# per-tenant token bucket, requests/s per API key or index\n'
          '# (0 disables; rejections are 429 + Retry-After)\n'
          'qos-tenant-rate = 0.0\n'
          'qos-tenant-burst = 0.0\n'
          '# per-peer circuit breaker: consecutive failures to open\n'
          '# (0 disables), cooldown before the half-open probe\n'
          'breaker-threshold = 5\n'
          'breaker-cooldown = 5.0\n'
          '# hedged reads on replicated legs (delay 0 = measured p95)\n'
          'hedge = true\n'
          'hedge-delay-ms = 0.0\n'
          'hedge-budget-pct = 5.0\n'
          '# chaos fault injection route (tests only; never production)\n'
          '# chaos-faults = false\n'
          '# persistent XLA compile cache ("" = <data-dir>/compile-cache,\n'
          '# "off" disables)\n'
          'compile-cache-dir = ""\n'
          '# plan-shape bucketing: "pow2" reuses compiled kernels across\n'
          '# shard counts, "none" pads only to the device mesh\n'
          'plan-buckets = "pow2"\n'
          '# plan-keyed result cache: budget in MB (0 disables) and TTL\n'
          '# backstop in seconds (0 = epoch invalidation only)\n'
          'result-cache-mb = 64\n'
          'result-cache-ttl = 0.0\n'
          '# device-side BSI bit-plane transpose for bulk value imports\n'
          'ingest-transpose = "auto"\n'
          '# WAL group-commit fsync window, ms (0 = fsync per append)\n'
          'wal-group-commit-ms = 0.0\n'
          '# import-stream in-flight budget, MB (0 = unbounded;\n'
          '# over budget replies 429 + Retry-After + applied count)\n'
          'ingest-max-inflight-mb = 0\n'
          '# query dispatch: fused one-program-per-query plans, same-plan\n'
          '# dispatch coalescing (window in microseconds), and inline\n'
          '# transfer resolution — all bit-identical on|off|auto knobs\n'
          'dispatch-fuse = "auto"\n'
          'dispatch-coalesce = "auto"\n'
          'dispatch-coalesce-us = 150.0\n'
          'inline-transfer = "auto"\n'
          '# device residency: packed index stacks for low-cardinality\n'
          '# rows (auto|on|off, bit-identical) and pipelined async\n'
          '# uploads for non-resident leaf stacks (on|off)\n'
          'residency-packed = "auto"\n'
          'prefetch = "on"\n'
          '# key translation: device-resident sorted-hash planes for\n'
          '# large keyed batches (auto = device probe for 256+ keys)\n'
          'translate-planes = "auto"\n'
          '# approximate analytics: HLL precision for Count(Distinct)\n'
          '# (2^p registers, ~1.04/sqrt(2^p) error) and the estimated\n'
          '# cardinality below which the answer is computed exactly\n'
          'sketch-precision = 12\n'
          'sketch-exact-threshold = 1024\n'
          '# per-query cost profiles: slowest-N retention ring served\n'
          '# at /debug/queries (0 disables); profile-queries = false\n'
          '# limits profiling to explicit ?profile=true requests\n'
          'profile-ring-n = 64\n'
          'profile-queries = true')
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="pilosa-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("server", help="run a node")
    s.add_argument("--bind", default="")
    s.add_argument("--data-dir", default="")
    s.add_argument("--peers", default="", help="comma-separated host:port")
    s.add_argument("--replica-n", type=int, default=0)
    s.add_argument("--no-planner", action="store_true")
    s.add_argument("--join", default="",
                   help="host:port of a running member to join")
    s.add_argument("--tls-cert", default="")
    s.add_argument("--tls-key", default="")
    s.add_argument("--tls-ca-cert", default="")
    s.add_argument("--tls-skip-verify", action="store_true")
    s.add_argument("--qos-max-concurrent", type=int, default=None,
                   help="concurrency gate on query dispatch (0 disables)")
    s.add_argument("--qos-max-queue", type=int, default=None,
                   help="bounded admission queue; excess load sheds w/ 503")
    s.add_argument("--qos-default-deadline", type=float, default=None,
                   help="default per-query deadline, seconds (0 = none)")
    s.add_argument("--qos-warmup", default=None,
                   help='kernel warmup set, e.g. "count,topn,bsi" '
                        '("" disables)')
    s.add_argument("--import-pool-mb", type=int, default=None,
                   help="buffer-pool pages pre-faulted at boot (0 disables)")
    s.add_argument("--backup-interval", type=float, default=None,
                   help="unattended backup cadence, seconds "
                        "(0 disables; needs --archive-url)")
    s.add_argument("--archive-url", default=None,
                   help="backup archive: a directory path or an "
                        "s3-style http(s)://host:port/bucket[/prefix] "
                        "object-store URL")
    s.add_argument("--backup-full-every", type=int, default=None,
                   help="start a new full chain every N scheduled "
                        "backups (default 8)")
    s.add_argument("--backup-keep-chains", type=int, default=None,
                   help="retention: keep the newest N full chains, "
                        "prune the rest (0 keeps all; default 2)")
    s.add_argument("--scrub-interval", type=float, default=None,
                   help="background integrity scrub cadence, seconds "
                        "(0 disables)")
    s.add_argument("--max-op-n", type=int, default=None,
                   help="WAL records per fragment before a snapshot "
                        "triggers")
    s.add_argument("--quarantine-keep-n", type=int, default=None,
                   help="preserved *.quarantine evidence files per "
                        "fragment; oldest pruned after a successful "
                        "repair (0 keeps all)")
    s.add_argument("--qos-adaptive", choices=("on", "off"), default=None,
                   help="measured concurrency limit under the "
                        "qos-max-concurrent ceiling (default on)")
    s.add_argument("--qos-tenant-rate", type=float, default=None,
                   help="per-tenant request rate, req/s per API key or "
                        "index (0 disables; rejections are 429)")
    s.add_argument("--qos-tenant-burst", type=float, default=None,
                   help="per-tenant burst size (0 = 2x rate)")
    s.add_argument("--breaker-threshold", type=int, default=None,
                   help="consecutive peer failures that open its "
                        "circuit breaker (0 disables)")
    s.add_argument("--breaker-cooldown", type=float, default=None,
                   help="seconds an open breaker waits before its "
                        "half-open probe")
    s.add_argument("--hedge", choices=("on", "off"), default=None,
                   help="hedged reads on replicated legs (default on)")
    s.add_argument("--hedge-delay-ms", type=float, default=None,
                   help="fixed hedge delay, ms (0 = measured p95)")
    s.add_argument("--hedge-budget-pct", type=float, default=None,
                   help="hedges as a %% of primary legs (default 5)")
    s.add_argument("--fence-stale-reads", action="store_true",
                   help="serve queries/exports while quorum-fenced "
                        "(stale reads; writes and schema stay fenced)")
    s.add_argument("--chaos-faults", action="store_true",
                   help="mount POST /internal/fault (chaos testing "
                        "only; never on production nodes)")
    s.add_argument("--trace-endpoint", default="",
                   help="OTLP/HTTP collector URL for trace export")
    s.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compile cache directory "
                        '("" = <data-dir>/compile-cache, "off" disables)')
    s.add_argument("--plan-buckets", choices=("pow2", "none"), default=None,
                   help="plan-shape bucketing policy: pow2 rounds stack "
                        "heights to power-of-two buckets so new shard "
                        "counts reuse compiled kernels (default pow2)")
    s.add_argument("--result-cache-mb", type=int, default=None,
                   help="plan-keyed result cache budget, MB "
                        "(default 64; 0 disables)")
    s.add_argument("--result-cache-ttl", type=float, default=None,
                   help="result cache TTL backstop, seconds "
                        "(default 0 = epoch invalidation only)")
    s.add_argument("--device-reduce", choices=("on", "off", "auto"),
                   default=None,
                   help="fold remote bitmap legs on the device: auto "
                        "picks host vs device by a measured size "
                        "crossover (default auto; bit-identical results)")
    s.add_argument("--multiplex", choices=("on", "off"), default=None,
                   help="coalesce concurrent legs to one peer into a "
                        "single multiplexed request (default on)")
    s.add_argument("--ingest-transpose", choices=("on", "off", "auto"),
                   default=None,
                   help="device-side BSI bit-plane transpose for bulk "
                        "value imports (default auto; bit-identical)")
    s.add_argument("--wal-group-commit-ms", type=float, default=None,
                   help="WAL group-commit fsync window in ms when "
                        "fsync-per-append is enabled (default 0 = one "
                        "fsync per append)")
    s.add_argument("--ingest-max-inflight-mb", type=int, default=None,
                   help="import-stream in-flight byte budget, MB "
                        "(default 0 = unbounded; over budget replies "
                        "429 + Retry-After)")
    s.add_argument("--dispatch-fuse", choices=("on", "off", "auto"),
                   default=None,
                   help="fuse hot read plans into one jitted device "
                        "program per query (default auto = on; "
                        "bit-identical to the stepped path)")
    s.add_argument("--dispatch-coalesce", choices=("on", "off", "auto"),
                   default=None,
                   help="batch concurrent same-plan dispatches into one "
                        "launch (default auto = batch only while a "
                        "same-plan launch is in flight)")
    s.add_argument("--dispatch-coalesce-us", type=float, default=None,
                   help="coalescing collection window, microseconds "
                        "(default 150)")
    s.add_argument("--inline-transfer", choices=("on", "off", "auto"),
                   default=None,
                   help="resolve a device->host wave on its waiter's "
                        "thread when it is the only waiter (default "
                        "auto)")
    s.add_argument("--residency-packed", choices=("on", "off", "auto"),
                   default=None,
                   help="pack low-cardinality rows as sorted-index "
                        "stacks on device instead of dense bit planes "
                        "(default auto = pack rows at least 8x smaller "
                        "packed; bit-identical)")
    s.add_argument("--prefetch", choices=("on", "off"), default=None,
                   help="upload non-resident leaf stacks asynchronously "
                        "ahead of query execution (default on)")
    s.add_argument("--translate-planes", choices=("on", "off", "auto"),
                   default=None,
                   help="forward key translation via device-resident "
                        "sorted-hash planes (default auto = device probe "
                        "for batches of 256+ keys, async plane rebuild; "
                        "off = host snapshot path only)")
    s.add_argument("--sketch-precision", type=int, default=None,
                   help="HLL precision p for Count(Distinct(...)): 2^p "
                        "registers, ~1.04/sqrt(2^p) relative error "
                        "(default 12 = ~1.6%%; range 4..18)")
    s.add_argument("--sketch-exact-threshold", type=int, default=None,
                   help="answer Count(Distinct(...)) EXACTLY when the "
                        "estimate falls below this cardinality "
                        "(default 1024; 0 disables the fallback)")
    s.add_argument("--profile-ring", type=int, default=None,
                   help="retain the slowest N query cost profiles at "
                        "/debug/queries (default 64; 0 disables)")
    s.add_argument("--profile-queries", choices=("true", "false"),
                   default=None,
                   help="profile every query into the retention ring "
                        "(default true; false limits profiling to "
                        "?profile=true requests)")
    s.add_argument("--config", default=None)
    s.set_defaults(fn=cmd_server)

    s = sub.add_parser("import", help="bulk import CSV")
    s.add_argument("--host", default="127.0.0.1:10101",
                   help="host:port, or a full http(s)://host:port URL")
    s.add_argument("--tls", action="store_true",
                   help="use https (implied by an https:// --host)")
    s.add_argument("--tls-skip-verify", action="store_true")
    s.add_argument("--buffer-size", type=int, default=100_000)
    s.add_argument("--clear", action="store_true")
    s.add_argument("index")
    s.add_argument("field")
    s.add_argument("files", nargs="+")
    s.set_defaults(fn=cmd_import)

    s = sub.add_parser("export", help="export CSV")
    s.add_argument("--host", default="127.0.0.1:10101",
                   help="host:port, or a full http(s)://host:port URL")
    s.add_argument("--tls", action="store_true",
                   help="use https (implied by an https:// --host)")
    s.add_argument("--tls-skip-verify", action="store_true")
    s.add_argument("--shard", type=int, default=None)
    s.add_argument("index")
    s.add_argument("field")
    s.set_defaults(fn=cmd_export)

    s = sub.add_parser("check", help="offline data-dir consistency check")
    s.add_argument("data_dir", nargs="?", default="")
    s.add_argument("--repair", action="store_true",
                   help="sweep stale .tmp crash leftovers")
    s.add_argument("--archive", default=None,
                   help="also verify a backup archive "
                        "(directory or object-store URL)")
    s.set_defaults(fn=cmd_check)

    s = sub.add_parser("backup", help="back up the cluster to an archive")
    s.add_argument("--host", default="127.0.0.1:10101",
                   help="host:port, or a full http(s)://host:port URL")
    s.add_argument("--tls", action="store_true",
                   help="use https (implied by an https:// --host)")
    s.add_argument("--tls-skip-verify", action="store_true")
    s.add_argument("--parent", default=None,
                   help="parent backup id: capture an incremental "
                        "against it")
    s.add_argument("archive", help="archive directory (on the server)")
    s.set_defaults(fn=cmd_backup)

    s = sub.add_parser("restore",
                       help="restore a backup onto the cluster")
    s.add_argument("--host", default="127.0.0.1:10101",
                   help="host:port, or a full http(s)://host:port URL")
    s.add_argument("--tls", action="store_true",
                   help="use https (implied by an https:// --host)")
    s.add_argument("--tls-skip-verify", action="store_true")
    s.add_argument("--id", default=None,
                   help="backup id (default: newest complete backup)")
    s.add_argument("--force", action="store_true",
                   help="overwrite live indexes with the same names")
    s.add_argument("--pitr-ops", type=int, default=None,
                   help="cap per-fragment WAL replay at this op offset "
                        "(point-in-time recovery)")
    s.add_argument("archive", help="archive directory (on the server)")
    s.set_defaults(fn=cmd_restore)

    s = sub.add_parser("backup-verify",
                       help="offline archive verification")
    s.add_argument("--id", default=None,
                   help="verify one backup id (default: all complete "
                        "backups in the archive)")
    s.add_argument("archive",
                   help="archive directory or object-store URL")
    s.set_defaults(fn=cmd_backup_verify)

    s = sub.add_parser("inspect", help="data-dir fragment stats")
    s.add_argument("data_dir")
    s.set_defaults(fn=cmd_inspect)

    s = sub.add_parser("config", help="print resolved config")
    s.add_argument("--config", default=None)
    s.set_defaults(fn=cmd_config)

    s = sub.add_parser("generate-config", help="print default TOML config")
    s.set_defaults(fn=cmd_generate_config)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
