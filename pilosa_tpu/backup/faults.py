"""Test-only archive fault injection (``storage/faults.py`` style).

Two layers, matching where real archives fail:

- ``FakeObjectServer`` — an in-process S3-compatible object server
  (stdlib http.server) with injectable *wire* faults: latency, 5xx
  error storms with Retry-After, probabilistic per-request failures,
  torn uploads (half the body lands, the connection dies), and
  corrupted downloads (bytes change, the CRC metadata doesn't). The
  dr_drill scenario and the objstore tests run against it.
- ``FaultyArchive`` — a wrapper over any ArchiveStore injecting
  *interface-level* faults (one-shot armed or probabilistic), for
  scheduler-backoff and retention tests that don't need a wire.

Deterministic: every probabilistic knob draws from a seeded
``random.Random``. Production code never imports this module.
"""

from __future__ import annotations

import random
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pilosa_tpu.backup.archive import ArchiveStore, BackupError


class _ObjHandler(BaseHTTPRequestHandler):
    """S3-ish surface: PUT (incl. x-amz-copy-source), GET, HEAD,
    DELETE on /bucket/key; GET /bucket?list-type=2 for listing."""

    protocol_version = "HTTP/1.1"
    server: "FakeObjectServer"

    def log_message(self, fmt, *args):  # noqa: ARG002 - quiet by design
        pass

    # -- helpers ------------------------------------------------------------

    def _key(self) -> tuple[str, str]:
        """(key-within-bucket, raw query) — any bucket name accepted."""
        path, _, query = self.path.partition("?")
        path = urllib.parse.unquote(path).lstrip("/")
        _bucket, _, key = path.partition("/")
        return key, query

    def _reply(self, status: int, body: bytes = b"",
               headers: dict | None = None) -> None:
        self.send_response(status)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _faulted(self) -> bool:
        """Apply the server's armed wire faults; True when this request
        was consumed by one (a response — or its absence — went out)."""
        srv = self.server
        with srv.lock:
            if srv.latency_s:
                delay = srv.latency_s
            else:
                delay = 0.0
            if srv.error_burst_left > 0:
                srv.error_burst_left -= 1
                srv.injected += 1
                status = srv.error_burst_status
            elif srv.fail_rate and srv.rng.random() < srv.fail_rate:
                srv.injected += 1
                status = 503
            else:
                status = 0
        if delay:
            time.sleep(delay)
        if status:
            # Drain any request body first: an unread PUT body would be
            # parsed as the next request line on this keep-alive
            # connection and turn the injected 5xx into a bogus 501.
            length = int(self.headers.get("Content-Length", 0))
            if length:
                self.rfile.read(length)
            self._reply(status, b"injected fault",
                        {"Retry-After": "0.01"})
            return True
        return False

    # -- methods ------------------------------------------------------------

    def do_PUT(self):  # noqa: N802 - http.server API
        srv = self.server
        srv.requests += 1
        if self._faulted():
            return
        key, _ = self._key()
        src = self.headers.get("x-amz-copy-source")
        if src is not None:
            src_key = urllib.parse.unquote(src).lstrip("/") \
                .partition("/")[2]
            with srv.lock:
                if src_key not in srv.objects:
                    self._reply(404, b"no such copy source")
                    return
                srv.objects[key] = srv.objects[src_key]
            self._reply(200, b"<CopyObjectResult/>")
            return
        length = int(self.headers.get("Content-Length", 0))
        meta = {k.lower(): v for k, v in self.headers.items()
                if k.lower().startswith("x-amz-meta-")}
        with srv.lock:
            torn = srv.torn_next_put > 0
            if torn:
                srv.torn_next_put -= 1
                srv.torn += 1
        if torn:
            # Half the body lands, then the connection dies without a
            # response — the classic torn upload. The half-object is
            # stored (a real store would keep the received bytes too);
            # only the tmp-key+finalize protocol keeps it invisible.
            half = self.rfile.read(length // 2)
            with srv.lock:
                srv.objects[key] = (half, meta)
            self.close_connection = True
            return
        body = self.rfile.read(length)
        with srv.lock:
            srv.objects[key] = (body, meta)
        self._reply(200)

    def do_GET(self):  # noqa: N802
        srv = self.server
        srv.requests += 1
        if self._faulted():
            return
        key, query = self._key()
        params = urllib.parse.parse_qs(query)
        if "list-type" in params:
            self._reply(200, srv.render_listing(
                params.get("prefix", [""])[0],
                params.get("continuation-token", [None])[0]),
                {"Content-Type": "application/xml"})
            return
        with srv.lock:
            obj = srv.objects.get(key)
            corrupt = srv.corrupt_next_get > 0
            if obj is not None and corrupt:
                srv.corrupt_next_get -= 1
                srv.injected += 1
        if obj is None:
            self._reply(404, b"no such key")
            return
        data, meta = obj
        if corrupt and data:
            # Flip one bit; the stored CRC metadata still describes the
            # original — the client-side verify must catch this.
            i = srv.rng.randrange(len(data))
            data = data[:i] + bytes([data[i] ^ 0x40]) + data[i + 1:]
        self._reply(200, data, dict(meta))

    def do_HEAD(self):  # noqa: N802
        srv = self.server
        srv.requests += 1
        if self._faulted():
            return
        key, _ = self._key()
        with srv.lock:
            obj = srv.objects.get(key)
        if obj is None:
            self._reply(404)
            return
        # HEAD: headers only; Content-Length advertises the body size.
        self.send_response(200)
        for k, v in obj[1].items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(obj[0])))
        self.end_headers()

    def do_DELETE(self):  # noqa: N802
        srv = self.server
        srv.requests += 1
        if self._faulted():
            return
        key, _ = self._key()
        with srv.lock:
            existed = srv.objects.pop(key, None) is not None
        self._reply(204 if existed else 404)


class FakeObjectServer(ThreadingHTTPServer):
    """In-process object store on a loopback port.

    Fault knobs (all safe to flip while serving):
      fail_rate        probability any request 503s (seeded rng)
      error_burst(n)   next n requests fail with the given status
      latency_s        added per-request delay
      torn_next_put    next n PUTs store half the body and drop the line
      corrupt_next_get next n GETs serve flipped bytes under a stale CRC
    """

    daemon_threads = True

    def __init__(self, seed: int = 0):
        super().__init__(("127.0.0.1", 0), _ObjHandler)
        self.lock = threading.Lock()
        self.objects: dict[str, tuple[bytes, dict]] = {}
        self.rng = random.Random(seed)
        self.fail_rate = 0.0
        self.error_burst_left = 0
        self.error_burst_status = 500
        self.latency_s = 0.0
        self.torn_next_put = 0
        self.corrupt_next_get = 0
        self.max_keys_page = 1000
        self.requests = 0
        self.injected = 0
        self.torn = 0
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="fake-objstore", daemon=True)
        self._thread.start()

    def url(self, bucket: str = "drill", prefix: str = "") -> str:
        host, port = self.server_address[:2]
        u = f"http://{host}:{port}/{bucket}"
        return f"{u}/{prefix}" if prefix else u

    def error_burst(self, n: int, status: int = 500) -> None:
        with self.lock:
            self.error_burst_left = n
            self.error_burst_status = status

    def render_listing(self, prefix: str, token: str | None) -> bytes:
        """ListObjectsV2 XML, paged at ``max_keys_page`` keys with
        start-after continuation semantics."""
        with self.lock:
            keys = sorted(k for k in self.objects if k.startswith(prefix))
            page = self.max_keys_page
        if token:
            keys = [k for k in keys if k > token]
        batch, rest = keys[:page], keys[page:]
        parts = ["<?xml version=\"1.0\" encoding=\"UTF-8\"?>",
                 "<ListBucketResult>",
                 f"<IsTruncated>{'true' if rest else 'false'}"
                 f"</IsTruncated>"]
        if rest:
            parts.append(f"<NextContinuationToken>{batch[-1]}"
                         f"</NextContinuationToken>")
        for k in batch:
            parts.append(f"<Contents><Key>{k}</Key></Contents>")
        parts.append("</ListBucketResult>")
        return "".join(parts).encode()

    def close(self) -> None:
        self.shutdown()
        self.server_close()
        self._thread.join(timeout=10)


class FaultyArchive(ArchiveStore):
    """ArchiveStore wrapper injecting interface-level faults: arm
    ``fail_next_ops`` for a deterministic burst (one-shot, counts
    down) or set ``fail_rate`` for a seeded probabilistic storm."""

    def __init__(self, inner: ArchiveStore, seed: int = 42):
        self.inner = inner
        self.fail_next_ops = 0
        self.fail_rate = 0.0
        self.faults_injected = 0
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def _maybe_fail(self, op: str) -> None:
        with self._lock:
            if self.fail_next_ops > 0:
                self.fail_next_ops -= 1
            elif not (self.fail_rate
                      and self._rng.random() < self.fail_rate):
                return
            self.faults_injected += 1
        raise BackupError(f"injected archive fault: {op}")

    def write(self, backup_id, rel_path, data):
        self._maybe_fail(f"write {rel_path}")
        return self.inner.write(backup_id, rel_path, data)

    def read(self, backup_id, rel_path):
        self._maybe_fail(f"read {rel_path}")
        return self.inner.read(backup_id, rel_path)

    def exists(self, backup_id, rel_path):
        self._maybe_fail(f"exists {rel_path}")
        return self.inner.exists(backup_id, rel_path)

    def list_backups(self):
        self._maybe_fail("list_backups")
        return self.inner.list_backups()

    def delete(self, backup_id, rel_path):
        self._maybe_fail(f"delete {rel_path}")
        return self.inner.delete(backup_id, rel_path)

    def delete_backup(self, backup_id):
        self._maybe_fail(f"delete_backup {backup_id}")
        return self.inner.delete_backup(backup_id)
