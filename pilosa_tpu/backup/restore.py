"""RestoreJob — manifest-driven cluster rebuild + point-in-time recovery.

Restore runs on one node of the TARGET cluster (any node; whichever
received ``/restore``). The manifest is a complete logical file list, so
the target's size is free to differ from the source's: every fragment is
resharded through the target's own placement (``cluster.shard_nodes``)
and pushed to each current owner — local fragments are rebuilt in place
(writing through the WAL so the restore itself is durable), remote ones
ship over the internal import RPC.

Fragment state is reconstructed LOCALLY from the archived pair before
any import: apply the snapshot's row arrays, then replay the WAL segment
with full op semantics (set_row/clear_row REPLACE rows — feeding raw WAL
ops to a bit-import would corrupt them), and only then flatten to
(row, column) pairs. ``pitr_ops`` caps that replay at an op offset,
which is point-in-time recovery: same base snapshot, shorter history.

Failure is atomic: if any fragment cannot reach a single live owner, or
any archived file fails its CRC, the job deletes everything it created
(locally and on every live peer) and raises — a half-restored index must
never become visible as if it were whole.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

from pilosa_tpu.backup.archive import (
    BackupError,
    KIND_ATTRS,
    KIND_SNAP,
    KIND_TRANSLATE,
    KIND_WAL,
    file_crc,
    resolve_files,
)
from pilosa_tpu.storage.integrity import (
    LineCorruptError,
    SnapshotCorruptError,
    parse_line,
    split_snapshot,
)
from pilosa_tpu.storage.wal import (
    OP_ADD,
    OP_CLEAR_ROW,
    OP_REMOVE,
    OP_SET_ROW,
    iter_wal_records,
)


def rebuild_fragment(snap_bytes: bytes | None, wal_bytes: bytes | None,
                     shard: int, pitr_ops: int | None = None):
    """Reconstruct a fragment's final bitmap from its archived pair.

    Returns ``(row_ids, column_ids)`` lists (absolute columns) plus the
    number of WAL ops applied. ``pitr_ops`` stops the replay after that
    many ops — the point-in-time knob."""
    from pilosa_tpu.config import SHARD_WIDTH
    base = shard * SHARD_WIDTH
    rows: dict[int, set] = {}
    if snap_bytes is not None:
        import io
        payload, _meta = split_snapshot(snap_bytes)
        with np.load(io.BytesIO(payload)) as z:
            row_ids = z["row_ids"]
            offsets = z["offsets"]
            positions = z["positions"]
        for i, rid in enumerate(row_ids.tolist()):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            rows[rid] = set(positions[lo:hi].tolist())
    applied = 0
    if wal_bytes:
        for code, r, c in iter_wal_records(wal_bytes):
            if pitr_ops is not None and applied >= pitr_ops:
                break
            applied += 1
            if code == OP_ADD:
                for rid, col in zip(r.tolist(), c.tolist()):
                    rows.setdefault(rid, set()).add(col - base)
            elif code == OP_REMOVE:
                for rid, col in zip(r.tolist(), c.tolist()):
                    s = rows.get(rid)
                    if s is not None:
                        s.discard(col - base)
            elif code == OP_SET_ROW:
                rid = int(r[0]) if len(r) else 0
                rows[rid] = {col - base for col in c.tolist()}
            elif code == OP_CLEAR_ROW:
                rid = int(r[0]) if len(r) else 0
                rows.pop(rid, None)
    out_rows: list[int] = []
    out_cols: list[int] = []
    for rid in sorted(rows):
        for pos in sorted(rows[rid]):
            out_rows.append(rid)
            out_cols.append(base + pos)
    return out_rows, out_cols, applied


def preflight_restore(archive, manifest: dict,
                      crc_samples: int = 4) -> dict:
    """Validate a manifest's FULL restore plan against the archive
    before anything touches a data dir: every ``stored_in`` ref must
    exist (an incremental leans on ancestors a bad prune could have
    taken), and a deterministic spread of entries is read back and
    CRC-checked. Failure names the missing or damaged object, so a
    pruned-or-torn chain dies here — fast — instead of mid-restore.

    Also the retention layer's verify-before-prune pass: a survivor
    that fails this must abort the prune."""
    files = resolve_files(manifest)
    missing = [(e["stored_in"], e["path"]) for e in files.values()
               if not archive.exists(e["stored_in"], e["path"])]
    if missing:
        sid, path = missing[0]
        raise BackupError(
            f"restore preflight: backup {manifest['id']!r} needs "
            f"{len(missing)} object(s) the archive no longer has, "
            f"first: {sid}/{path}")
    ordered = [files[p] for p in sorted(files)]
    if crc_samples <= 0 or not ordered:
        sampled = []
    elif crc_samples >= len(ordered):
        sampled = ordered
    else:
        # Deterministic spread across the sorted plan (always includes
        # the first and last entries).
        step = (len(ordered) - 1) / (crc_samples - 1) if crc_samples > 1 \
            else len(ordered)
        sampled = [ordered[int(i * step)] for i in range(crc_samples)]
    for entry in sampled:
        data = archive.read(entry["stored_in"], entry["path"])
        if file_crc(data) != entry.get("crc"):
            raise BackupError(
                f"restore preflight: backup {manifest['id']!r}: CRC "
                f"mismatch on {entry['stored_in']}/{entry['path']}")
    return {"checked": len(files), "crcChecked": len(sampled)}


def select_backup_at(archive, timestamp: float) -> dict | None:
    """Latest complete backup captured at or before ``timestamp`` — the
    coarse half of PITR (pick the base archive by time, then ``pitr_ops``
    refines within its WAL segments)."""
    best = None
    for bid in archive.list_backups():
        try:
            m = archive.read_manifest(bid)
        except BackupError:
            continue  # incomplete/damaged: not a restore candidate
        if m.get("created", 0) <= timestamp:
            if best is None or m["created"] > best["created"]:
                best = m
    return best


class RestoreJob:
    """One restore run; ``progress`` is live for /restore/status."""

    def __init__(self, holder, cluster, client, archive, backup_id: str,
                 store=None, stats=None, logger=None, force: bool = False,
                 pitr_ops: int | None = None, on_fragment=None):
        self.holder = holder
        self.cluster = cluster
        self.client = client
        self.archive = archive
        self.backup_id = backup_id
        self.store = store
        self.stats = stats
        self.logger = logger
        self.force = force
        self.pitr_ops = pitr_ops
        #: test hook: called with each fragment key just before its
        #: fan-out (the chaos drill kills a node from here).
        self.on_fragment = on_fragment
        self.progress: dict = {"state": "idle"}
        self._lock = threading.Lock()

    # -- helpers -----------------------------------------------------------

    def _count(self, name: str, value: int = 1) -> None:
        if self.stats is not None:
            self.stats.count(name, value)

    def _log(self, fmt: str, *args) -> None:
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    def _live_peers(self):
        if self.cluster is None:
            return []
        return [n for n in self.cluster.nodes
                if n.id != self.cluster.local_id and n.state != "DOWN"]

    def _read(self, entry: dict) -> bytes:
        data = self.archive.read(entry["stored_in"], entry["path"])
        if file_crc(data) != entry.get("crc"):
            raise BackupError(
                f"archive damage: CRC mismatch reading {entry['path']} "
                f"from backup {entry['stored_in']!r}")
        return data

    # -- local/remote import ------------------------------------------------

    def _import_local(self, index, field, view, shard, rows, cols):
        f = self.holder.field(index, field)
        if f is None:
            raise LookupError(f"field not found: {index}/{field}")
        v = f.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard)
        frag.bulk_import(rows, cols)

    def _push_fragment(self, key: tuple, rows, cols) -> None:
        """Import one rebuilt fragment into every CURRENT owner under the
        target placement. A DOWN owner is skipped and the shard marked
        dirty (the scrubber heals it when the node returns) — but zero
        reachable owners aborts the whole job."""
        index, field, view, shard = key
        delivered = 0
        skipped = 0
        if self.cluster is None:
            self._import_local(index, field, view, shard, rows, cols)
            delivered += 1
        else:
            for node in self.cluster.shard_nodes(index, shard):
                if node.state == "DOWN":
                    skipped += 1
                    continue
                try:
                    if node.id == self.cluster.local_id:
                        self._import_local(index, field, view, shard,
                                           rows, cols)
                    else:
                        self.client.import_bits(node, index, field, view,
                                                shard, rows, cols, False)
                    delivered += 1
                except (ConnectionError, OSError, RuntimeError):
                    skipped += 1
        if delivered == 0:
            raise BackupError(
                f"restore: no live owner reachable for "
                f"{index}/{field}/{view}/{shard}")
        if skipped and self.cluster is not None:
            self.cluster.dirty_shards.mark(index, shard)
            self._count("restore.replicasSkipped", skipped)

    # -- meta stores --------------------------------------------------------

    def _apply_meta(self, entry: dict, data: bytes) -> None:
        lines = [ln for ln in data.decode().splitlines() if ln]
        payloads = []
        for ln in lines:
            try:
                payload, _verified = parse_line(ln)
            except LineCorruptError as e:
                raise BackupError(
                    f"archive damage: bad line in {entry['path']}") from e
            payloads.append(json.loads(payload))
        idx = self.holder.index(entry["index"])
        if idx is None:
            return
        target = idx if entry.get("field") is None \
            else idx.field(entry["field"])
        if target is None:
            return
        if entry["kind"] == KIND_TRANSLATE:
            target.translate_store.apply_entries(
                [(int(i), k) for i, k in payloads])
        elif entry["kind"] == KIND_ATTRS:
            store = (idx.column_attr_store if entry.get("field") is None
                     else target.row_attr_store)
            store.set_bulk_attrs({int(i): a for i, a in payloads})

    # -- rollback -----------------------------------------------------------

    def _rollback(self, index_names: list[str]) -> None:
        """All-or-nothing: tear the half-restored indexes back out of
        every live node so no partially-visible index survives."""
        for name in index_names:
            if self.holder.index(name) is not None:
                try:
                    self.holder.delete_index(name)
                except Exception:
                    pass
            if self.store is not None:
                try:
                    self.store.delete_subtree_files(name)
                except Exception:
                    pass
            for node in self._live_peers():
                try:
                    self.client.send_message(
                        node, {"type": "delete-index", "index": name})
                except (ConnectionError, RuntimeError, OSError):
                    pass  # that peer is gone; its cleaner converges later
        self._count("restore.rollbacks")
        self._log("restore: rolled back %s", ",".join(index_names))

    # -- run ----------------------------------------------------------------

    def run(self) -> dict:
        t0 = time.perf_counter()
        manifest = self.archive.read_manifest(self.backup_id)
        files = resolve_files(manifest)
        schema = manifest.get("schema", [])
        index_names = [i["name"] for i in schema]

        conflicting = [n for n in index_names
                       if self.holder.index(n) is not None]
        if conflicting and not self.force:
            raise BackupError(
                f"restore would clobber existing index(es) "
                f"{conflicting}: pass force to overwrite")
        # Preflight the whole plan BEFORE touching any data dir (ours
        # or a conflicting index we're about to force-drop): a broken
        # chain must fail here, not as a mid-restore rollback.
        preflight_restore(self.archive, manifest)
        self._count("restore.preflights")
        for name in conflicting:
            # force: drop the live index everywhere before rebuilding.
            self.holder.delete_index(name)
            if self.store is not None:
                self.store.delete_subtree_files(name)
            for node in self._live_peers():
                try:
                    self.client.send_message(
                        node, {"type": "delete-index", "index": name})
                except (ConnectionError, RuntimeError, OSError):
                    pass

        # Group the fragment entries: one (snap?, wal?) pair per key.
        frags: dict[tuple, dict] = {}
        meta_entries = []
        for entry in files.values():
            if entry["kind"] in (KIND_SNAP, KIND_WAL):
                key = (entry["index"], entry["field"], entry["view"],
                       int(entry["shard"]))
                frags.setdefault(key, {})[entry["kind"]] = entry
            elif entry["kind"] in (KIND_TRANSLATE, KIND_ATTRS):
                meta_entries.append(entry)

        with self._lock:
            self.progress = {"state": "running", "id": self.backup_id,
                             "totalFragments": len(frags),
                             "doneFragments": 0, "bytes": 0,
                             "pitrOps": self.pitr_ops}
        restored_bytes = 0
        try:
            # Schema first, everywhere: imports land in existing fields.
            self.holder.apply_schema(schema)
            for node in self._live_peers():
                self.client.post_schema(node, schema)

            for key in sorted(frags):
                pair = frags[key]
                snap = self._read(pair["snap"]) if "snap" in pair else None
                wal = self._read(pair["wal"]) if "wal" in pair else None
                restored_bytes += (len(snap) if snap else 0) \
                    + (len(wal) if wal else 0)
                try:
                    rows, cols, _applied = rebuild_fragment(
                        snap, wal, key[3], pitr_ops=self.pitr_ops)
                except SnapshotCorruptError as e:
                    raise BackupError(
                        f"archive damage: bad snapshot for {key}") from e
                if self.on_fragment is not None:
                    self.on_fragment(key)
                if rows:
                    self._push_fragment(key, rows, cols)
                self._count("restore.fragments")
                self.progress["doneFragments"] += 1
                self.progress["bytes"] = restored_bytes

            for entry in meta_entries:
                self._apply_meta(entry, self._read(entry))
        except BaseException as e:
            self._rollback(index_names)
            with self._lock:
                self.progress = dict(self.progress, state="failed",
                                     error=str(e))
            self._count("restore.failures")
            raise

        if self.store is not None:
            # Persist the restored schema + meta stores now; fragments
            # already went through the WAL on their way in.
            self.store.flush()
        seconds = time.perf_counter() - t0
        with self._lock:
            self.progress = dict(self.progress, state="done",
                                 seconds=round(seconds, 3))
        self._count("restore.runs")
        self._count("restore.bytes", restored_bytes)
        if self.stats is not None:
            self.stats.timing("restore.seconds", seconds)
            if seconds > 0:
                self.stats.gauge("restore.bytesPerSec",
                                 restored_bytes / seconds)
        self._log("restore %s: %d fragments, %d bytes in %.2fs",
                  self.backup_id, len(frags), restored_bytes, seconds)
        return {"id": self.backup_id, "fragments": len(frags),
                "bytes": restored_bytes, "indexes": index_names,
                "seconds": seconds}
