"""Backup, restore, and point-in-time recovery.

The checkpointing layer over the durability engine: cluster-consistent
archives of every fragment's CRC-verified snapshot + WAL segment plus
schema, key translation, and attr stores, written through a small
``ArchiveStore`` interface (``LocalDirArchive`` for a directory,
``ObjectArchiveStore`` for an S3-compatible object store).

- ``BackupWriter``   — full + incremental capture, coordinated across
  the cluster so each shard is archived exactly once from a healthy
  (non-quarantined) replica, rate-limited through the QoS internal
  class.
- ``BackupScheduler``— unattended periodic incrementals (coordinator-
  only with takeover, epoch fast path, failure backoff) plus the
  keep-N-full-chains retention pruner (``retention.prune_archive``).
- ``RestoreJob``     — manifest-driven rebuild of a fresh (possibly
  differently sized) cluster, resharded through the placement layer,
  preflighted against the archive before it touches a data dir,
  CRC-verified on ingest, atomic (all-or-nothing per restore).
- ``verify_archive`` — offline archive check (manifest completeness,
  per-file CRCs, snapshot footers, WAL chain continuity).

Reference: ctl/backup.go / ctl/restore.go (operator-driven disaster
recovery over the Holder→fragment tree).
"""

from .archive import (
    ArchiveStore,
    BackupError,
    LocalDirArchive,
    MANIFEST_NAME,
    new_backup_id,
    resolve_files,
)
from .objstore import ObjectArchiveStore, open_archive, parse_archive_url
from .restore import RestoreJob, preflight_restore, select_backup_at
from .retention import plan_prune, prune_archive
from .scheduler import BackupScheduler
from .verify import verify_archive
from .writer import BackupWriter, capture_fragment

__all__ = [
    "ArchiveStore",
    "BackupError",
    "BackupScheduler",
    "BackupWriter",
    "LocalDirArchive",
    "MANIFEST_NAME",
    "ObjectArchiveStore",
    "RestoreJob",
    "capture_fragment",
    "new_backup_id",
    "open_archive",
    "parse_archive_url",
    "plan_prune",
    "preflight_restore",
    "prune_archive",
    "resolve_files",
    "select_backup_at",
    "verify_archive",
]
