"""Backup, restore, and point-in-time recovery.

The checkpointing layer over the durability engine: cluster-consistent
archives of every fragment's CRC-verified snapshot + WAL segment plus
schema, key translation, and attr stores, written through a small
``ArchiveStore`` interface (local directory today, object store later).

- ``BackupWriter``   — full + incremental capture, coordinated across
  the cluster so each shard is archived exactly once from a healthy
  (non-quarantined) replica, rate-limited through the QoS internal
  class.
- ``RestoreJob``     — manifest-driven rebuild of a fresh (possibly
  differently sized) cluster, resharded through the placement layer,
  CRC-verified on ingest, atomic (all-or-nothing per restore).
- ``verify_archive`` — offline archive check (manifest completeness,
  per-file CRCs, snapshot footers, WAL chain continuity).

Reference: ctl/backup.go / ctl/restore.go (operator-driven disaster
recovery over the Holder→fragment tree).
"""

from .archive import (
    ArchiveStore,
    BackupError,
    LocalDirArchive,
    MANIFEST_NAME,
    new_backup_id,
    resolve_files,
)
from .restore import RestoreJob, select_backup_at
from .verify import verify_archive
from .writer import BackupWriter, capture_fragment

__all__ = [
    "ArchiveStore",
    "BackupError",
    "BackupWriter",
    "LocalDirArchive",
    "MANIFEST_NAME",
    "RestoreJob",
    "capture_fragment",
    "new_backup_id",
    "resolve_files",
    "select_backup_at",
    "verify_archive",
]
