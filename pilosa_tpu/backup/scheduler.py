"""BackupScheduler — unattended periodic backups with retention.

The scheduler turns operator-initiated backups into a background habit:
every ``interval`` seconds it drives one ``BackupWriter`` run through
the QoS internal class, incremental against the last success, opening a
fresh full chain every ``full_every`` runs so retention has something
to prune. Design constraints, in order:

- **never hurt the serving path.** A failing archive degrades to
  alerting (counters + log lines), never to blocking queries or
  crashing the node: every run is wrapped, every failure backs off
  exponentially (full jitter, bounded) before the next attempt.
- **coordinator-only, with takeover.** On a cluster every node ticks,
  but only the current coordinator captures; when the coordinator
  changes, the new one's next tick picks the duty up and *adopts* the
  latest complete backup in the archive as its incremental parent, so
  a handoff doesn't force a full.
- **no-op cycles are free.** If no index epoch moved since the parent
  manifest, the cycle is skipped without touching a fragment (the
  ``skipped-unchanged`` fast path).

Health surface: ``backup.scheduler.{runs,skipped,failed,overruns,
consecutiveFailures,lastSuccessEpoch}`` on /debug/vars and /metrics,
plus ``status()`` behind /debug/backup and a slowlog of runs that
overran their interval.

Deterministic by construction: the clock, and the jitter rng are
injectable, so the fake-clock tests replay interval math, backoff
curves, and coordinator handoffs exactly.
"""

from __future__ import annotations

import random
import time as _time
from collections import deque

from pilosa_tpu.backup.archive import BackupError
from pilosa_tpu.backup.retention import prune_archive
from pilosa_tpu.backup.writer import BackupWriter

#: new full chain every N runs (the incremental chain's max length);
#: retention prunes whole superseded chains.
DEFAULT_FULL_EVERY = 8
#: failure backoff never exceeds this many intervals
MAX_BACKOFF_INTERVALS = 8
#: slowlog entries kept (runs that overran the interval)
SLOWLOG_KEEP = 16

#: run_once outcomes
RAN = "ran"
SKIP_UNCHANGED = "skipped-unchanged"
SKIP_NOT_COORDINATOR = "skipped-not-coordinator"
SKIP_FENCED = "skipped-fenced"
SKIP_NOT_DUE = "waiting"
FAILED = "failed"


class BackupScheduler:
    """Periodic incremental backups into one archive. ``tick()`` is the
    only entry point the node's timer calls; it is cheap unless a run
    is actually due, and it never raises."""

    def __init__(self, *, holder, cluster, client, store, archive,
                 interval: float, node_id: str | None = None,
                 stats=None, logger=None, admission=None,
                 full_every: int = DEFAULT_FULL_EVERY,
                 keep_chains: int = 0,
                 clock=_time.monotonic, rng=None):
        self.holder = holder
        self.cluster = cluster
        self.client = client
        self.store = store
        self.archive = archive
        self.interval = interval
        self.node_id = node_id
        self.stats = stats
        self.logger = logger
        self.admission = admission
        self.full_every = max(1, full_every)
        self.keep_chains = keep_chains
        self.clock = clock
        self._rng = rng or random.Random()

        now = clock()
        self._next_due = now + interval
        self._backoff_until = now
        self._adopted = False
        self.last_manifest: dict | None = None
        self._runs_in_chain = 0
        self.consecutive_failures = 0
        self.last_error: str | None = None
        self.last_status: str = "idle"
        self.last_success_wall: float | None = None
        self.last_prune: dict | None = None
        self.slowlog: deque = deque(maxlen=SLOWLOG_KEEP)
        self.runs = 0
        self.skipped = 0
        self.failed = 0

    # -- helpers -----------------------------------------------------------

    def _count(self, name: str, value: int = 1) -> None:
        if self.stats is not None:
            self.stats.count(name, value)

    def _gauge(self, name: str, value: float) -> None:
        if self.stats is not None:
            self.stats.gauge(name, value)

    def _log(self, fmt: str, *args) -> None:
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    def _is_coordinator(self) -> bool:
        if self.cluster is None or self.node_id is None:
            return True
        coord = self.cluster.coordinator()
        return coord is None or coord.id == self.node_id

    def _is_fenced(self) -> bool:
        """Fencing gate for the coordinator duty: a fenced coordinator
        is on the minority side of a partition, where the majority may
        already have a successor ticking — two schedulers capturing and
        pruning the same archive is exactly the split-brain retention
        was not designed to survive."""
        return (self.cluster is not None
                and getattr(self.cluster, "fenced", False))

    def _current_epochs(self) -> dict:
        epochs = {}
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            epochs[iname] = {"instance": idx.instance_id,
                             "epoch": idx.epoch.value,
                             "schemaEpoch": idx.schema_epoch.value}
        return epochs

    def _adopt_latest(self) -> None:
        """Continue the chain across restarts and coordinator handoffs:
        the latest complete backup in the archive becomes the parent,
        with the chain position recovered by walking its parents."""
        self._adopted = True
        try:
            best = None
            for bid in self.archive.list_backups():
                m = self.archive.read_manifest(bid)
                if best is None or m.get("created", 0) > best["created"]:
                    best = m
            if best is None:
                return
            depth, cur, manifests = 1, best, {best["id"]: best}
            while cur.get("parent"):
                pid = cur["parent"]
                if pid in manifests or not self.archive.has_manifest(pid):
                    break
                cur = self.archive.read_manifest(pid)
                manifests[pid] = cur
                depth += 1
            self.last_manifest = best
            self._runs_in_chain = depth
        except (BackupError, OSError, ValueError) as e:
            # Unreadable archive state: start a fresh full chain.
            self._log("backup scheduler: adopt failed (%s); "
                      "starting a new full chain", e)
            self.last_manifest = None
            self._runs_in_chain = 0

    # -- the tick ----------------------------------------------------------

    def due(self, now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        return now >= self._next_due and now >= self._backoff_until

    def tick(self) -> str:
        """Timer entry point: run if due, swallow everything — a broken
        archive must degrade to counters, never take the node down."""
        try:
            if not self.due():
                return SKIP_NOT_DUE
            return self.run_once()
        except BaseException as e:  # belt and braces over run_once
            self.last_error = str(e)
            self.last_status = FAILED
            return FAILED

    def run_once(self, now: float | None = None,
                 force: bool = False) -> str:
        """One scheduling decision + (maybe) one backup run. ``force``
        bypasses the due/backoff checks (drills, tests), not the
        coordinator or epoch checks."""
        now = self.clock() if now is None else now
        self._next_due = now + self.interval
        if not force and now < self._backoff_until:
            return SKIP_NOT_DUE

        if not self._is_coordinator():
            # Another node owns the duty; stay warm for takeover.
            self.skipped += 1
            self._count("backup.scheduler.skipped")
            self.last_status = SKIP_NOT_COORDINATOR
            return SKIP_NOT_COORDINATOR

        if self._is_fenced():
            # Still nominally coordinator, but we cannot see a majority
            # of the ring: suspend the duty until the fence lifts.
            self.skipped += 1
            self._count("backup.scheduler.skippedFenced")
            self.last_status = SKIP_FENCED
            return SKIP_FENCED

        if not self._adopted:
            self._adopt_latest()

        parent = None
        if (self.last_manifest is not None
                and self._runs_in_chain < self.full_every):
            parent = self.last_manifest["id"]

        # Epoch fast path: no index moved since the parent capture and
        # none appeared or vanished — the cycle is a no-op, skip it
        # without touching a single fragment.
        if parent is not None \
                and self._current_epochs() == self.last_manifest.get(
                    "epochs"):
            self.skipped += 1
            self._count("backup.scheduler.skipped")
            self.last_status = SKIP_UNCHANGED
            return SKIP_UNCHANGED

        writer = BackupWriter(self.holder, self.cluster, self.client,
                              self.store, self.archive, stats=self.stats,
                              logger=self.logger,
                              admission=self.admission)
        try:
            manifest = writer.run(parent=parent)
        except BaseException as e:
            self._on_failure(now, e)
            return FAILED

        self.runs += 1
        self.consecutive_failures = 0
        self.last_error = None
        self.last_manifest = manifest
        self._runs_in_chain = (1 if parent is None
                               else self._runs_in_chain + 1)
        self.last_success_wall = manifest.get("created", _time.time())
        self._count("backup.scheduler.runs")
        self._gauge("backup.scheduler.consecutiveFailures", 0)
        self._gauge("backup.scheduler.lastSuccessEpoch",
                    self.last_success_wall)
        self.last_status = RAN

        if self.keep_chains > 0:
            try:
                self.last_prune = prune_archive(
                    self.archive, self.keep_chains, stats=self.stats,
                    logger=self.logger, fence=self._is_fenced)
            except BaseException as e:
                # Retention trouble alerts but never fails the backup.
                self._count("backup.retention.failures")
                self._log("backup retention failed: %s", e)

        took = self.clock() - now
        if self.interval > 0 and took > self.interval:
            # Slowlog: the cadence silently degraded to ~took seconds;
            # an operator reading /debug/backup should see it.
            self.slowlog.append({"id": manifest["id"],
                                 "seconds": round(took, 3),
                                 "intervalS": self.interval,
                                 "finishedEpoch": self.last_success_wall})
            self._count("backup.scheduler.overruns")
            self._log("backup %s overran its interval: %.1fs > %.1fs",
                      manifest["id"], took, self.interval)
        return RAN

    def _on_failure(self, now: float, err: BaseException) -> None:
        self.failed += 1
        self.consecutive_failures += 1
        self.last_error = str(err)
        self.last_status = FAILED
        self._count("backup.scheduler.failed")
        self._gauge("backup.scheduler.consecutiveFailures",
                    self.consecutive_failures)
        # Full-jitter exponential backoff in units of the interval: a
        # down archive costs one cheap failed attempt per backoff
        # window, not a capture storm.
        mult = min(MAX_BACKOFF_INTERVALS,
                   2 ** (self.consecutive_failures - 1))
        delay = self.interval * mult * (1.0 + self._rng.uniform(0, 0.25))
        self._backoff_until = now + delay
        self._log("backup scheduler: run failed (%s); backing off %.1fs "
                  "(%d consecutive)", err, delay,
                  self.consecutive_failures)

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """The /debug/backup document."""
        now = self.clock()
        return {
            "intervalS": self.interval,
            "fullEvery": self.full_every,
            "keepChains": self.keep_chains,
            "runs": self.runs,
            "skipped": self.skipped,
            "failed": self.failed,
            "consecutiveFailures": self.consecutive_failures,
            "lastStatus": self.last_status,
            "lastError": self.last_error,
            "fenced": self._is_fenced(),
            "lastSuccessEpoch": self.last_success_wall,
            "lastBackupId": (self.last_manifest or {}).get("id"),
            "runsInChain": self._runs_in_chain,
            "nextDueInS": round(max(self._next_due, self._backoff_until)
                                - now, 3),
            "backoffRemainingS": round(max(0.0,
                                           self._backoff_until - now), 3),
            "lastPrune": self.last_prune,
            "slowlog": list(self.slowlog),
        }
