"""Archive layout + the ArchiveStore backend interface.

An archive root holds any number of backups, each a directory named by
its backup id::

    <root>/<backup_id>/manifest.json        # written LAST: its presence
                                            # marks the backup complete
    <root>/<backup_id>/schema.json
    <root>/<backup_id>/data/<index>/<field>/<view>/<shard>.snap
    <root>/<backup_id>/data/<index>/<field>/<view>/<shard>.wal
    <root>/<backup_id>/data/<index>/translate.jsonl
    <root>/<backup_id>/data/<index>/column_attrs.jsonl
    <root>/<backup_id>/data/<index>/<field>/translate.jsonl
    <root>/<backup_id>/data/<index>/<field>/row_attrs.jsonl

The manifest records every logical file of the cluster state at capture
time; an incremental backup stores bytes only for files that changed
since the parent and points unchanged entries at the ancestor that
holds them (``stored_in``), so a single manifest is always a complete,
self-describing restore plan — no chain walk at restore time.

``ArchiveStore`` is deliberately tiny (write/read/exists/list) so an
object-store backend can slot in behind the same BackupWriter/
RestoreJob; ``LocalDirArchive`` is the local-directory implementation.
"""

from __future__ import annotations

import json
import os
import time
import uuid
import zlib

from pilosa_tpu.errors import PilosaError

MANIFEST_NAME = "manifest.json"
FORMAT_VERSION = 1

#: file kinds a manifest entry may carry
KIND_SNAP = "snap"
KIND_WAL = "wal"
KIND_TRANSLATE = "translate"
KIND_ATTRS = "attrs"
KIND_SCHEMA = "schema"


class BackupError(PilosaError):
    message = "backup/restore error"


def file_crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def new_backup_id(kind: str = "full") -> str:
    """Sortable, collision-free id: UTC timestamp + kind + nonce."""
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    return f"{stamp}-{kind}-{uuid.uuid4().hex[:8]}"


class ArchiveStore:
    """Backend interface: a flat (backup_id, rel_path) -> bytes store."""

    def write(self, backup_id: str, rel_path: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, backup_id: str, rel_path: str) -> bytes:
        raise NotImplementedError

    def exists(self, backup_id: str, rel_path: str) -> bool:
        raise NotImplementedError

    def list_backups(self) -> list[str]:
        raise NotImplementedError

    def delete(self, backup_id: str, rel_path: str) -> None:
        """Remove one object (missing is not an error)."""
        raise NotImplementedError

    def delete_backup(self, backup_id: str) -> None:
        """Remove a whole backup, manifest first — the backup must drop
        out of ``list_backups`` before any payload byte goes, so a
        crash mid-delete leaves only complete, restorable listings.
        Retention pruning is the only caller."""
        raise NotImplementedError

    # -- manifest helpers (shared across backends) -------------------------

    def write_manifest(self, backup_id: str, manifest: dict) -> None:
        self.write(backup_id, MANIFEST_NAME,
                   json.dumps(manifest, indent=1).encode())

    def read_manifest(self, backup_id: str) -> dict:
        try:
            doc = json.loads(self.read(backup_id, MANIFEST_NAME))
        except (OSError, ValueError) as e:
            raise BackupError(
                f"backup {backup_id!r}: unreadable manifest "
                f"(incomplete or damaged archive): {e}") from e
        if doc.get("format") != FORMAT_VERSION:
            raise BackupError(
                f"backup {backup_id!r}: unsupported manifest format "
                f"{doc.get('format')!r} (this build reads "
                f"{FORMAT_VERSION})")
        return doc

    def has_manifest(self, backup_id: str) -> bool:
        return self.exists(backup_id, MANIFEST_NAME)


class LocalDirArchive(ArchiveStore):
    """Local-directory backend with the durable-write discipline of the
    data dir: unique tmp name + fsync + rename, so a crash mid-backup
    never leaves a file the verifier would half-trust — and the manifest
    is written last, so a backup without one is simply incomplete."""

    def __init__(self, root: str):
        self.root = root

    def _path(self, backup_id: str, rel_path: str) -> str:
        # Ids and paths come from manifests and operators: confine the
        # id to the root and the path to that backup's directory (a
        # hostile manifest must not write or read through "..").
        root = os.path.normpath(self.root)
        base = os.path.normpath(os.path.join(root, backup_id))
        p = os.path.normpath(os.path.join(base, rel_path))
        if (not base.startswith(root + os.sep)
                or not p.startswith(base + os.sep)):
            raise BackupError(f"archive path escapes root: "
                              f"{backup_id!r}/{rel_path!r}")
        return p

    def write(self, backup_id: str, rel_path: str, data: bytes) -> None:
        path = self._path(backup_id, rel_path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read(self, backup_id: str, rel_path: str) -> bytes:
        with open(self._path(backup_id, rel_path), "rb") as f:
            return f.read()

    def exists(self, backup_id: str, rel_path: str) -> bool:
        return os.path.exists(self._path(backup_id, rel_path))

    def list_backups(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(d for d in os.listdir(self.root)
                      if os.path.isfile(
                          os.path.join(self.root, d, MANIFEST_NAME)))

    def delete(self, backup_id: str, rel_path: str) -> None:
        try:
            os.remove(self._path(backup_id, rel_path))
        except FileNotFoundError:
            pass

    def delete_backup(self, backup_id: str) -> None:
        import shutil
        base = self._path(backup_id, MANIFEST_NAME)
        self.delete(backup_id, MANIFEST_NAME)  # unlist before payloads go
        shutil.rmtree(os.path.dirname(base), ignore_errors=True)


def fragment_rel_path(index: str, field: str, view: str, shard: int,
                      ext: str) -> str:
    return f"data/{index}/{field}/{view}/{shard}.{ext}"


def meta_rel_path(index: str, field: str | None, name: str) -> str:
    if field is None:
        return f"data/{index}/{name}"
    return f"data/{index}/{field}/{name}"


def resolve_files(manifest: dict) -> dict[str, dict]:
    """path -> entry map of a manifest's complete logical file set.

    Every entry carries ``stored_in`` (the backup id whose archive holds
    the bytes — this backup for captured files, an ancestor for
    incremental refs), so callers read each file with one lookup."""
    out = {}
    for e in manifest.get("files", []):
        entry = dict(e)
        entry.setdefault("stored_in", manifest["id"])
        out[entry["path"]] = entry
    return out
