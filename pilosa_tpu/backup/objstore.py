"""ObjectArchiveStore — S3-compatible HTTP backend for ArchiveStore.

The remote archive is the first dependency that fails *partially*:
timeouts, 5xx storms, torn uploads. This backend gives the backup path
the same discipline the query path already has:

- every operation runs under a per-op timeout and a bounded full-jitter
  retry loop (the ``httpclient`` 503 curve, honoring Retry-After) so a
  transient storm costs latency, not a failed backup;
- every object carries its content CRC as metadata, verified on read —
  a damaged or torn download is retried, then refused, never trusted;
- writes go to a *tmp key* first and are finalized with a server-side
  copy to the real key, so a torn upload is never visible as a real
  object (``list_backups`` and reads only ever see finalized keys),
  preserving the manifest-written-last completeness contract end to
  end.

Key layout inside the bucket mirrors ``LocalDirArchive``::

    <prefix><backup_id>/manifest.json
    <prefix><backup_id>/data/<index>/<field>/<view>/<shard>.snap
    ...

URL scheme (also accepted by ``--archive-url``, ``check --archive``
and ``backup-verify``)::

    http://host:port/bucket[/prefix]    -> ObjectArchiveStore
    https://host:port/bucket[/prefix]   -> ObjectArchiveStore
    file:///path  or  /plain/path       -> LocalDirArchive
"""

from __future__ import annotations

import http.client
import random
import socket
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

from pilosa_tpu.backup.archive import (
    MANIFEST_NAME,
    ArchiveStore,
    BackupError,
    LocalDirArchive,
    file_crc,
)
# Reuse the query path's retry curve so one tuning governs every
# remote dependency (full jitter over an exponential cap; see
# server/httpclient.py for the rationale).
from pilosa_tpu.server.httpclient import RETRY_BASE_DELAY, RETRY_MAX_DELAY

#: metadata header carrying the object's content CRC (S3 user metadata)
CRC_HEADER = "x-amz-meta-crc32"
#: marker segment in tmp keys; anything carrying it is an unfinalized
#: upload and invisible to read/exists/list.
TMP_MARKER = ".tmp-"

#: default attempts per operation (first try + retries)
DEFAULT_ATTEMPTS = 6
#: default per-op socket timeout, seconds
DEFAULT_TIMEOUT = 10.0

#: statuses worth retrying: server-side trouble or explicit backpressure
_RETRY_STATUSES = frozenset({429, 500, 502, 503, 504})

_CONN_ERRORS = (ConnectionError, socket.timeout, TimeoutError, OSError,
                http.client.HTTPException)


class _RetryableDamage(Exception):
    """A read came back bytes-complete but wrong (CRC/length mismatch):
    could be a torn transfer, worth the remaining retry budget."""


def parse_archive_url(url: str) -> tuple[str, str, int, str, str]:
    """-> (scheme, host, port, bucket, key_prefix).

    The first path segment is the bucket; the rest is an optional key
    prefix ('' or 'a/b/'). Raises BackupError for anything that isn't
    http(s) with a bucket."""
    u = urllib.parse.urlsplit(url)
    if u.scheme not in ("http", "https"):
        raise BackupError(f"archive url {url!r}: want http(s)://host/bucket")
    if not u.hostname:
        raise BackupError(f"archive url {url!r}: missing host")
    path = u.path.strip("/")
    if not path:
        raise BackupError(f"archive url {url!r}: missing bucket")
    bucket, _, prefix = path.partition("/")
    port = u.port or (443 if u.scheme == "https" else 80)
    return u.scheme, u.hostname, port, bucket, \
        (prefix + "/" if prefix else "")


def open_archive(root, stats=None, **kwargs) -> ArchiveStore:
    """Archive factory behind every operator knob (``--archive-url``,
    ``check --archive``, ``backup-verify``, POST /backup): an http(s)
    URL opens an object store, anything else a local directory. An
    ArchiveStore instance passes through untouched."""
    if isinstance(root, ArchiveStore):
        return root
    if not isinstance(root, str) or not root:
        raise BackupError("archive: path or http(s) URL required")
    if root.startswith(("http://", "https://")):
        return ObjectArchiveStore(root, stats=stats, **kwargs)
    if root.startswith("file://"):
        root = urllib.parse.urlsplit(root).path
    return LocalDirArchive(root)


class ObjectArchiveStore(ArchiveStore):
    """S3-compatible object store behind the ArchiveStore interface.

    One persistent connection (serialized behind a lock), re-dialed on
    failure; every op is bounded by ``timeout`` and retried up to
    ``attempts`` times with full jitter. Counters (``archive.retries``,
    ``archive.bytesOut``, ``archive.bytesIn``) surface on /debug/vars
    and /metrics when a stats registry is attached."""

    def __init__(self, url: str, stats=None, timeout: float = DEFAULT_TIMEOUT,
                 attempts: int = DEFAULT_ATTEMPTS, rng=None):
        self.url = url.rstrip("/")
        (self.scheme, self.host, self.port,
         self.bucket, self.prefix) = parse_archive_url(url)
        self.stats = stats
        self.timeout = timeout
        self.attempts = max(1, attempts)
        self._rng = rng or random.Random()
        self._conn: http.client.HTTPConnection | None = None
        self._lock = threading.Lock()

    # -- plumbing -----------------------------------------------------------

    def _count(self, name: str, value: int = 1) -> None:
        if self.stats is not None:
            self.stats.count(name, value)

    def _obj_key(self, backup_id: str, rel_path: str) -> str:
        # Ids and paths come from manifests and operators: refuse
        # anything that could escape the prefix (mirrors the
        # LocalDirArchive traversal guard).
        if ("/" in backup_id or backup_id in ("", ".", "..")
                or rel_path.startswith("/")
                or ".." in rel_path.split("/")):
            raise BackupError(f"archive path escapes root: "
                              f"{backup_id!r}/{rel_path!r}")
        return f"{self.prefix}{backup_id}/{rel_path}"

    def _obj_path(self, key: str) -> str:
        return f"/{self.bucket}/" + urllib.parse.quote(key)

    def _dial(self) -> http.client.HTTPConnection:
        cls = (http.client.HTTPSConnection if self.scheme == "https"
               else http.client.HTTPConnection)
        return cls(self.host, self.port, timeout=self.timeout)

    def _backoff(self, attempt: int, retry_after: float | None) -> float:
        cap = min(RETRY_MAX_DELAY, RETRY_BASE_DELAY * (2 ** attempt))
        delay = self._rng.uniform(0, cap)
        if retry_after is not None:
            # The server knows its queue better than our curve does;
            # keep jitter on top so retries don't synchronize.
            delay = retry_after + self._rng.uniform(0, cap)
        return delay

    def _request(self, method: str, path: str, body: bytes | None = None,
                 headers: dict | None = None,
                 ok_statuses: tuple = (200,),
                 not_found_ok: bool = False):
        """One logical op = up to ``attempts`` wire tries. Returns
        (status, lowercased response headers, body bytes); 404 comes
        back (instead of raising) only when ``not_found_ok``. Raises
        BackupError on exhaustion or a non-retryable status."""
        last_err = "unknown"
        with self._lock:
            for attempt in range(self.attempts):
                if attempt:
                    self._count("archive.retries")
                conn, self._conn = self._conn or self._dial(), None
                try:
                    conn.request(method, path, body=body,
                                 headers=headers or {})
                    resp = conn.getresponse()
                    data = resp.read()
                    status = resp.status
                    resp_headers = {k.lower(): v
                                    for k, v in resp.getheaders()}
                except _CONN_ERRORS as e:
                    conn.close()
                    last_err = f"{type(e).__name__}: {e}"
                    time.sleep(self._backoff(attempt, None))
                    continue
                self._conn = conn
                if status in ok_statuses or (status == 404 and not_found_ok):
                    return status, resp_headers, data
                last_err = (f"HTTP {status}: "
                            f"{data[:200].decode(errors='replace')}")
                if status not in _RETRY_STATUSES:
                    break  # other 4xx: retrying won't change the answer
                ra = resp_headers.get("retry-after")
                try:
                    retry_after = float(ra) if ra is not None else None
                except ValueError:
                    retry_after = None
                time.sleep(self._backoff(attempt, retry_after))
        raise BackupError(
            f"object store {method} {path!r} failed after "
            f"{self.attempts} attempt(s): {last_err}")

    # -- ArchiveStore interface ---------------------------------------------

    def write(self, backup_id: str, rel_path: str, data: bytes) -> None:
        key = self._obj_key(backup_id, rel_path)
        crc = file_crc(data)
        headers = {"Content-Length": str(len(data)), CRC_HEADER: str(crc)}
        # tmp-key + finalize: a torn upload leaves only an unfinalized
        # tmp object that read/exists/list never surface; the object
        # becomes real only through the server-side copy, which starts
        # from a fully-received tmp body.
        tmp = f"{key}{TMP_MARKER}{uuid.uuid4().hex[:8]}"
        self._request("PUT", self._obj_path(tmp), body=data, headers=headers)
        self._request("PUT", self._obj_path(key), body=b"", headers={
            "Content-Length": "0",
            "x-amz-copy-source": self._obj_path(tmp),
        })
        self._count("archive.bytesOut", len(data))
        try:
            self._request("DELETE", self._obj_path(tmp),
                          ok_statuses=(200, 204), not_found_ok=True)
        except BackupError:
            pass  # orphaned tmp key: invisible to reads and listings

    def read(self, backup_id: str, rel_path: str) -> bytes:
        key = self._obj_key(backup_id, rel_path)
        last = "unknown"
        for attempt in range(self.attempts):
            _, resp_headers, data = self._request("GET", self._obj_path(key))
            try:
                self._verify_read(resp_headers, data)
            except _RetryableDamage as e:
                last = str(e)
                self._count("archive.retries")
                time.sleep(self._backoff(attempt, None))
                continue
            self._count("archive.bytesIn", len(data))
            return data
        raise BackupError(f"object store GET {key!r}: {last}")

    def _verify_read(self, resp_headers: dict, data: bytes) -> None:
        want_len = resp_headers.get("content-length")
        if want_len is not None and int(want_len) != len(data):
            raise _RetryableDamage(
                f"torn download: got {len(data)} of {want_len} bytes")
        want_crc = resp_headers.get(CRC_HEADER)
        if want_crc is not None and int(want_crc) != file_crc(data):
            raise _RetryableDamage(
                f"content CRC mismatch (want {want_crc}, "
                f"got {file_crc(data)})")

    def exists(self, backup_id: str, rel_path: str) -> bool:
        key = self._obj_key(backup_id, rel_path)
        status, _, _ = self._request("HEAD", self._obj_path(key),
                                     not_found_ok=True)
        return status == 200

    def delete(self, backup_id: str, rel_path: str) -> None:
        key = self._obj_key(backup_id, rel_path)
        self._request("DELETE", self._obj_path(key),
                      ok_statuses=(200, 204), not_found_ok=True)

    def list_backups(self) -> list[str]:
        out = []
        for key in self._list_keys(self.prefix):
            # A backup is real iff its FINALIZED manifest object exists
            # directly under <prefix><id>/ — the completeness contract.
            rest = key[len(self.prefix):]
            parts = rest.split("/")
            if len(parts) == 2 and parts[1] == MANIFEST_NAME:
                out.append(parts[0])
        return sorted(out)

    def delete_backup(self, backup_id: str) -> None:
        """Remove every object of a backup, manifest FIRST: the backup
        drops out of list_backups before any payload byte goes, so a
        crash mid-delete leaves only complete, restorable listings."""
        prefix = self._obj_key(backup_id, "x")[:-1]
        keys = self._list_keys(prefix)
        keys.sort(key=lambda k: (not k.endswith("/" + MANIFEST_NAME), k))
        for key in keys:
            self._request("DELETE", self._obj_path(key),
                          ok_statuses=(200, 204), not_found_ok=True)

    # -- listing ------------------------------------------------------------

    def _list_keys(self, prefix: str) -> list[str]:
        """All finalized keys under ``prefix`` (ListObjectsV2, paged)."""
        keys: list[str] = []
        token = None
        while True:
            q = f"list-type=2&prefix={urllib.parse.quote(prefix)}"
            if token:
                q += f"&continuation-token={urllib.parse.quote(token)}"
            _, _, body = self._request("GET", f"/{self.bucket}?{q}")
            try:
                root = ET.fromstring(body.decode())
            except ET.ParseError as e:
                raise BackupError(
                    f"object store list: unparseable response: {e}") from e
            for el in root.iter():
                if el.tag.endswith("Key") and el.text \
                        and TMP_MARKER not in el.text:
                    keys.append(el.text)
            truncated = next((el.text for el in root.iter()
                              if el.tag.endswith("IsTruncated")), "false")
            token = next((el.text for el in root.iter()
                          if el.tag.endswith("NextContinuationToken")), None)
            if truncated != "true" or not token:
                return keys

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
