"""BackupWriter — cluster-consistent full + incremental capture.

One node drives the backup (whichever received ``/backup`` — any node
works; the data plane is symmetric). For every fragment key known
anywhere in the cluster it captures the on-disk pair — CRC-verified
``.snap`` + the WAL segment's valid prefix — exactly once, from the
first healthy owner: the local store when this node owns the shard, a
peer via the internal backup RPC otherwise. A quarantined or
corrupt-on-read copy fails over to the next replica, so a backup never
launders damage into the archive.

Snapshots are NOT forced before capture: the archived (snap, wal) pair
reproduces the live state exactly (that is the load-path contract), and
keeping the WAL segment is what makes point-in-time recovery possible —
a forced snapshot would truncate the very history PITR replays.

Incremental mode captures only what changed since the parent manifest:
an index whose mutation epoch stands still (same process incarnation)
is skipped wholesale — except shards the write fan-out marked dirty —
and everything else is compared file-by-file by CRC, so an unchanged
snapshot with a grown WAL ships just the WAL segment.

Every fragment's work is admitted under the QoS internal class, the
same gate the scrubber uses, so a backup never starves user queries.
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.backup.archive import (
    ArchiveStore,
    BackupError,
    KIND_ATTRS,
    KIND_SCHEMA,
    KIND_SNAP,
    KIND_TRANSLATE,
    KIND_WAL,
    FORMAT_VERSION,
    file_crc,
    fragment_rel_path,
    meta_rel_path,
    new_backup_id,
    resolve_files,
)
from pilosa_tpu.qos.admission import CLASS_INTERNAL, QueryShedError
from pilosa_tpu.storage.integrity import SnapshotCorruptError, split_snapshot
from pilosa_tpu.storage.quarantine import ShardCorruptError
from pilosa_tpu.storage.wal import scan_wal

#: shed-retry schedule for the per-fragment admission gate: a backup
#: must not skip shards (unlike the scrubber, whose next pass retries),
#: so it backs off and re-admits before giving up on the whole run.
ADMIT_RETRIES = 40
ADMIT_RETRY_DELAY = 0.05


def capture_fragment(store, key: tuple) -> dict:
    """Read one fragment's durable pair from a node's DiskStore.

    Returns ``{"snap": bytes|None, "wal": bytes|None, "ops": int}``.
    Raises ``ShardCorruptError`` when the local copy is quarantined or
    fails verification on read (the caller fails over to a replica) and
    ``LookupError`` when the fragment has no durable files at all."""
    if store.quarantine.get(key) is not None:
        # Any quarantine state — routed, unavailable, even degraded —
        # means this copy is not the full acknowledged truth.
        raise ShardCorruptError()
    import os
    snap_path = store._snap_path(key)
    wal_path = store._wal_path(key)
    snap = None
    if os.path.exists(snap_path):
        with open(snap_path, "rb") as f:
            snap = f.read()
        try:
            split_snapshot(snap)  # CRC-verify before the bytes ship
        except SnapshotCorruptError as e:
            raise ShardCorruptError() from e
    wal = None
    ops = 0
    info = scan_wal(wal_path)
    if info["corrupt"]:
        raise ShardCorruptError()
    if info["total_bytes"]:
        with open(wal_path, "rb") as f:
            # Only the valid prefix ships: a torn tail is the normal
            # crash shape and replay would truncate it anyway.
            wal = f.read(info["valid_bytes"])
        ops = info["ops"]
    if snap is None and wal is None:
        raise LookupError(f"no durable files for {key}")
    return {"snap": snap, "wal": wal, "ops": ops}


class BackupWriter:
    """Drives one backup run; ``progress`` is live for /backup/status."""

    def __init__(self, holder, cluster, client, store,
                 archive: ArchiveStore, stats=None, logger=None,
                 admission=None):
        self.holder = holder
        self.cluster = cluster
        self.client = client
        self.store = store
        self.archive = archive
        self.stats = stats
        self.logger = logger
        self.admission = admission
        self.progress: dict = {"state": "idle"}
        self._lock = threading.Lock()

    # -- helpers -----------------------------------------------------------

    def _count(self, name: str, value: int = 1) -> None:
        if self.stats is not None:
            self.stats.count(name, value)

    def _log(self, fmt: str, *args) -> None:
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    def _admitted(self, fn):
        if self.admission is None:
            return fn()
        for attempt in range(ADMIT_RETRIES):
            try:
                with self.admission.admit(CLASS_INTERNAL):
                    return fn()
            except QueryShedError:
                self._count("backup.shedRetries")
                time.sleep(ADMIT_RETRY_DELAY * (attempt + 1))
        raise BackupError("backup shed by admission control: node "
                          "overloaded, retry later")

    def _local_id(self) -> str | None:
        return self.cluster.local_id if self.cluster is not None else None

    def _live_peers(self):
        if self.cluster is None:
            return []
        return [n for n in self.cluster.nodes
                if n.id != self.cluster.local_id and n.state != "DOWN"]

    # -- enumeration -------------------------------------------------------

    def _all_keys(self) -> dict[tuple, list]:
        """Every fragment key known cluster-wide -> nodes listing it
        (local node first when present)."""
        keys: dict[tuple, list] = {}
        for key in self.store.all_fragment_keys():
            keys.setdefault(key, []).append(None)  # None = local
        for node in self._live_peers():
            try:
                listed = self.client.backup_keys(node)
            except (ConnectionError, RuntimeError, OSError):
                self._count("backup.nodesUnreachable")
                self._log("backup: cannot list fragments on %s", node.id)
                continue
            for item in listed:
                keys.setdefault(tuple(item[:3]) + (int(item[3]),),
                                []).append(node)
        return keys

    def _candidates(self, key: tuple, listers: list):
        """Capture order: owners under current placement (local first),
        then any non-owner that listed the key (stale former owners
        still hold restorable bytes)."""
        index, _field, _view, shard = key
        out, seen = [], set()

        def add(node):
            nid = node.id if node is not None else None
            if nid not in seen:
                seen.add(nid)
                out.append(node)

        if self.cluster is not None:
            for n in self.cluster.shard_nodes(index, shard):
                if n.state == "DOWN":
                    continue
                add(None if n.id == self.cluster.local_id else n)
        else:
            add(None)
        for n in listers:
            add(n)
        return out

    # -- capture -----------------------------------------------------------

    def _capture(self, key: tuple, listers: list) -> dict | None:
        """Fetch one fragment from the first healthy candidate; None
        when the fragment vanished everywhere (deleted mid-backup)."""
        index, field, view, shard = key
        found = False
        for node in self._candidates(key, listers):
            try:
                if node is None:
                    pair = capture_fragment(self.store, key)
                else:
                    raw = self.client.backup_fragment(node, index, field,
                                                      view, shard)
                    pair = {"snap": raw.get("snap"), "wal": raw.get("wal"),
                            "ops": int(raw.get("ops") or 0)}
                    self._count("backup.fragmentsRemote")
                pair["source"] = node.id if node is not None \
                    else (self._local_id() or "local")
                return pair
            except ShardCorruptError:
                found = True
                self._count("backup.skippedQuarantined")
                self._log("backup: %s/%s/%s/%d unhealthy on %s, trying "
                          "next replica", index, field, view, shard,
                          node.id if node is not None else "local")
            except LookupError:
                continue
            except (ConnectionError, OSError, RuntimeError):
                found = True
                self._count("backup.fetchErrors")
        if found:
            raise BackupError(
                f"no healthy copy of {index}/{field}/{view}/{shard} "
                "reachable: backup would be incomplete")
        return None  # listed nowhere anymore: deleted mid-backup

    # -- meta stores -------------------------------------------------------

    def _meta_files(self) -> list[tuple[str, str, str, str | None, bytes]]:
        """[(rel_path, kind, index, field, jsonl bytes)] for every
        non-empty translate/attr store, serialized from the in-memory
        truth with the same checksummed line frames the data dir uses."""
        import json as _json

        from pilosa_tpu.storage.integrity import frame_line

        out = []

        def jsonl(pairs) -> bytes:
            return "".join(frame_line(_json.dumps(p)) + "\n"
                           for p in pairs).encode()

        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            entries = idx.translate_store.entries_since(0)
            if entries:
                out.append((meta_rel_path(iname, None, "translate.jsonl"),
                            KIND_TRANSLATE, iname, None,
                            jsonl([[i, k] for i, k in entries])))
            attrs = idx.column_attr_store
            ids = attrs.ids()
            if ids:
                out.append((meta_rel_path(iname, None, "column_attrs.jsonl"),
                            KIND_ATTRS, iname, None,
                            jsonl([[i, attrs.attrs(i)] for i in ids])))
            for fname, f in sorted(idx.fields.items()):
                entries = f.translate_store.entries_since(0)
                if entries:
                    out.append((meta_rel_path(iname, fname,
                                              "translate.jsonl"),
                                KIND_TRANSLATE, iname, fname,
                                jsonl([[i, k] for i, k in entries])))
                ids = f.row_attr_store.ids()
                if ids:
                    out.append((meta_rel_path(iname, fname,
                                              "row_attrs.jsonl"),
                                KIND_ATTRS, iname, fname,
                                jsonl([[i, f.row_attr_store.attrs(i)]
                                       for i in ids])))
        return out

    # -- run ---------------------------------------------------------------

    def run(self, backup_id: str | None = None,
            parent: str | None = None) -> dict:
        """Capture one backup; returns the manifest. ``parent`` makes it
        incremental against that manifest (which must be restorable from
        the same archive root)."""
        kind = "incremental" if parent else "full"
        backup_id = backup_id or new_backup_id(kind)
        t0 = time.perf_counter()
        parent_manifest = None
        parent_files: dict[str, dict] = {}
        if parent:
            parent_manifest = self.archive.read_manifest(parent)
            parent_files = resolve_files(parent_manifest)

        keys = self._admitted(self._all_keys)
        dirty = set()
        if self.cluster is not None:
            dirty = self.cluster.dirty_shards.peek()

        # Epoch fast path: an index whose (instance, epoch) pair matches
        # the parent's had no mutation since that capture — reference
        # its fragment files wholesale, except dirty shards (a DOWN
        # replica missed a write there; re-capture settles which copy
        # the archive trusts). instance_id pins the comparison to one
        # process incarnation: epochs restart at 0 across restarts.
        epochs = {}
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            epochs[iname] = {"instance": idx.instance_id,
                             "epoch": idx.epoch.value,
                             "schemaEpoch": idx.schema_epoch.value}
        unchanged_indexes = set()
        if parent_manifest is not None:
            for iname, stamp in epochs.items():
                if parent_manifest.get("epochs", {}).get(iname) == stamp:
                    unchanged_indexes.add(iname)

        with self._lock:
            self.progress = {"state": "running", "id": backup_id,
                             "kind": kind, "totalFragments": len(keys),
                             "doneFragments": 0, "files": 0, "bytes": 0,
                             "unchanged": 0}
        files: list[dict] = []
        stored_bytes = 0

        def store_file(rel: str, kind_: str, data: bytes, **extra) -> dict:
            nonlocal stored_bytes
            crc = file_crc(data)
            pe = parent_files.get(rel)
            entry = {"path": rel, "kind": kind_, "crc": crc,
                     "size": len(data), **extra}
            if pe is not None and pe.get("crc") == crc:
                entry["stored_in"] = pe.get("stored_in", parent)
                self.progress["unchanged"] += 1
                self._count("backup.skippedUnchanged")
            else:
                self.archive.write(backup_id, rel, data)
                stored_bytes += len(data)
                self.progress["files"] += 1
                self.progress["bytes"] = stored_bytes
            return entry

        try:
            for key in sorted(keys):
                index, field, view, shard = key
                if (index in unchanged_indexes
                        and (index, shard) not in dirty):
                    for ext in ("snap", "wal"):
                        rel = fragment_rel_path(index, field, view, shard,
                                                ext)
                        pe = parent_files.get(rel)
                        if pe is not None:
                            files.append(dict(pe))
                            self.progress["unchanged"] += 1
                    self.progress["doneFragments"] += 1
                    continue
                pair = self._admitted(
                    lambda k=key, ls=keys[key]: self._capture(k, ls))
                if pair is None:
                    self.progress["doneFragments"] += 1
                    continue
                base = {"index": index, "field": field, "view": view,
                        "shard": shard, "source": pair["source"]}
                if pair["snap"] is not None:
                    files.append(store_file(
                        fragment_rel_path(index, field, view, shard,
                                          "snap"),
                        KIND_SNAP, pair["snap"], **base))
                if pair["wal"] is not None:
                    files.append(store_file(
                        fragment_rel_path(index, field, view, shard,
                                          "wal"),
                        KIND_WAL, pair["wal"], ops=pair["ops"], **base))
                self._count("backup.fragments")
                self.progress["doneFragments"] += 1

            for rel, kind_, iname, fname, data in self._meta_files():
                files.append(store_file(rel, kind_, data, index=iname,
                                        field=fname))

            import json as _json
            schema = self.holder.schema()
            files.append(store_file("schema.json", KIND_SCHEMA,
                                    _json.dumps(schema).encode()))

            manifest = {
                "format": FORMAT_VERSION,
                "id": backup_id,
                "parent": parent,
                "kind": kind,
                "created": time.time(),
                "cluster": {
                    "nodes": (len(self.cluster.nodes)
                              if self.cluster is not None else 1),
                    "replicaN": (self.cluster.replica_n
                                 if self.cluster is not None else 1),
                },
                "epochs": epochs,
                "schema": schema,
                "files": files,
            }
            # Manifest last: its presence marks the backup complete.
            self.archive.write_manifest(backup_id, manifest)
        except BaseException as e:
            with self._lock:
                self.progress = dict(self.progress, state="failed",
                                     error=str(e))
            self._count("backup.failures")
            raise

        seconds = time.perf_counter() - t0
        with self._lock:
            self.progress = dict(self.progress, state="done",
                                 seconds=round(seconds, 3))
        self._count("backup.runs")
        self._count("backup.files", self.progress["files"])
        self._count("backup.bytes", stored_bytes)
        if self.stats is not None:
            self.stats.timing("backup.seconds", seconds)
            self.stats.gauge("backup.lastSuccess", time.time())
            if seconds > 0:
                self.stats.gauge("backup.bytesPerSec",
                                 stored_bytes / seconds)
        self._log("backup %s (%s): %d fragments, %d files, %d bytes "
                  "stored (%d unchanged) in %.2fs", backup_id, kind,
                  len(keys), self.progress["files"], stored_bytes,
                  self.progress["unchanged"], seconds)
        return manifest
