"""Offline archive verification — ``cmd_check --archive`` / ``backup-verify``.

Walks one backup (or every backup under a root) without touching a live
cluster: manifest present and well-formed, parent chain resolvable,
every listed file present in the archive that claims to hold it, every
whole-file CRC intact, every snapshot footer verified, every WAL segment
a clean op chain (no mid-file corruption, op count matching the
manifest), every jsonl line frame valid. Exit-1 material for the CLI:
damage found here is damage a restore would hit at the worst moment.
"""

from __future__ import annotations

import json

from pilosa_tpu.backup.archive import (
    ArchiveStore,
    BackupError,
    KIND_ATTRS,
    KIND_SNAP,
    KIND_TRANSLATE,
    KIND_WAL,
    file_crc,
    resolve_files,
)
from pilosa_tpu.storage.integrity import (
    LineCorruptError,
    SnapshotCorruptError,
    parse_line,
    split_snapshot,
)


def _verify_wal_bytes(data: bytes) -> dict:
    """scan_wal's contract over in-memory bytes: archived segments hold
    only valid records (the writer ships the valid prefix), so ANY
    trailing garbage — torn or mid-file — is archive damage."""
    from pilosa_tpu.storage.wal import iter_wal_records
    ops = 0
    consumed = 0
    from pilosa_tpu.storage.wal import _HEADER
    off = 0
    for _code, rows, cols in iter_wal_records(data):
        ops += 1
        off += _HEADER.size + 8 * (len(rows) + len(cols))
    consumed = off
    return {"ops": ops, "clean": consumed == len(data)}


def verify_backup(store: ArchiveStore, backup_id: str) -> dict:
    """Verify one backup; returns {"ok", "problems", "checked"}."""
    problems: list[str] = []
    checked = 0
    try:
        manifest = store.read_manifest(backup_id)
    except BackupError as e:
        return {"ok": False, "problems": [str(e)], "checked": 0}

    # Parent chain: every ancestor an incremental references must still
    # be a complete backup, or its referenced bytes are gone.
    seen = {backup_id}
    parent = manifest.get("parent")
    while parent:
        if parent in seen:
            problems.append(f"parent chain loop at {parent!r}")
            break
        seen.add(parent)
        if not store.has_manifest(parent):
            problems.append(f"missing parent backup {parent!r}")
            break
        parent = store.read_manifest(parent).get("parent")

    for path, entry in sorted(resolve_files(manifest).items()):
        checked += 1
        holder_id = entry["stored_in"]
        if not store.exists(holder_id, path):
            problems.append(f"{path}: missing from backup {holder_id!r}")
            continue
        data = store.read(holder_id, path)
        if entry.get("size") is not None and len(data) != entry["size"]:
            problems.append(f"{path}: size mismatch (manifest "
                            f"{entry['size']}, file {len(data)})")
        if file_crc(data) != entry.get("crc"):
            problems.append(f"{path}: file CRC mismatch")
            continue  # deeper checks would just re-report the damage
        kind = entry.get("kind")
        if kind == KIND_SNAP:
            try:
                _payload, meta = split_snapshot(data)
                if meta is None:
                    problems.append(f"{path}: snapshot has no footer")
            except SnapshotCorruptError as e:
                problems.append(f"{path}: {e}")
        elif kind == KIND_WAL:
            info = _verify_wal_bytes(data)
            if not info["clean"]:
                problems.append(f"{path}: WAL chain broken (trailing "
                                "bytes fail record verification)")
            elif (entry.get("ops") is not None
                    and info["ops"] != entry["ops"]):
                problems.append(f"{path}: WAL op count mismatch "
                                f"(manifest {entry['ops']}, "
                                f"file {info['ops']})")
        elif kind in (KIND_TRANSLATE, KIND_ATTRS):
            for i, ln in enumerate(data.decode().splitlines()):
                if not ln:
                    continue
                try:
                    payload, _verified = parse_line(ln)
                    json.loads(payload)
                except (LineCorruptError, ValueError) as e:
                    problems.append(f"{path}: line {i + 1}: {e}")
    return {"ok": not problems, "problems": problems, "checked": checked}


def verify_archive(root, backup_id: str | None = None) -> dict:
    """Verify one backup, or every backup under an archive root.

    ``root`` is a directory path, an object-store URL, or an
    ArchiveStore. Returns ``{"ok", "problems", "checked", "backups"}``
    with problems prefixed by backup id when scanning the whole root."""
    from pilosa_tpu.backup.objstore import open_archive
    store = root if isinstance(root, ArchiveStore) else open_archive(root)
    if backup_id is not None:
        out = verify_backup(store, backup_id)
        out["backups"] = 1
        return out
    ids = store.list_backups()
    problems: list[str] = []
    checked = 0
    for bid in ids:
        res = verify_backup(store, bid)
        problems.extend(f"{bid}: {p}" for p in res["problems"])
        checked += res["checked"]
    if not ids:
        problems.append("no complete backups found in archive root")
    return {"ok": not problems, "problems": problems, "checked": checked,
            "backups": len(ids)}
