"""Retention — keep-N-full-chains pruning with a crash-safe journal.

An unattended scheduler fills the archive forever; retention is the
half that empties it, and it is the ONLY code allowed to delete from an
archive, so it is built around one invariant:

    at every instant — including mid-crash — every backup that
    ``list_backups`` returns is fully restorable.

Mechanics, in order:

1. **Plan.** Backups group into chains by following ``parent`` links to
   their full; the newest ``keep_chains`` chains survive, older chains
   are victims.
2. **Prove.** A victim is only deletable if no *surviving* manifest's
   ``stored_in`` refs reach it (an incremental references the ancestor
   that physically holds its unchanged bytes). Anything still
   referenced is kept, whatever the chain math said.
3. **Verify before prune.** Every survivor passes a restore preflight
   (all refs present, CRC spot-checks) BEFORE anything is deleted —
   deleting from an archive whose survivors are already damaged only
   destroys evidence.
4. **Journal, then delete.** The victim list lands in a journal object
   first; each victim's manifest is deleted before its payloads (so it
   drops out of listings while still whole); a crash mid-prune leaves
   either intact listed backups or unlisted orphans that the next
   prune run sweeps by replaying the journal.
"""

from __future__ import annotations

import json
import time

from pilosa_tpu.backup.archive import (
    ArchiveStore,
    BackupError,
    resolve_files,
)
from pilosa_tpu.backup.restore import preflight_restore

#: pseudo backup id holding the prune journal. It never carries a
#: manifest, so no backend ever lists it as a backup.
JOURNAL_ID = "_prune"
JOURNAL_NAME = "journal.json"


def plan_prune(archive: ArchiveStore, keep_chains: int) -> dict:
    """The prune decision, decided but not executed: which backups are
    victims, which survive, and why — so tests (and operators via
    /debug/backup) can audit the reachability proof separately from
    the deletes."""
    ids = archive.list_backups()
    manifests = {bid: archive.read_manifest(bid) for bid in ids}

    def root_of(bid: str) -> str:
        seen = {bid}
        cur = bid
        while True:
            parent = manifests[cur].get("parent")
            if not parent or parent not in manifests or parent in seen:
                return cur
            seen.add(parent)
            cur = parent

    roots = {bid: root_of(bid) for bid in ids}
    chain_order = sorted({r for r in roots.values()},
                         key=lambda r: (manifests[r].get("created", 0), r))
    kept_roots = set(chain_order[-keep_chains:]) if keep_chains > 0 \
        else set(chain_order)
    victims = [bid for bid in ids if roots[bid] not in kept_roots]
    survivors = [bid for bid in ids if roots[bid] in kept_roots]

    # The proof: union every survivor's stored_in refs; a victim any
    # survivor still reaches is NOT deletable, whatever chain it's in.
    referenced: set[str] = set(survivors)
    for bid in survivors:
        for entry in resolve_files(manifests[bid]).values():
            referenced.add(entry["stored_in"])
    still_referenced = [v for v in victims if v in referenced]
    victims = [v for v in victims if v not in referenced]
    return {"victims": victims, "survivors": survivors,
            "stillReferenced": still_referenced, "manifests": manifests}


def _replay_journal(archive: ArchiveStore, stats, logger) -> int:
    """Finish a crashed prune: its victims are already journaled (and
    possibly half-deleted); deleting them again is idempotent."""
    if not archive.exists(JOURNAL_ID, JOURNAL_NAME):
        return 0
    try:
        journal = json.loads(archive.read(JOURNAL_ID, JOURNAL_NAME))
    except (ValueError, BackupError, OSError):
        archive.delete(JOURNAL_ID, JOURNAL_NAME)
        return 0
    resumed = 0
    if journal.get("state") == "pruning":
        for bid in journal.get("victims", []):
            archive.delete_backup(bid)
            resumed += 1
        if resumed and logger is not None:
            logger.printf("backup retention: resumed crashed prune "
                          "(%d victim(s) swept)", resumed)
        if resumed and stats is not None:
            stats.count("backup.retention.resumed", resumed)
    archive.delete(JOURNAL_ID, JOURNAL_NAME)
    return resumed


def prune_archive(archive: ArchiveStore, keep_chains: int,
                  stats=None, logger=None, fence=None) -> dict:
    """Apply the keep-N-full-chains policy. Returns a summary dict;
    ``aborted`` is set (and nothing was deleted) when a survivor
    failed its pre-prune verification, or when the ``fence`` gate
    (a callable; the scheduler passes its quorum-fence check) says a
    partitioned minority must not delete from a shared archive a
    majority-side successor may be writing to."""
    if fence is not None and fence():
        if stats is not None:
            stats.count("backup.retention.fenced")
        if logger is not None:
            logger.printf("backup retention: skipped while fenced")
        return {"pruned": 0, "victims": [], "survivors": 0,
                "stillReferenced": [], "resumed": 0, "aborted": "fenced"}
    resumed = _replay_journal(archive, stats, logger)
    plan = plan_prune(archive, keep_chains)
    victims, survivors = plan["victims"], plan["survivors"]
    summary = {"pruned": 0, "victims": victims,
               "survivors": len(survivors),
               "stillReferenced": plan["stillReferenced"],
               "resumed": resumed, "aborted": None}
    if plan["stillReferenced"] and logger is not None:
        logger.printf("backup retention: keeping %s: still referenced "
                      "by a surviving manifest",
                      ",".join(plan["stillReferenced"]))
    if not victims:
        return summary

    # Verify-before-prune: every survivor must be restorable NOW.
    for bid in survivors:
        try:
            preflight_restore(archive, plan["manifests"][bid],
                              crc_samples=2)
        except BackupError as e:
            summary["aborted"] = f"survivor {bid} failed preflight: {e}"
            if stats is not None:
                stats.count("backup.retention.aborts")
            if logger is not None:
                logger.printf("backup retention ABORTED: %s",
                              summary["aborted"])
            return summary

    archive.write(JOURNAL_ID, JOURNAL_NAME, json.dumps({
        "state": "pruning", "victims": victims,
        "keep": survivors, "startedEpoch": time.time()}).encode())
    for bid in victims:
        archive.delete_backup(bid)
        summary["pruned"] += 1
    archive.delete(JOURNAL_ID, JOURNAL_NAME)
    if stats is not None:
        stats.count("backup.retention.pruned", summary["pruned"])
    if logger is not None:
        logger.printf("backup retention: pruned %d superseded backup(s) "
                      "(%s), %d surviving", summary["pruned"],
                      ",".join(victims), len(survivors))
    return summary


__all__ = ["JOURNAL_ID", "JOURNAL_NAME", "plan_prune", "prune_archive"]
