"""Fragment — one (field, view, shard) bitmap matrix.

Reference: fragment.go (struct :100, setBit/clearBit :645/:729, row :602,
pos encoding :3090, sum/min/max :1111-1227, rangeOp :1272, top :1570,
bulkImport :1997, importValue :2205, Blocks/checksums :1762-1841,
mutex/bool vectors :3094-3164).

Design split (TPU-first):
- **Host truth**: ``rows[row_id] -> HostRow`` — sparse positions at rest,
  dense past cutoff. Mutations are host ops (the device never scatters
  single bits; cf. SURVEY §7 "mutation on TPU").
- **Device cache**: dense uint32 blocks uploaded lazily per row / per row
  stack, invalidated by a generation counter. Query math (set algebra,
  BSI, popcounts) runs on-device over these blocks.
- **Row-count vector**: per-row popcounts maintained incrementally on
  host; TopN/Rows read it directly. This *replaces* the reference's
  rankCache machinery (cache.go:136) — recompute is exact and cheap, so
  there is no threshold staleness to manage.

The reference's positional flattening pos = row*ShardWidth + col%ShardWidth
(fragment.go:3090) survives only in the WAL/serialized format; in memory the
row dimension is explicit (it is the device batch axis).
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.config import (
    DEFAULT_CACHE_SIZE,
    HASH_BLOCK_SIZE,
    SHARD_WIDTH,
    WORDS_PER_SHARD,
)
from pilosa_tpu.core.hostrow import HostRow
from pilosa_tpu.core.row import Row
from pilosa_tpu.ops import bitops, bsi as bsi_ops, pallas_kernels

# BSI row layout, reference fragment.go:87-93.
FALSE_ROW_ID = 0
TRUE_ROW_ID = 1
BSI_EXISTS_BIT = 0
BSI_SIGN_BIT = 1
BSI_OFFSET_BIT = 2

#: Row-group tiling (SURVEY §7): streaming count paths materialize at most
#: this many rows on device at once, so TopN/GroupBy over huge fields
#: (1M+ rows; BASELINE "TopN ranked cache 1M×10M") run in O(tile) HBM
#: instead of O(rows) — the reference's analog is per-container iteration
#: (fragment.go:1570-1740).
ROW_TILE = 512
#: Row sets at or below this size use the cached whole-stack fast path
#: (repeat queries hit HBM-resident blocks with zero re-upload).
STACK_CACHE_MAX_ROWS = 1024
#: With ``reuse=True``, up to this many streamed tiles stay device-resident
#: so repeated sweeps over the same row set (GroupBy: one per group prefix)
#: skip re-materialization; larger sets fall back to pure streaming.
MAX_RESIDENT_TILES = 8


class Fragment:
    """One shard of one view of one field."""

    def __init__(self, index: str, field: str, view: str, shard: int,
                 cache_type: str = "ranked", cache_size: int = DEFAULT_CACHE_SIZE,
                 stats=None, op_writer: Callable | None = None,
                 mutex: bool = False, epoch=None):
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.stats = stats
        #: WAL hook: called as op_writer(op, rows, cols) on mutation.
        self.op_writer = op_writer
        #: Mutex semantics: at most one row bit per column (reference
        #: mutexVector fragment.go:3094; bool fields use rows 0/1).
        self.mutex = mutex
        #: index-level Epoch (core.index): bumped on every mutation so
        #: index-wide caches (planner leaf stacks, executor results)
        #: validate in O(1) instead of per-fragment generation walks.
        self.epoch = epoch

        self.rows: dict[int, HostRow] = {}
        self.generation = 0
        #: Mutex vector (fragment.go:3094): lazily-built local-pos -> row_id
        #: map so mutex lookups/imports are O(1) per column instead of a
        #: scan over every row. None = not built / dirty. Maintained
        #: incrementally by set_bit/clear_bit; any other mutation of
        #: ``rows`` must reset it to None.
        self._col_row: dict[int, int] | None = None
        #: generation-stamped (gen, ids, counts) — see row_counts().
        self._count_cache: tuple | None = None
        #: generation-stamped (gen, ids, counts) sorted by count desc —
        #: see top_counts().
        self._top_cache: tuple | None = None
        #: generation-stamped concatenated sparse-row index — see
        #: _sparse_index().
        self._sparse_cache: tuple | None = None
        #: generation-stamped (gen, depth, [depth+1, W] words) host stack
        #: of the sign + magnitude planes — see value().
        self._value_stack: tuple | None = None
        self._lock = threading.RLock()
        # device caches: row_id -> (gen, jax.Array[W]); stack key -> (gen, ids, jax.Array[n, W])
        self._dev_rows: dict[int, tuple[int, jax.Array]] = {}
        self._dev_stacks: dict[object, tuple[int, tuple, jax.Array]] = {}

    # -- position encoding -------------------------------------------------

    def _local(self, column_id: int) -> int:
        lo = self.shard * SHARD_WIDTH
        if not (lo <= column_id < lo + SHARD_WIDTH):
            raise ValueError(f"column:{column_id} out of bounds")
        return column_id - lo

    # -- mutation ----------------------------------------------------------

    def _invalidate(self, bump_epoch: bool = True):
        self.generation += 1
        if bump_epoch and self.epoch is not None:
            # Shard-tagged: plans not touching this shard keep their
            # cached results (Epoch.max_shard_epoch).
            self.epoch.bump(shard=self.shard)
        # Stale device blocks would never be re-hit (generation mismatch) but
        # would pin HBM forever; drop them eagerly.
        self._dev_rows.clear()
        self._dev_stacks.clear()
        self._value_stack = None

    def set_bit(self, row_id: int, column_id: int) -> bool:
        with self._lock:
            pos = self._local(column_id)
            if self.mutex:
                # Unset any other row's bit for this column first
                # (reference handleMutex fragment.go:3094-3164).
                existing = self.row_for_column(column_id)
                if existing is not None and existing != row_id:
                    self.clear_bit(existing, column_id)
            hr = self.rows.get(row_id)
            if hr is None:
                hr = self.rows[row_id] = HostRow()
            changed = hr.add(pos)
            if changed:
                if self.mutex and self._col_row is not None:
                    self._col_row[pos] = row_id
                self._invalidate()
                if self.op_writer:
                    self.op_writer("add", [row_id], [column_id])
            return changed

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        with self._lock:
            pos = self._local(column_id)
            hr = self.rows.get(row_id)
            if hr is None:
                return False
            changed = hr.remove(pos)
            if changed:
                if (self.mutex and self._col_row is not None
                        and self._col_row.get(pos) == row_id):
                    del self._col_row[pos]
                self._invalidate()
                if self.op_writer:
                    self.op_writer("remove", [row_id], [column_id])
            return changed

    def contains(self, row_id: int, column_id: int) -> bool:
        hr = self.rows.get(row_id)
        return hr is not None and hr.contains(self._local(column_id))

    def clear_row(self, row_id: int) -> bool:
        """Reference clearRow (fragment.go, used by ClearRow/Store)."""
        with self._lock:
            hr = self.rows.pop(row_id, None)
            if hr is None or hr.count() == 0:
                return False
            self._col_row = None
            self._invalidate()
            if self.op_writer:
                cols = (hr.to_positions() + np.uint64(self.shard * SHARD_WIDTH))
                self.op_writer("removeBatch", [row_id] * len(cols), cols.tolist())
            return True

    def set_row(self, row: Row, row_id: int) -> bool:
        """Replace a row wholesale (reference setRow, used by Store)."""
        with self._lock:
            seg = row.segment(self.shard)
            words = np.asarray(seg) if seg is not None else bitops.np_zero_row()
            self.rows[row_id] = HostRow.from_words(words)
            self._col_row = None
            self._invalidate()
            if self.op_writer:
                cols = bitops.words_to_positions(words) + np.uint64(self.shard * SHARD_WIDTH)
                self.op_writer("setRow", [row_id], cols.tolist())
            return True

    def bulk_import(self, row_ids: Iterable[int], column_ids: Iterable[int],
                    clear: bool = False) -> int:
        """Batched set/clear (reference bulkImport fragment.go:1997).
        Returns number of changed bits."""
        with self._lock:
            if not isinstance(row_ids, np.ndarray):
                row_ids = np.asarray(list(row_ids), dtype=np.uint64)
            row_ids = row_ids.astype(np.uint64, copy=False)
            if not isinstance(column_ids, np.ndarray):
                column_ids = np.asarray(list(column_ids), dtype=np.uint64)
            column_ids = column_ids.astype(np.uint64, copy=False)
            if len(row_ids) != len(column_ids):
                raise ValueError("row/column length mismatch")
            if len(row_ids) == 0:
                return 0
            local = column_ids - np.uint64(self.shard * SHARD_WIDTH)
            if (local >= SHARD_WIDTH).any():
                raise ValueError("column out of shard bounds")
            changed = 0
            # Vectorized by-row split: one stable sort + boundary scan
            # (a per-row boolean mask would be O(rows * n)).
            order = np.argsort(row_ids, kind="stable")
            sorted_rows = row_ids[order]
            sorted_local = local[order]
            uniq, starts = np.unique(sorted_rows, return_index=True)
            bounds = np.append(starts, len(sorted_rows))
            for i, rid in enumerate(uniq.tolist()):
                lo, hi = int(bounds[i]), int(bounds[i + 1])
                hr = self.rows.get(int(rid))
                if hr is None:
                    if clear:
                        continue
                    hr = self.rows[int(rid)] = HostRow()
                if clear:
                    changed += hr.remove_many(sorted_local[lo:hi])
                else:
                    changed += hr.add_many(sorted_local[lo:hi])
            if changed:
                self._col_row = None
                self._invalidate()
                if self.op_writer:
                    self.op_writer("removeBatch" if clear else "addBatch",
                                   row_ids.tolist(), column_ids.tolist())
            return changed

    def bulk_import_sorted_local(self, row_ids: np.ndarray,
                                 local: np.ndarray, clear: bool = False) -> int:
        """Bulk set/clear of shard-relative positions PRE-SORTED by
        (row, pos) — the no-copy core of the import path (reference
        importPositions fragment.go:2053). Boundary-scans row groups,
        dedupes each group's sorted positions with one diff pass, and
        hands them to HostRow without any further sort."""
        with self._lock:
            n = len(row_ids)
            if n == 0:
                return 0
            row_ids = np.asarray(row_ids, dtype=np.int64)
            local = np.asarray(local, dtype=np.uint32)
            cut = np.flatnonzero(row_ids[1:] != row_ids[:-1]) + 1
            bounds = np.concatenate(([0], cut, [n]))
            changed = 0
            for i in range(len(bounds) - 1):
                lo, hi = int(bounds[i]), int(bounds[i + 1])
                rid = int(row_ids[lo])
                seg = local[lo:hi]
                if hi - lo > 1:  # drop duplicate positions (sorted input)
                    keep = np.empty(hi - lo, dtype=bool)
                    keep[0] = True
                    np.not_equal(seg[1:], seg[:-1], out=keep[1:])
                    if not keep.all():
                        seg = seg[keep]
                hr = self.rows.get(rid)
                if hr is None:
                    if clear:
                        continue
                    hr = self.rows[rid] = HostRow()
                if clear:
                    changed += hr.remove_many_sorted_unique(seg)
                else:
                    changed += hr.add_many_sorted_unique(seg)
            if changed:
                self._col_row = None
                self._invalidate()
                if self.op_writer:
                    base = np.uint64(self.shard * SHARD_WIDTH)
                    self.op_writer("removeBatch" if clear else "addBatch",
                                   row_ids.astype(np.uint64),
                                   local.astype(np.uint64) + base)
            return changed

    def merge_row_words(self, row_id: int, words: np.ndarray,
                        bit_count: int | None = None,
                        bump_epoch: bool = True,
                        prefer_dense: bool = False) -> int:
        """Merge a freshly-scattered dense word block into one row — the
        landing half of the native bulk-import scatter (reference
        importRoaringBits' container merge, roaring.go:1511). ``words``
        ownership transfers to the fragment; returns bits added.

        Bulk callers landing MANY rows per batch pass bump_epoch=False
        and bump the shared index epoch ONCE at the end (one cache
        invalidation + dirty broadcast per import, not per plane), and
        prefer_dense=True when ``words`` is a view of a scatter buffer
        whose chunk stays pinned by sibling planes anyway — converting a
        near-empty plane to positions there costs a scan and saves no
        memory."""
        from pilosa_tpu import native
        with self._lock:
            if bit_count is None:
                bit_count = native.popcount_words(words)
            if bit_count == 0:
                return 0
            hr = self.rows.get(row_id)
            if hr is None or hr.n == 0:
                self.rows[row_id] = HostRow.adopt_words(
                    words, bit_count, prefer_dense=prefer_dense)
                changed = bit_count
            else:
                changed = hr.merge_words(words)
            if changed:
                self._col_row = None
                self._invalidate(bump_epoch=bump_epoch)
                if self.op_writer:
                    pos = native.words_to_positions(words)
                    base = np.uint64(self.shard * SHARD_WIDTH)
                    self.op_writer("addBatch",
                                   np.full(len(pos), row_id, dtype=np.uint64),
                                   pos + base)
            return changed

    def bulk_import_mutex(self, row_ids, column_ids) -> int:
        """Mutex-field import: setting (row, col) clears any other row's bit
        in that column; last write per column wins (reference
        bulkImportMutex fragment.go:2108). Steals are found through the
        column->row mutex vector (O(1) per column, fragment.go:3094), not
        by scanning every row."""
        with self._lock:
            if len(row_ids) != len(column_ids):
                raise ValueError("row/column length mismatch")
            base = np.uint64(self.shard * SHARD_WIDTH)
            desired: dict[int, int] = {}  # local pos -> row id
            for rid, cid in zip(row_ids, column_ids):
                desired[self._local(int(cid))] = int(rid)
            vec = self._mutex_map()
            changed = 0
            # Clear any column whose bit currently lives in a different row.
            steals: dict[int, list[int]] = {}
            for pos, rid in desired.items():
                cur = vec.get(pos)
                if cur is not None and cur != rid:
                    steals.setdefault(cur, []).append(pos)
            for rid, lpos in steals.items():
                stolen = np.asarray(lpos, dtype=np.uint64)
                changed += self.rows[rid].remove_many(stolen)
                for p in lpos:
                    vec.pop(p, None)
                if self.op_writer:
                    self.op_writer("removeBatch", [rid] * len(lpos),
                                   (stolen + base).tolist())
            # Set the desired bits, grouped by row.
            by_row: dict[int, list[int]] = {}
            for pos, rid in desired.items():
                by_row.setdefault(rid, []).append(pos)
            for rid, lpos in by_row.items():
                hr = self.rows.get(rid)
                if hr is None:
                    hr = self.rows[rid] = HostRow()
                added = hr.add_many(np.asarray(lpos, dtype=np.uint64))
                changed += added
                for p in lpos:
                    vec[p] = rid
                if added and self.op_writer:
                    self.op_writer("addBatch", [rid] * len(lpos),
                                   [p + int(base) for p in lpos])
            if changed:
                self._invalidate()
            return changed

    def import_roaring(self, data: bytes, clear: bool = False) -> int:
        """Merge a serialized roaring bitmap of pos-encoded bits
        (pos = row*ShardWidth + col_local, fragment.go:3090) into this
        fragment (reference importRoaring fragment.go:2255 →
        ImportRoaringBits roaring.go:1511). Returns changed-bit count."""
        from pilosa_tpu import native
        positions = native.decode_roaring(data)
        if len(positions) == 0:
            return 0
        rows = (positions // np.uint64(SHARD_WIDTH)).astype(np.uint64)
        cols = (positions % np.uint64(SHARD_WIDTH)).astype(np.uint64)
        abs_cols = cols + np.uint64(self.shard * SHARD_WIDTH)
        return self.bulk_import(rows.tolist(), abs_cols.tolist(), clear=clear)

    #: bit budget per streamed transfer chunk (~8 MB of positions):
    #: the resize migration streamer slices rows_snapshot into PTS1
    #: import requests of at most this many (row, col) pairs.
    TRANSFER_CHUNK_BITS = 1 << 20

    def to_roaring(self) -> bytes:
        """Serialize all bits in the reference's pos-encoded roaring
        format (the fragment-data transfer format, fragment.go:2436).
        This materializes the WHOLE fragment — transfer paths (resize,
        sync) instead chunk rows_snapshot through the PTS1 import
        stream in TRANSFER_CHUNK_BITS batches."""
        from pilosa_tpu import native
        parts = [pos + np.uint64(rid * SHARD_WIDTH)
                 for rid, pos in self.rows_snapshot()]
        positions = (np.concatenate(parts) if parts
                     else np.empty(0, dtype=np.uint64))
        return native.encode_roaring(positions)

    # -- reads -------------------------------------------------------------

    def row_ids(self) -> list[int]:
        return sorted(self.rows)

    def max_row_id(self) -> int | None:
        return max(self.rows) if self.rows else None

    def min_row_id(self) -> int | None:
        return min(self.rows) if self.rows else None

    def row_words(self, row_id: int) -> np.ndarray:
        """Host dense block for one row (zeros if absent). Locked: the
        materialization may flush pending adds (hostrow._flush)."""
        with self._lock:
            hr = self.rows.get(row_id)
            if hr is None:
                return bitops.np_zero_row()
            return hr.to_words()

    def row_cardinality(self, row_id: int) -> int:
        """Set-bit count of one row, O(1) (HostRow maintains it
        incrementally); 0 for absent rows. Lockless like `contains`:
        the planner's residency class policy reads this per shard at
        plan time, and an off-by-a-few count under a concurrent write
        only shifts WHICH representation class is chosen, never
        correctness."""
        hr = self.rows.get(row_id)
        return 0 if hr is None else hr.count()

    def row_upload(self, row_id: int):
        """Cheapest faithful host form for a device upload:
        ``("dense", uint32[W])`` or ``("sparse", uint64[positions])``
        (positions sorted, deduped). Sparse rows let the planner ship
        ~8B/set-bit COO triplets instead of the 128 KiB dense block —
        the difference IS the query rate when leaves page over a
        bandwidth-bound link (planner sparse-upload path)."""
        with self._lock:
            hr = self.rows.get(row_id)
            if hr is None:
                return ("sparse", np.empty(0, dtype=np.uint64))
            if hr.is_dense:
                return ("dense", hr.dense.copy())
            hr._flush()
            if hr.dense is not None:  # flush may densify
                return ("dense", hr.dense.copy())
            return ("sparse", hr.positions.copy())

    def rows_snapshot(self) -> list[tuple[int, np.ndarray]]:
        """Atomic [(row_id, positions)] snapshot of every row, sorted by
        id — THE way to read all rows for serialization/checksums (the
        position materialization may flush pending adds, so it must
        happen under the fragment lock)."""
        with self._lock:
            return [(rid, self.rows[rid].to_positions())
                    for rid in sorted(self.rows)]

    def device_row(self, row_id: int) -> jax.Array:
        """Device block for one row, cached until next mutation."""
        with self._lock:
            ent = self._dev_rows.get(row_id)
            if ent is not None and ent[0] == self.generation:
                return ent[1]
            arr = jnp.asarray(self.row_words(row_id))
            self._dev_rows[row_id] = (self.generation, arr)
            return arr

    def device_stack(self, row_ids: tuple[int, ...], key: object = None) -> jax.Array:
        """[len(row_ids), W] device block stack; cached by key until mutation.
        This is the unit the fused planner and BSI ops consume."""
        key = key if key is not None else row_ids
        with self._lock:
            ent = self._dev_stacks.get(key)
            if ent is not None and ent[0] == self.generation and ent[1] == row_ids:
                return ent[2]
            mat = np.stack([self.row_words(r) for r in row_ids]) if row_ids else \
                np.zeros((0, WORDS_PER_SHARD), dtype=np.uint32)
            arr = jnp.asarray(mat)
            self._dev_stacks[key] = (self.generation, row_ids, arr)
            return arr

    def row(self, row_id: int) -> Row:
        """Row result for one bitmap row (reference fragment.row :602)."""
        return Row({self.shard: self.device_row(row_id)})

    def intersection_counts(self, row_ids, seg,
                            reuse: bool = False) -> np.ndarray:
        """popcount(row & seg) for each row id — the exact-count engine
        behind TopN/GroupBy/MinRow/MaxRow.

        Two-tier, matching the storage split: SPARSE rows (position
        arrays) are counted host-side by vectorized membership against
        one host copy of the filter — O(set bits) per row, the analog of
        roaring's array-container intersection (roaring.go:3121) and
        ~1000x less data motion than densifying a 20-bit row to 128 KiB.
        DENSE rows go to the device: small sets ride the cached stack;
        large ones stream fixed [ROW_TILE, W] tiles so device memory is
        O(tile) regardless of field cardinality.

        ``reuse=True`` keeps up to MAX_RESIDENT_TILES streamed tiles
        device-resident (generation-checked) so a caller sweeping the same
        row set against many segments — GroupBy's last level, one sweep
        per group prefix — pays materialization and upload once.

        Deliberate: the lock spans the whole sweep, including device
        dispatches, so the counts vector reflects one atomic fragment
        state — writers stall for the sweep, exactly like the reference's
        fragment.top holding f.mu for its full walk (fragment.go:1570)."""
        out, parts = self.intersection_counts_async(row_ids, seg, reuse)
        for slots, dev in parts:
            out[slots] = np.asarray(dev, dtype=np.int64)[:len(slots)]
        return out

    def intersection_counts_async(self, row_ids, seg, reuse: bool = False,
                                  seg_host: np.ndarray | None = None):
        """Non-blocking intersection_counts: returns (counts, parts)
        where ``counts`` already holds the host-tier (sparse) results and
        ``parts`` is [(slot_indices, device_count_array), ...] — device
        programs DISPATCHED but not synced. Callers sweeping many
        fragments resolve every part in one transfer wave instead of one
        sync per fragment (the r2 filtered-TopN latency). Pass
        ``seg_host`` when the filter already exists host-side so the
        sparse tier never pulls it off the device."""
        ids = [int(r) for r in row_ids]
        if not ids:
            return np.empty(0, dtype=np.int64), []
        seg = seg if isinstance(seg, jax.Array) else jnp.asarray(seg)
        out = np.zeros(len(ids), dtype=np.int64)
        parts: list[tuple[np.ndarray, jax.Array]] = []
        ids_arr = np.asarray(ids, dtype=np.int64)
        with self._lock:
            s_ids, concat, starts, lens = self._sparse_index()
            dense_ids: list[int] = []
            dense_slots: list[int] = []
            if len(s_ids):
                at = np.searchsorted(s_ids, ids_arr)
                at_c = np.minimum(at, len(s_ids) - 1)
                is_sparse = s_ids[at_c] == ids_arr
            else:
                at_c = np.zeros(len(ids_arr), dtype=np.int64)
                is_sparse = np.zeros(len(ids_arr), dtype=bool)
            for i in np.flatnonzero(~is_sparse).tolist():
                hr = self.rows.get(ids[i])
                if hr is not None and hr.is_dense:
                    dense_ids.append(ids[i])
                    dense_slots.append(i)
                # else: absent/empty row, count stays 0

            sparse_slots = np.flatnonzero(is_sparse)
            if len(sparse_slots):
                if seg_host is None:
                    seg_host = np.asarray(seg, dtype=np.uint32)
                sel = at_c[sparse_slots]
                if len(sel) == len(s_ids) and np.array_equal(
                        sel, np.arange(len(s_ids))):
                    pos = concat            # whole-index sweep: no gather
                    offsets = starts
                else:
                    l_sel = lens[sel]
                    s_sel = starts[sel]
                    total = int(l_sel.sum())
                    # Ragged gather without a per-row loop: ones with
                    # jumps at group heads, cumsum = flat indices.
                    step = np.ones(total, dtype=np.int64)
                    head = np.zeros(len(l_sel), dtype=np.int64)
                    np.cumsum(l_sel[:-1], out=head[1:])
                    step[head[0]] = s_sel[0]
                    if len(l_sel) > 1:
                        step[head[1:]] = (s_sel[1:] - s_sel[:-1]
                                          - l_sel[:-1] + 1)
                    pos = concat[np.cumsum(step)]
                    offsets = head
                word = (pos >> np.uint64(5)).astype(np.int64)
                bit = np.left_shift(
                    np.uint32(1), (pos & np.uint64(31)).astype(np.uint32))
                hits = ((seg_host[word] & bit) != 0).astype(np.int64)
                # All lens > 0, so every reduceat offset is < len(hits).
                out[sparse_slots] = np.add.reduceat(hits, offsets)

            if dense_ids:
                if len(dense_ids) <= STACK_CACHE_MAX_ROWS:
                    stack = self.device_stack(tuple(dense_ids))
                    parts.append((np.asarray(dense_slots, dtype=np.int64),
                                  pallas_kernels.pair_count(stack, seg,
                                                            "and")))
                else:
                    n_tiles = (len(dense_ids) + ROW_TILE - 1) // ROW_TILE
                    cache_tiles = reuse and n_tiles <= MAX_RESIDENT_TILES
                    # Fixed tile shape (zero-padded tail) → one compiled
                    # kernel. Tile keys are positional ("ic_tile", lo),
                    # NOT id-set-keyed, so a fragment never pins more
                    # than MAX_RESIDENT_TILES tiles: a different id set
                    # replaces them (device_stack verifies stored ids).
                    dense_slots_a = np.asarray(dense_slots, dtype=np.int64)
                    for lo in range(0, len(dense_ids), ROW_TILE):
                        chunk = dense_ids[lo:lo + ROW_TILE]
                        if cache_tiles:
                            arr = self.device_stack(tuple(chunk),
                                                    key=("ic_tile", lo))
                        else:
                            # Fresh buffer per tile: uploads are async
                            # (and zero-copy on the CPU backend), so a
                            # reused buffer would be overwritten while
                            # the deferred kernel still reads it.
                            mat = np.zeros((ROW_TILE, WORDS_PER_SHARD),
                                           dtype=np.uint32)
                            for i, r in enumerate(chunk):
                                mat[i] = self.row_words(r)
                            arr = jnp.asarray(mat)
                        parts.append(
                            (dense_slots_a[lo:lo + len(chunk)],
                             pallas_kernels.pair_count(arr, seg, "and")))
        return out, parts

    def _sparse_index(self):
        """(row_ids, concat_positions, starts, lens) over every non-empty
        SPARSE row, cached per generation — the batched count paths'
        replacement for per-row position materialization (one build per
        mutation, then every TopN/GroupBy sweep is pure vectorized
        numpy). Caller must hold the fragment lock."""
        if self._sparse_cache is not None and \
                self._sparse_cache[0] == self.generation:
            return self._sparse_cache[1:]
        ids: list[int] = []
        bufs: list[np.ndarray] = []
        for rid in sorted(self.rows):
            hr = self.rows[rid]
            if hr.is_dense or hr.n == 0:
                continue
            hr._flush()
            ids.append(rid)
            bufs.append(hr.positions)  # no copy: generation guards reuse
        ids_a = np.asarray(ids, dtype=np.int64)
        lens = np.fromiter((len(b) for b in bufs), dtype=np.int64,
                           count=len(bufs))
        concat = (np.concatenate(bufs) if bufs
                  else np.empty(0, dtype=np.uint64))
        starts = np.zeros(len(lens), dtype=np.int64)
        if len(lens) > 1:
            np.cumsum(lens[:-1], out=starts[1:])
        self._sparse_cache = (self.generation, ids_a, concat, starts, lens)
        return ids_a, concat, starts, lens

    def row_counts(self) -> tuple[np.ndarray, np.ndarray]:
        """(row_ids, counts), cached per generation — the exact
        replacement for the reference's rankCache (cache.go:136): first
        TopN after a mutation pays one O(rows) sweep, repeats are O(1).
        Unlike the threshold-gated cache there is no staleness."""
        with self._lock:
            if self._count_cache is not None and \
                    self._count_cache[0] == self.generation:
                return self._count_cache[1], self._count_cache[2]
            ids = np.asarray(sorted(self.rows), dtype=np.uint64)
            counts = np.asarray([self.rows[int(i)].count() for i in ids],
                                dtype=np.int64)
            self._count_cache = (self.generation, ids, counts)
            return ids, counts

    def top_counts(self) -> tuple[np.ndarray, np.ndarray]:
        """(ids, counts) sorted by count desc then id asc, cached per
        generation — the sorted order is what made the reference's
        rankCache O(results) per TopN (cache.go:136); here it is exact."""
        with self._lock:
            if self._top_cache is not None and \
                    self._top_cache[0] == self.generation:
                return self._top_cache[1], self._top_cache[2]
            ids, counts = self.row_counts()
            order = np.lexsort((ids, -counts))
            ids, counts = ids[order], counts[order]
            self._top_cache = (self.generation, ids, counts)
            return ids, counts

    def row_for_column(self, column_id: int) -> int | None:
        """Mutex/bool vector Get (fragment.go:3117): which row holds this
        column's bit, if any."""
        pos = self._local(column_id)
        if self.mutex:
            return self._mutex_map().get(pos)
        for rid, hr in self.rows.items():
            if hr.contains(pos):
                return rid
        return None

    def _mutex_map(self) -> dict[int, int]:
        """The column vector, rebuilt from rows when dirty."""
        with self._lock:
            if self._col_row is None:
                m: dict[int, int] = {}
                for rid in sorted(self.rows):
                    for p in self.rows[rid].to_positions().tolist():
                        m[int(p)] = rid
                self._col_row = m
            return self._col_row

    # -- BSI ---------------------------------------------------------------

    def _bsi_stacks(self, bit_depth: int):
        """(exists, sign, bits[depth, W]) device arrays."""
        ids = tuple(range(BSI_OFFSET_BIT, BSI_OFFSET_BIT + bit_depth))
        bits = self.device_stack(ids, key=("bsi", bit_depth))
        return self.device_row(BSI_EXISTS_BIT), self.device_row(BSI_SIGN_BIT), bits

    def set_value(self, column_id: int, bit_depth: int, value: int) -> bool:
        """Sign-magnitude BSI write (reference setValueBase fragment.go:939)."""
        with self._lock:
            gen_before = self.generation
            changed = False
            changed |= self.set_bit(BSI_EXISTS_BIT, column_id)
            if value < 0:
                changed |= self.set_bit(BSI_SIGN_BIT, column_id)
            else:
                changed |= self.clear_bit(BSI_SIGN_BIT, column_id)
            mag = abs(value)
            for i in range(bit_depth):
                if (mag >> i) & 1:
                    changed |= self.set_bit(BSI_OFFSET_BIT + i, column_id)
                else:
                    changed |= self.clear_bit(BSI_OFFSET_BIT + i, column_id)
            if changed and getattr(self, "_hll_planes", None):
                from pilosa_tpu.sketch import store as sketch_store
                sketch_store.observe_values(
                    self, np.asarray([self._local(column_id)], dtype=np.int64),
                    np.asarray([value], dtype=np.int64),
                    gen_before, self.generation)
            return changed

    #: exists-plane cardinality below which value() keeps the per-bit
    #: probe loop: materializing the plane stack costs O(depth * W), a
    #: loss for tiny fragments but amortized across the thousands of
    #: lookups row materialization makes against a big one.
    VALUE_STACK_MIN = 2048

    def value(self, column_id: int, bit_depth: int) -> tuple[int, bool]:
        """(value, exists) — reference fragment.value (fragment.go:897).

        Row materialization calls this per column, so the per-bit
        ``contains`` loop (one dict probe + searchsorted per plane) was
        the hot path. Planes gather instead as ONE fancy-index into a
        generation-stamped ``[depth+1, W]`` host word stack (sign row
        first, then magnitude rows) rebuilt lazily after mutations."""
        if not self.contains(BSI_EXISTS_BIT, column_id):
            return 0, False
        pos = self._local(column_id)
        vs = self._value_stack
        if vs is None or vs[0] != self.generation or vs[1] < bit_depth:
            hr_e = self.rows.get(BSI_EXISTS_BIT)
            if hr_e is None or hr_e.n < self.VALUE_STACK_MIN:
                mag = 0
                for i in range(bit_depth):
                    if self.contains(BSI_OFFSET_BIT + i, column_id):
                        mag |= 1 << i
                if self.contains(BSI_SIGN_BIT, column_id):
                    mag = -mag
                return mag, True
            vs = self._build_value_stack(bit_depth)
        words = vs[2][: bit_depth + 1, pos >> 5]  # one gather across planes
        on = (words >> np.uint32(pos & 31)) & np.uint32(1)
        mag = int(on[1:].astype(np.uint64)
                  @ (np.uint64(1) << np.arange(bit_depth, dtype=np.uint64)))
        return (-mag if int(on[0]) else mag), True

    def _build_value_stack(self, bit_depth: int) -> tuple:
        with self._lock:
            vs = self._value_stack
            if vs is not None and vs[0] == self.generation and vs[1] >= bit_depth:
                return vs
            mat = np.zeros((bit_depth + 1, WORDS_PER_SHARD), dtype=np.uint32)
            ids = [BSI_SIGN_BIT] + list(range(BSI_OFFSET_BIT,
                                              BSI_OFFSET_BIT + bit_depth))
            for i, rid in enumerate(ids):
                hr = self.rows.get(rid)
                if hr is not None and hr.n:
                    mat[i] = hr.to_words()
            vs = self._value_stack = (self.generation, bit_depth, mat)
            return vs

    def import_values(self, column_ids, values, bit_depth: int, clear: bool = False) -> None:
        """Batched BSI write (reference importValue fragment.go:2205),
        vectorized by bit plane: the batch becomes ONE bulk clear + ONE
        bulk set across the exists/sign/magnitude rows instead of
        per-column per-bit writes. Plane batches are assembled as
        (plane-row, local-pos) arrays, lexsorted once, and fed through
        the pre-sorted bulk path. Last write per column wins, like
        sequential writes."""
        cols = np.asarray(column_ids, dtype=np.int64)
        if len(cols) == 0:
            return
        local_all = (cols & (SHARD_WIDTH - 1)).astype(np.uint32)
        if clear:
            o = np.argsort(local_all, kind="stable")
            self.bulk_import_sorted_local(
                np.full(len(cols), BSI_EXISTS_BIT, dtype=np.int64),
                local_all[o], clear=True)
            # A clear un-exists columns — not expressible as a plane
            # point-overwrite, so drop the sketch state wholesale.
            if (getattr(self, "_hll_planes", None)
                    or getattr(self, "_hll_regs", None)):
                from pilosa_tpu.sketch import store as sketch_store
                sketch_store.invalidate(self)
            return
        vals = np.asarray(values, dtype=np.int64)
        # Keep the LAST occurrence of each duplicated column.
        local_u, idx = np.unique(local_all[::-1], return_index=True)
        vals_u = vals[::-1][idx]
        from pilosa_tpu.exec import ingest_transpose
        if ingest_transpose.use_device(len(local_u) * (bit_depth + 2)):
            self._import_values_device(local_u, vals_u, bit_depth)
            return
        neg = vals_u < 0
        mag = np.abs(vals_u).astype(np.uint64)

        set_rows, set_cols = [], []
        clr_rows, clr_cols = [], []

        def _add(bucket_r, bucket_c, row_id, mask):
            n = int(mask.sum())
            if n:
                bucket_r.append(np.full(n, row_id, dtype=np.int64))
                bucket_c.append(local_u[mask])

        all_mask = np.ones(len(local_u), dtype=bool)
        _add(set_rows, set_cols, BSI_EXISTS_BIT, all_mask)
        _add(set_rows, set_cols, BSI_SIGN_BIT, neg)
        _add(clr_rows, clr_cols, BSI_SIGN_BIT, ~neg)
        for i in range(bit_depth):
            on = ((mag >> np.uint64(i)) & np.uint64(1)) == 1
            _add(set_rows, set_cols, BSI_OFFSET_BIT + i, on)
            _add(clr_rows, clr_cols, BSI_OFFSET_BIT + i, ~on)

        def _run(rows_list, cols_list, clear_flag):
            if not rows_list:
                return
            rows = np.concatenate(rows_list)
            local = np.concatenate(cols_list)
            # Plane buckets are emitted row-ascending with sorted
            # positions inside each (local_u is sorted), so the pairs
            # are already (row, pos)-sorted — no lexsort needed.
            self.bulk_import_sorted_local(rows, local, clear=clear_flag)

        with self._lock:  # one atomic overwrite, clears before sets
            gen_before = self.generation
            _run(clr_rows, clr_cols, True)
            _run(set_rows, set_cols, False)
            if (self.generation != gen_before
                    and getattr(self, "_hll_planes", None)):
                from pilosa_tpu.sketch import store as sketch_store
                sketch_store.observe_values(self, local_u.astype(np.int64),
                                            vals_u, gen_before,
                                            self.generation)

    def _import_values_device(self, local_u: np.ndarray, vals_u: np.ndarray,
                              bit_depth: int) -> None:
        """Device half of import_values: one jitted transpose yields the
        full ``[depth+2, W]`` plane image for the deduplicated batch,
        merged here with word ops. Bit-identical to the host plane
        loop: row 0 doubles as the written-column mask, so
        ``(old & ~mask) | new`` is exactly clear-then-set per column
        (exists only ever ORs in — columns are never un-existed)."""
        from pilosa_tpu.exec import ingest_transpose
        planes = ingest_transpose.transpose_planes(local_u, vals_u, bit_depth)
        colmask = planes[0]
        notmask = np.invert(colmask)
        plane_ids = [BSI_EXISTS_BIT, BSI_SIGN_BIT] + list(
            range(BSI_OFFSET_BIT, BSI_OFFSET_BIT + bit_depth))
        with self._lock:
            gen_before = self.generation
            added = removed = 0
            for j, rid in enumerate(plane_ids):
                set_w = planes[j]
                hr = self.rows.get(rid)
                if hr is None or hr.n == 0:
                    a = int(bitops.np_count(set_w))
                    if a == 0:
                        continue
                    # set_w is a view of the shared plane image: siblings
                    # pin the block anyway, so keep it dense in place.
                    self.rows[rid] = HostRow.adopt_words(
                        set_w, a, prefer_dense=True)
                    added += a
                    continue
                old = hr.to_words()
                if rid == BSI_EXISTS_BIT:
                    new = np.bitwise_or(old, set_w)
                else:
                    new = np.bitwise_or(np.bitwise_and(old, notmask), set_w)
                a = int(bitops.np_count(np.bitwise_and(new, np.invert(old))))
                r = int(bitops.np_count(np.bitwise_and(old, np.invert(new))))
                if a == 0 and r == 0:
                    continue
                self.rows[rid] = HostRow.adopt_words(
                    new, hr.n + a - r, prefer_dense=True)
                added += a
                removed += r
            if added or removed:
                self._col_row = None
                self._invalidate()
                if self.op_writer:
                    self._emit_value_wal(local_u, vals_u, bit_depth,
                                         removed, added)
                if getattr(self, "_hll_planes", None):
                    from pilosa_tpu.sketch import store as sketch_store
                    sketch_store.observe_values(self,
                                                local_u.astype(np.int64),
                                                vals_u, gen_before,
                                                self.generation)

    def _emit_value_wal(self, local_u: np.ndarray, vals_u: np.ndarray,
                        bit_depth: int, removed: int, added: int) -> None:
        """Replay the host path's WAL framing for a device-side value
        import: one removeBatch of every (plane, column) whose bit is
        off in the new values, then one addBatch of every on bit — the
        same full request arrays bulk_import_sorted_local logs, gated
        the same way (a record only when its pass changed bits)."""
        neg = vals_u < 0
        mag = np.abs(vals_u).astype(np.uint64)
        set_rows, set_cols = [], []
        clr_rows, clr_cols = [], []

        def _add(bucket_r, bucket_c, row_id, mask):
            n = int(mask.sum())
            if n:
                bucket_r.append(np.full(n, row_id, dtype=np.uint64))
                bucket_c.append(local_u[mask].astype(np.uint64))

        all_mask = np.ones(len(local_u), dtype=bool)
        _add(set_rows, set_cols, BSI_EXISTS_BIT, all_mask)
        _add(set_rows, set_cols, BSI_SIGN_BIT, neg)
        _add(clr_rows, clr_cols, BSI_SIGN_BIT, ~neg)
        for i in range(bit_depth):
            on = ((mag >> np.uint64(i)) & np.uint64(1)) == 1
            _add(set_rows, set_cols, BSI_OFFSET_BIT + i, on)
            _add(clr_rows, clr_cols, BSI_OFFSET_BIT + i, ~on)
        base = np.uint64(self.shard * SHARD_WIDTH)
        if removed and clr_rows:
            self.op_writer("removeBatch", np.concatenate(clr_rows),
                           np.concatenate(clr_cols) + base)
        if added and set_rows:
            self.op_writer("addBatch", np.concatenate(set_rows),
                           np.concatenate(set_cols) + base)

    def _filter_seg(self, filter_row: Row | None) -> jax.Array:
        if filter_row is None:
            return jnp.full((WORDS_PER_SHARD,), jnp.uint32(0xFFFFFFFF))
        seg = filter_row.segment(self.shard)
        if seg is None:
            return jnp.zeros((WORDS_PER_SHARD,), jnp.uint32)
        return seg if isinstance(seg, jax.Array) else jnp.asarray(seg)

    def sum(self, filter_row: Row | None, bit_depth: int) -> tuple[int, int]:
        """(sum, count) — reference fragment.sum (fragment.go:1111)."""
        exists, sign, bits = self._bsi_stacks(bit_depth)
        return bsi_ops.host_sum(exists, sign, bits, self._filter_seg(filter_row), bit_depth)

    def min(self, filter_row: Row | None, bit_depth: int) -> tuple[int, int]:
        exists, sign, bits = self._bsi_stacks(bit_depth)
        return bsi_ops.host_min(exists, sign, bits, self._filter_seg(filter_row), bit_depth)

    def max(self, filter_row: Row | None, bit_depth: int) -> tuple[int, int]:
        exists, sign, bits = self._bsi_stacks(bit_depth)
        return bsi_ops.host_max(exists, sign, bits, self._filter_seg(filter_row), bit_depth)

    def range_op(self, op: str, bit_depth: int, predicate: int) -> Row:
        """op in {eq, neq, lt, lte, gt, gte} (reference rangeOp :1274)."""
        exists, sign, bits = self._bsi_stacks(bit_depth)
        if op == "eq":
            seg = bsi_ops.range_eq(exists, sign, bits, predicate, bit_depth)
        elif op == "neq":
            seg = bsi_ops.range_neq(exists, sign, bits, predicate, bit_depth)
        elif op in ("lt", "lte"):
            seg = bsi_ops.range_lt(exists, sign, bits, predicate, bit_depth, op == "lte")
        elif op in ("gt", "gte"):
            seg = bsi_ops.range_gt(exists, sign, bits, predicate, bit_depth, op == "gte")
        else:
            raise ValueError(f"invalid range op {op!r}")
        return Row({self.shard: seg})

    def range_between(self, bit_depth: int, pmin: int, pmax: int) -> Row:
        exists, sign, bits = self._bsi_stacks(bit_depth)
        seg = bsi_ops.range_between(exists, sign, bits, pmin, pmax, bit_depth)
        return Row({self.shard: seg})

    def not_null(self) -> Row:
        return self.row(BSI_EXISTS_BIT)

    # -- TopN / Rows -------------------------------------------------------

    def top(self, n: int = 0, src: Row | None = None,
            row_ids: Iterable[int] | None = None) -> list[tuple[int, int]]:
        """Top rows by count, optionally filtered to rows intersecting src
        or an explicit row-id set. Exact (device intersection counts), not
        cache-approximate like the reference (fragment.go:1570).
        Returns [(row_id, count)] sorted by count desc, id asc."""
        presorted = False
        if row_ids is not None:
            ids = np.asarray(sorted(set(int(r) for r in row_ids)), dtype=np.uint64)
            if len(ids) == 0:
                return []
            if src is not None:
                counts = self.intersection_counts(ids, self._filter_seg(src))
            else:
                counts = np.asarray(
                    [self.rows[int(i)].count() if int(i) in self.rows else 0
                     for i in ids], dtype=np.int64)
        else:
            if src is not None:
                ids = np.asarray(sorted(self.rows), dtype=np.uint64)
                if len(ids) == 0:
                    return []
                counts = self.intersection_counts(ids, self._filter_seg(src))
            else:
                ids, counts = self.top_counts()  # cached sorted order
                if len(ids) == 0:
                    return []
                presorted = True
        if presorted:
            keep = counts > 0
            ids, counts = ids[keep], counts[keep]
            limit = n if n > 0 else len(ids)
            return [(int(r), int(cnt))
                    for r, cnt in zip(ids[:limit].tolist(),
                                      counts[:limit].tolist())]
        order = np.lexsort((ids, -counts))
        pairs = [(int(ids[i]), int(counts[i])) for i in order if counts[i] > 0]
        if n > 0:
            pairs = pairs[:n]
        return pairs

    def rows_list(self, start_row: int = 0, column: int | None = None,
                  limit: int | None = None,
                  among: Iterable[int] | None = None) -> list[int]:
        """Row IDs present, from start_row, optionally only rows with a bit
        in `column` and/or restricted to the `among` set (reference rows +
        rowFilters fragment.go:2618-2724)."""
        allowed = set(among) if among is not None else None
        out = []
        for r in sorted(self.rows):
            if r < start_row or self.rows[r].n == 0:
                continue
            if allowed is not None and r not in allowed:
                continue
            if column is not None and not self.rows[r].contains(self._local(column)):
                continue
            out.append(r)
            if limit is not None and len(out) >= limit:
                break
        return out

    def _filtered_row_counts(self, filter_row: Row | None) -> tuple[list[int], np.ndarray]:
        """(row_ids, counts[∩ filter]) — one batched device call when a
        filter is present, host counters otherwise."""
        ids = self.rows_list()
        if not ids:
            return ids, np.empty(0, dtype=np.int64)
        if filter_row is None:
            return ids, np.asarray([self.rows[r].count() for r in ids],
                                   dtype=np.int64)
        seg = filter_row.segment(self.shard)
        if seg is None:
            return ids, np.zeros(len(ids), dtype=np.int64)
        return ids, self.intersection_counts(ids, seg)

    def min_row(self, filter_row: Row | None = None) -> tuple[int, int]:
        """(min row id with any bit [∩ filter], its count) or (0, 0)
        (reference minRow fragment.go:1232)."""
        ids, counts = self._filtered_row_counts(filter_row)
        for rid, cnt in zip(ids, counts.tolist()):
            if cnt > 0:
                return rid, int(cnt)
        return 0, 0

    def max_row(self, filter_row: Row | None = None) -> tuple[int, int]:
        """(max row id with any bit [∩ filter], its count) or (0, 0)
        (reference maxRow fragment.go:1253)."""
        ids, counts = self._filtered_row_counts(filter_row)
        for rid, cnt in zip(reversed(ids), reversed(counts.tolist())):
            if cnt > 0:
                return rid, int(cnt)
        return 0, 0

    # -- anti-entropy checksums -------------------------------------------

    def checksum_blocks(self, block_rows: int = HASH_BLOCK_SIZE) -> dict[int, bytes]:
        """Block id -> content hash over 100-row blocks (reference
        Blocks/Checksum fragment.go:1762-1841, xxhash over containers).
        Used by the replica-repair sync protocol. Each row is framed as
        (row id, bit count, positions) so distinct row partitions of the
        same positions can't collide."""
        import hashlib
        blocks: dict[int, "hashlib._Hash"] = {}
        for rid, pos in self.rows_snapshot():
            if len(pos) == 0:
                continue
            b = rid // block_rows
            h = blocks.get(b)
            if h is None:
                h = blocks[b] = hashlib.blake2b(digest_size=16)
            h.update(np.uint64(rid).tobytes())
            h.update(np.uint64(len(pos)).tobytes())
            h.update(pos.tobytes())
        return {b: h.digest() for b, h in blocks.items()}

    def block_data(self, block: int, block_rows: int = HASH_BLOCK_SIZE) -> tuple[np.ndarray, np.ndarray]:
        """(row_ids, column_ids) of all bits in a checksum block."""
        rows_out, cols_out = [], []
        base = np.uint64(self.shard * SHARD_WIDTH)
        for rid, pos in self.rows_snapshot():
            if rid // block_rows != block:
                continue
            rows_out.append(np.full(len(pos), rid, dtype=np.uint64))
            cols_out.append(pos + base)
        if not rows_out:
            return np.empty(0, np.uint64), np.empty(0, np.uint64)
        return np.concatenate(rows_out), np.concatenate(cols_out)

    # -- stats -------------------------------------------------------------

    def bit_count(self) -> int:
        return sum(hr.count() for hr in self.rows.values())

    def __repr__(self):
        return (f"Fragment({self.index}/{self.field}/{self.view}/{self.shard} "
                f"rows={len(self.rows)} bits={self.bit_count()})")
