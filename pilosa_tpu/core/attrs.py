"""Attribute storage: arbitrary k/v metadata on rows and columns.

Reference: attr.go (AttrStore :34, AttrBlocks/Diff :80-110) with the
boltdb implementation (boltdb/attrstore.go). Here: an in-memory dict with
optional JSON-lines persistence (durability handled by the holder's
snapshot cycle), plus the same 100-id checksummed block protocol used by
anti-entropy sync.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any

from pilosa_tpu.obs.logger import StandardLogger
from pilosa_tpu.storage.integrity import (
    LineCorruptError,
    frame_line,
    parse_line,
)

#: ids per checksum block (reference attrBlockSize attr.go:28).
ATTR_BLOCK_SIZE = 100

_logger = StandardLogger()


class AttrStore:
    """id -> {attr: value} with checksummed blocks for replica diffing."""

    def __init__(self, path: str | None = None, epoch=None):
        self.path = path
        #: index mutation epoch (core.index.Epoch): attr writes change
        #: query results (Row attrs, TopN attr filters), so they must
        #: invalidate epoch-stamped result caches too.
        self.epoch = epoch
        self._attrs: dict[int, dict[str, Any]] = {}
        #: integrity counters from the last _load (operator-facing).
        self.corrupt_lines = 0
        self.unverified_lines = 0
        self._lock = threading.RLock()
        if path and os.path.exists(path):
            self._load()

    # -- kv ----------------------------------------------------------------

    def attrs(self, id_: int) -> dict[str, Any]:
        with self._lock:
            return dict(self._attrs.get(id_, {}))

    def set_attrs(self, id_: int, attrs: dict[str, Any]) -> None:
        """Merge semantics: None deletes a key (reference attr.go SetAttrs)."""
        with self._lock:
            cur = self._attrs.setdefault(id_, {})
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            if not cur:
                del self._attrs[id_]
            if self.epoch is not None:
                self.epoch.bump()

    def set_bulk_attrs(self, attrs_by_id: dict[int, dict[str, Any]]) -> None:
        with self._lock:
            for id_, attrs in attrs_by_id.items():
                self.set_attrs(id_, attrs)

    def ids(self) -> list[int]:
        with self._lock:
            return sorted(self._attrs)

    # -- anti-entropy blocks (reference attr.go:80-110) --------------------

    def blocks(self) -> list[tuple[int, bytes]]:
        """[(block_id, checksum)] over ATTR_BLOCK_SIZE-id blocks."""
        with self._lock:
            out: dict[int, hashlib._Hash] = {}
            for id_ in sorted(self._attrs):
                b = id_ // ATTR_BLOCK_SIZE
                h = out.get(b)
                if h is None:
                    h = out[b] = hashlib.blake2b(digest_size=16)
                h.update(json.dumps([id_, self._attrs[id_]], sort_keys=True).encode())
            return [(b, h.digest()) for b, h in sorted(out.items())]

    def block_data(self, block: int) -> dict[int, dict[str, Any]]:
        with self._lock:
            lo, hi = block * ATTR_BLOCK_SIZE, (block + 1) * ATTR_BLOCK_SIZE
            return {i: dict(a) for i, a in self._attrs.items() if lo <= i < hi}

    @staticmethod
    def diff_blocks(mine: list[tuple[int, bytes]],
                    theirs: list[tuple[int, bytes]]) -> list[int]:
        """Block ids present/differing in theirs vs mine (attr.go Diff)."""
        m = dict(mine)
        return sorted(b for b, sum_ in theirs if m.get(b) != sum_)

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        with open(self.path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.rstrip("\n")
                if not line.strip():
                    continue
                try:
                    payload, verified = parse_line(line)
                    id_, attrs = json.loads(payload)
                except (LineCorruptError, ValueError) as e:
                    self.corrupt_lines += 1
                    _logger.printf(
                        "attrs: skipping corrupt line %d in %s: %s",
                        lineno, self.path, e)
                    continue
                if not verified:
                    self.unverified_lines += 1
                self._attrs[int(id_)] = attrs

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            tmp = self.path + ".tmp"
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                for id_ in sorted(self._attrs):
                    f.write(frame_line(json.dumps([id_, self._attrs[id_]]))
                            + "\n")
            os.replace(tmp, self.path)
