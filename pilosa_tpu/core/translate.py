"""Key translation: string key ⇄ uint64 id, per index and per field.

Reference: translate.go (TranslateStore :35, in-memory impl :220) and
boltdb/translate.go:48 (sequence-allocated ids starting at 1, with a
primary/replica streaming protocol handled at the cluster layer).

Concurrency model (the lock-free read path):

The maps ``_fwd``/``_rev`` and the id-ordered entry log ``_log`` are
*published immutable snapshots*: mutators build new containers under
``_lock`` and rebind the attributes; they never mutate a published
container in place. Readers do one attribute load plus a ``dict.get``
— no lock — and see either the old snapshot or the new one, both
internally consistent. Mappings are append-only (an id, once
allocated, never changes meaning on the allocation path), so a stale
snapshot is *correct but incomplete*: a reader can miss a brand-new
key, never see a wrong id.

``version`` counts snapshot publications. Derived read structures
(the device key planes in ``exec/keyplane.py``) record the version
they were built from and rebuild when it moves. Readers that pair a
version with a snapshot must read ``version`` FIRST: racing a publish
then yields an *older* version with possibly newer dicts, which only
causes a redundant rebuild — the reverse order could stamp a stale
snapshot as current.

Batched mutators (``translate_keys``, ``apply_entries``) take the lock
at most once per batch and bump the index epoch at most once per
batch. The per-key epoch storm of the original implementation
invalidated the result cache once per new key on keyed ingest.
"""

from __future__ import annotations

import json
import os
import threading
from bisect import bisect_right
from operator import itemgetter

from pilosa_tpu.errors import TranslateStoreReadOnlyError
from pilosa_tpu.obs.logger import StandardLogger
from pilosa_tpu.storage.integrity import (
    LineCorruptError,
    frame_line,
    parse_line,
)

_logger = StandardLogger()

_entry_id = itemgetter(0)


class TranslateStore:
    """Monotonic id allocator with forward and reverse maps."""

    def __init__(self, path: str | None = None, read_only: bool = False,
                 epoch=None):
        self.path = path
        self.read_only = read_only
        #: index mutation Epoch, bumped whenever a NEW mapping lands.
        #: Cached query results embed translated keys (and the
        #: ``str(id)`` fallback for ids with no mapping yet), so a
        #: mapping arriving after a result was cached must invalidate it
        #: — this was a silent mutating path before the result cache
        #: keyed on it. Index-wide (floor) bump: keys aren't per-shard.
        self.epoch = epoch
        #: snapshot publication counter (see module docstring). Device
        #: key planes compare against this to decide on a rebuild.
        self.version = 0
        self._fwd: dict[str, int] = {}
        self._rev: dict[int, str] = {}
        #: id-ascending ``(id, key)`` entry log, published immutable
        #: alongside the maps. ``entries_since`` bisects it so replica
        #: pulls are O(delta), not O(store).
        self._log: list[tuple[int, str]] = []
        self._next = 1  # ids start at 1 (boltdb/translate.go sequence)
        #: contiguous replication watermark: highest id W such that every
        #: id in [1, W] is present. apply_entries may skip ids allocated
        #: on the coordinator by other writers, so replica pulls resume
        #: from here, not max_id() (which _next races ahead of).
        self._watermark = 0
        #: integrity counters from the last _load (operator-facing).
        self.corrupt_lines = 0
        self.unverified_lines = 0
        self._lock = threading.RLock()
        if path and os.path.exists(path):
            self._load()

    # -- read path (lock-free snapshot loads) ------------------------------

    def translate_key(self, key: str, create: bool = True) -> int | None:
        return self.translate_keys((key,), create)[0]

    def translate_id(self, id_: int) -> str | None:
        return self._rev.get(id_)

    def translate_ids(self, ids) -> list[str | None]:
        rev = self._rev  # one snapshot for the whole batch
        return [rev.get(i) for i in ids]

    def snapshot(self) -> tuple[int, dict[str, int], dict[int, str]]:
        """``(version, fwd, rev)`` for derived read structures.

        The dicts are published snapshots — treat them as immutable.
        ``version`` is read first so a racing publish can only make the
        triple conservative (older version, possibly newer dicts).
        """
        v = self.version
        return v, self._fwd, self._rev

    def max_id(self) -> int:
        with self._lock:
            return self._next - 1

    def replication_watermark(self) -> int:
        """Highest id up to which the store is gap-free — the safe
        ``entries_since`` cursor for replica pulls."""
        with self._lock:
            w = self._watermark
            rev = self._rev
            while (w + 1) in rev:
                w += 1
            self._watermark = w
            return w

    # -- write path (one lock acquisition, one epoch bump per batch) -------

    def translate_keys(self, keys, create: bool = True) -> list[int | None]:
        keys = list(keys)
        fwd = self._fwd  # lock-free fast path over one snapshot
        ids = [fwd.get(k) for k in keys]
        if not create or None not in ids:
            return ids
        if self.read_only:
            raise TranslateStoreReadOnlyError()
        allocated = False
        with self._lock:
            fwd = dict(self._fwd)
            rev = dict(self._rev)
            log = self._log
            appended: list[tuple[int, str]] = []
            for pos, key in enumerate(keys):
                if ids[pos] is not None:
                    continue
                id_ = fwd.get(key)  # re-check: may have landed since
                if id_ is None:
                    id_ = self._next
                    self._next += 1
                    fwd[key] = id_
                    rev[id_] = key
                    appended.append((id_, key))
                    allocated = True
                ids[pos] = id_
            if allocated:
                self.version += 1
                self._fwd = fwd
                self._rev = rev
                self._log = log + appended  # local ids are ascending
        if allocated and self.epoch is not None:
            self.epoch.bump()  # local allocation: notify (dirty broadcast)
        return ids

    # -- replication feed (cluster layer streams entries id-ascending) -----

    def entries_since(self, after_id: int) -> list[tuple[int, str]]:
        log = self._log  # published snapshot, lock-free
        return log[bisect_right(log, after_id, key=_entry_id):]

    def apply_entries(self, entries) -> None:
        entries = list(entries)
        if not entries:
            return
        applied = False
        with self._lock:
            fwd = dict(self._fwd)
            rev = dict(self._rev)
            log = list(self._log)
            needs_sort = False
            rebuild_log = False
            for id_, key in entries:
                id_ = int(id_)
                cur = rev.get(id_)
                if cur != key:
                    applied = True
                    if cur is None:
                        # Remote ids may interleave with local ones, so
                        # appends can land out of order — note it and
                        # restore id order once, after the loop.
                        if log and id_ <= log[-1][0]:
                            needs_sort = True
                        log.append((id_, key))
                    else:
                        rebuild_log = True  # id re-keyed: entry replaced
                fwd[key] = id_
                rev[id_] = key
                self._next = max(self._next, id_ + 1)
            if applied:
                if rebuild_log:
                    log = sorted(rev.items())
                elif needs_sort:
                    log.sort(key=_entry_id)
                self.version += 1
                self._fwd = fwd
                self._rev = rev
                self._log = log
        if applied and self.epoch is not None:
            # Remote-origin sync: invalidate local caches, no re-broadcast.
            self.epoch.bump(notify=False)

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        with open(self.path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.rstrip("\n")
                if not line.strip():
                    continue
                try:
                    payload, verified = parse_line(line)
                    id_, key = json.loads(payload)
                except (LineCorruptError, ValueError) as e:
                    # Skip the damaged line, keep the rest of the store:
                    # one flipped bit must not take the whole index's
                    # key translation down.
                    self.corrupt_lines += 1
                    _logger.printf(
                        "translate: skipping corrupt line %d in %s: %s",
                        lineno, self.path, e)
                    continue
                if not verified:
                    self.unverified_lines += 1
                self._fwd[key] = int(id_)
                self._rev[int(id_)] = key
        if self._rev:
            self._next = max(self._rev) + 1
            self._log = sorted(self._rev.items())

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            log = self._log
            tmp = self.path + ".tmp"
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                for id_, key in log:
                    f.write(frame_line(json.dumps([id_, key])) + "\n")
            os.replace(tmp, self.path)
