"""Key translation: string key ⇄ uint64 id, per index and per field.

Reference: translate.go (TranslateStore :35, in-memory impl :220) and
boltdb/translate.go:48 (sequence-allocated ids starting at 1, with a
primary/replica streaming protocol handled at the cluster layer).
"""

from __future__ import annotations

import json
import os
import threading

from pilosa_tpu.errors import TranslateStoreReadOnlyError
from pilosa_tpu.obs.logger import StandardLogger
from pilosa_tpu.storage.integrity import (
    LineCorruptError,
    frame_line,
    parse_line,
)

_logger = StandardLogger()


class TranslateStore:
    """Monotonic id allocator with forward and reverse maps."""

    def __init__(self, path: str | None = None, read_only: bool = False,
                 epoch=None):
        self.path = path
        self.read_only = read_only
        #: index mutation Epoch, bumped whenever a NEW mapping lands.
        #: Cached query results embed translated keys (and the
        #: ``str(id)`` fallback for ids with no mapping yet), so a
        #: mapping arriving after a result was cached must invalidate it
        #: — this was a silent mutating path before the result cache
        #: keyed on it. Index-wide (floor) bump: keys aren't per-shard.
        self.epoch = epoch
        self._fwd: dict[str, int] = {}
        self._rev: dict[int, str] = {}
        self._next = 1  # ids start at 1 (boltdb/translate.go sequence)
        #: contiguous replication watermark: highest id W such that every
        #: id in [1, W] is present. apply_entries may skip ids allocated
        #: on the coordinator by other writers, so replica pulls resume
        #: from here, not max_id() (which _next races ahead of).
        self._watermark = 0
        #: integrity counters from the last _load (operator-facing).
        self.corrupt_lines = 0
        self.unverified_lines = 0
        self._lock = threading.RLock()
        if path and os.path.exists(path):
            self._load()

    def translate_key(self, key: str, create: bool = True) -> int | None:
        with self._lock:
            id_ = self._fwd.get(key)
            if id_ is not None:
                return id_
            if not create:
                return None
            if self.read_only:
                raise TranslateStoreReadOnlyError()
            id_ = self._next
            self._next += 1
            self._fwd[key] = id_
            self._rev[id_] = key
        if self.epoch is not None:
            self.epoch.bump()  # local allocation: notify (dirty broadcast)
        return id_

    def translate_keys(self, keys, create: bool = True) -> list[int | None]:
        return [self.translate_key(k, create) for k in keys]

    def translate_id(self, id_: int) -> str | None:
        with self._lock:
            return self._rev.get(id_)

    def translate_ids(self, ids) -> list[str | None]:
        return [self.translate_id(i) for i in ids]

    def max_id(self) -> int:
        with self._lock:
            return self._next - 1

    def replication_watermark(self) -> int:
        """Highest id up to which the store is gap-free — the safe
        ``entries_since`` cursor for replica pulls."""
        with self._lock:
            w = self._watermark
            while (w + 1) in self._rev:
                w += 1
            self._watermark = w
            return w

    # -- replication feed (cluster layer streams entries id-ascending) -----

    def entries_since(self, after_id: int) -> list[tuple[int, str]]:
        with self._lock:
            return sorted((i, k) for i, k in self._rev.items() if i > after_id)

    def apply_entries(self, entries) -> None:
        applied = False
        with self._lock:
            for id_, key in entries:
                if self._rev.get(id_) != key:
                    applied = True
                self._fwd[key] = id_
                self._rev[id_] = key
                self._next = max(self._next, id_ + 1)
        if applied and self.epoch is not None:
            # Remote-origin sync: invalidate local caches, no re-broadcast.
            self.epoch.bump(notify=False)

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        with open(self.path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.rstrip("\n")
                if not line.strip():
                    continue
                try:
                    payload, verified = parse_line(line)
                    id_, key = json.loads(payload)
                except (LineCorruptError, ValueError) as e:
                    # Skip the damaged line, keep the rest of the store:
                    # one flipped bit must not take the whole index's
                    # key translation down.
                    self.corrupt_lines += 1
                    _logger.printf(
                        "translate: skipping corrupt line %d in %s: %s",
                        lineno, self.path, e)
                    continue
                if not verified:
                    self.unverified_lines += 1
                self._fwd[key] = int(id_)
                self._rev[int(id_)] = key
        if self._rev:
            self._next = max(self._rev) + 1

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            tmp = self.path + ".tmp"
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                for id_ in sorted(self._rev):
                    f.write(frame_line(json.dumps([id_, self._rev[id_]]))
                            + "\n")
            os.replace(tmp, self.path)
