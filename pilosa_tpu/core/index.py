"""Index — a container of fields plus column metadata.

Reference: index.go (struct :37, createField :416, DeleteField :471,
AvailableShards union :292) and holder.go:46 (existence field ``_exists``
backing Not()/existence semantics).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Iterable

from pilosa_tpu.config import EXISTENCE_FIELD_NAME
from pilosa_tpu.core.attrs import AttrStore
from pilosa_tpu.core.field import Field, FieldOptions
from pilosa_tpu.core.row import Row
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.errors import (
    FieldExistsError,
    FieldNotFoundError,
    validate_name,
)


@dataclass
class IndexOptions:
    """Reference IndexOptions (index.go:910)."""

    keys: bool = False
    track_existence: bool = True

    def to_json(self) -> dict:
        return {"keys": self.keys, "trackExistence": self.track_existence}

    @classmethod
    def from_json(cls, d: dict) -> "IndexOptions":
        return cls(keys=d.get("keys", False),
                   track_existence=d.get("trackExistence", True))


class Epoch:
    """Monotonic mutation counter for one index, with per-shard grain.

    Bumped by every fragment/attr mutation anywhere under the index; the
    planner's leaf-stack cache and the executor's result cache validate
    with ONE epoch compare instead of walking per-fragment generations
    (the per-query 954-fragment walk was the r2 flagship bottleneck).

    Shard grain: a bump that knows which shard mutated records that
    shard's position in the global sequence, so a plan touching shards
    S can stamp itself with ``max_shard_epoch(S)`` — writes to shards
    OUTSIDE S advance ``value`` but leave that max unchanged, and the
    plan's cached result survives. A shardless ``bump()`` (schema-ish
    or index-wide mutations: attrs, key translation, field delete,
    remote-origin invalidation without shard detail) raises the floor
    under every shard instead, which also keeps the per-shard dict from
    accumulating state older than the floor.

    Listeners (cluster mode) turn local bumps into index-dirty
    broadcasts so PEER nodes can invalidate their coordinator result
    caches; listeners are called ``fn(shard)`` with the mutated shard or
    ``None`` for index-wide bumps. Remote-triggered bumps pass
    ``notify=False`` to stop the echo from re-broadcasting forever.
    """

    __slots__ = ("_value", "_floor", "_shards", "_lock", "_listeners")

    def __init__(self):
        self._value = 0
        #: every shard's epoch is at least this (index-wide bumps land here).
        self._floor = 0
        #: shard -> sequence position of its last shard-tagged bump.
        self._shards: dict[int, int] = {}
        self._lock = threading.Lock()
        self._listeners: list = []

    def bump(self, notify: bool = True, shard: int | None = None) -> None:
        with self._lock:
            self._value += 1
            if shard is None:
                self._floor = self._value
                self._shards.clear()  # all <= floor now: drop the detail
            else:
                self._shards[shard] = self._value
        if notify:
            for fn in list(self._listeners):
                try:
                    fn(shard)
                except Exception:
                    pass  # observers never break the write path

    def bump_shards(self, shards: Iterable[int], notify: bool = True) -> None:
        """One sequence increment covering a whole shard batch (bulk
        importers: one cache invalidation + one dirty broadcast per
        batch, not one per shard)."""
        shards = [int(s) for s in shards]
        if not shards:
            return
        with self._lock:
            self._value += 1
            v = self._value
            for s in shards:
                self._shards[s] = v
        if notify:
            for fn in list(self._listeners):
                for s in shards:
                    try:
                        fn(s)
                    except Exception:
                        pass

    def subscribe(self, fn) -> None:
        self._listeners.append(fn)

    @property
    def value(self) -> int:
        return self._value

    # -- per-shard reads (result-cache stamps) -----------------------------

    def shard_epoch(self, shard: int) -> int:
        with self._lock:
            return max(self._shards.get(shard, 0), self._floor)

    def max_shard_epoch(self, shards: Iterable[int]) -> int:
        """Stamp for a plan touching ``shards``: strictly increases when
        any of them mutates (its entry moves to the new sequence head),
        holds still when only other shards do."""
        with self._lock:
            m = self._floor
            get = self._shards.get
            for s in shards:
                v = get(s, 0)
                if v > m:
                    m = v
            return m

    def shard_vector(self, shards: Iterable[int]) -> dict[int, int]:
        """Per-shard epochs for the wire (remote legs report theirs so
        the coordinator can stamp cross-node cache entries)."""
        with self._lock:
            floor = self._floor
            get = self._shards.get
            return {int(s): max(get(int(s), 0), floor) for s in shards}


_instance_counter = itertools.count(1)


class Index:
    """Reference Index (index.go:37)."""

    def __init__(self, name: str, options: IndexOptions | None = None,
                 stats=None, fragment_listener=None, op_writer_factory=None):
        validate_name(name)
        self.name = name
        #: process-unique identity: epoch counters restart at 0 when an
        #: index is deleted and recreated under the same name, so caches
        #: keyed (name, epoch) must also key on this nonce or a recreated
        #: index could serve its predecessor's cached results.
        self.instance_id = next(_instance_counter)
        self.options = options or IndexOptions()
        self.stats = stats
        self.fragment_listener = fragment_listener
        self.op_writer_factory = op_writer_factory
        self.epoch = Epoch()
        #: bumped on STRUCTURAL changes (field create/delete, BSI
        #: bit-depth growth) — prepared query plans bake field structure
        #: (e.g. how many bit planes a comparator reads), so they key on
        #: this, separately from the data epoch.
        self.schema_epoch = Epoch()
        #: (epoch stamp, frozenset) memo for available_shards().
        self._avail_shards_cache: tuple | None = None
        self.fields: dict[str, Field] = {}
        self.column_attr_store = AttrStore(epoch=self.epoch)
        self.translate_store = TranslateStore(epoch=self.epoch)
        self._lock = threading.RLock()
        if self.options.track_existence:
            self._create_existence_field()

    # -- fields ------------------------------------------------------------

    def field(self, name: str) -> Field | None:
        return self.fields.get(name)

    def existence_field(self) -> Field | None:
        return self.fields.get(EXISTENCE_FIELD_NAME)

    def public_fields(self) -> list[Field]:
        return [f for n, f in sorted(self.fields.items())
                if n != EXISTENCE_FIELD_NAME]

    def _create_existence_field(self) -> Field:
        f = Field(self.name, EXISTENCE_FIELD_NAME,
                  FieldOptions(cache_type="none", cache_size=0),
                  stats=self.stats, fragment_listener=self.fragment_listener,
                  op_writer_factory=self.op_writer_factory, epoch=self.epoch)
        self.fields[EXISTENCE_FIELD_NAME] = f
        return f

    def create_field(self, name: str, options: FieldOptions | None = None) -> Field:
        with self._lock:
            if name in self.fields:
                raise FieldExistsError()
            f = Field(self.name, name, options, stats=self.stats,
                      fragment_listener=self.fragment_listener,
                      op_writer_factory=self.op_writer_factory,
                      epoch=self.epoch, schema_epoch=self.schema_epoch)
            self.fields[name] = f
            self.schema_epoch.bump()
            return f

    def create_field_if_not_exists(self, name: str,
                                   options: FieldOptions | None = None) -> Field:
        with self._lock:
            return self.fields.get(name) or self.create_field(name, options)

    def delete_field(self, name: str) -> None:
        with self._lock:
            if name not in self.fields:
                raise FieldNotFoundError()
            del self.fields[name]
            self.epoch.bump()
            self.schema_epoch.bump()

    # -- existence ---------------------------------------------------------

    def add_existence(self, column_ids: Iterable[int]) -> None:
        """Mark columns existing (reference executeSet's existence write,
        executor.go:2096)."""
        ef = self.existence_field()
        if ef is None:
            return
        import numpy as np
        cols = np.asarray(column_ids
                          if isinstance(column_ids, np.ndarray)
                          else list(column_ids), dtype=np.uint64)
        ef.import_bits(np.zeros(len(cols), dtype=np.uint64), cols)

    def existence_row(self) -> Row:
        ef = self.existence_field()
        return ef.row(0) if ef is not None else Row()

    # -- shards ------------------------------------------------------------

    def available_shards(self) -> set[int]:
        """Union over fields (reference index.go:292). Memoized on the
        (data, schema) epoch pair: every query start calls this, and for
        a time field the underlying walk visits hundreds of time views —
        ~0.7 ms per call that turned sub-ms cached reads into
        millisecond ones. Any write or schema change invalidates."""
        stamp = (self.epoch.value, self.schema_epoch.value)
        cached = self._avail_shards_cache
        if cached is not None and cached[0] == stamp:
            return set(cached[1])
        out: set[int] = set()
        for f in self.fields.values():
            out |= f.available_shards()
        out = out or {0}
        self._avail_shards_cache = (stamp, frozenset(out))
        return out

    # -- schema ------------------------------------------------------------

    def info(self) -> dict:
        return {
            "name": self.name,
            "options": self.options.to_json(),
            "fields": [f.info() for f in self.public_fields()],
        }

    def __repr__(self):
        return f"Index({self.name} fields={sorted(self.fields)})"
