"""Field — a typed container of views.

Reference: field.go (types :56-62, FieldOptions :1419, SetBit :927,
ClearBit :967, SetValue :1075, Sum/Min/Max/Range :1121-1201, Import :1204,
importValue :1285, bsiGroup :1561-1643, remote AvailableShards :263-358).

Types:
- ``set``   — plain rows, ranked/lru TopN cache options.
- ``int``   — BSI (bit-sliced integers) with [min, max] and an offset base.
- ``time``  — set + time-quantum views for range queries.
- ``mutex`` — set with one-row-per-column invariant.
- ``bool``  — mutex over rows {0:false, 1:true}.
"""

from __future__ import annotations

import datetime as dt
import threading
from dataclasses import dataclass, field as dc_field
from typing import Iterable

import numpy as np

from pilosa_tpu.config import (
    DEFAULT_CACHE_SIZE,
    EXISTENCE_FIELD_NAME,
    SHARD_WIDTH,
    WORDS_PER_SHARD,
)
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.attrs import AttrStore
from pilosa_tpu.core.row import Row
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.core.view import (
    VIEW_STANDARD,
    View,
    is_time_view,
    view_bsi_name,
)
from pilosa_tpu.errors import (
    BSIGroupNotFoundError,
    BSIGroupValueTooHighError,
    BSIGroupValueTooLowError,
    InvalidBSIGroupRangeError,
    InvalidCacheTypeError,
    InvalidFieldTypeError,
    validate_name,
)
from pilosa_tpu.pql import ast as pql_ast

FIELD_TYPE_SET = "set"
FIELD_TYPE_INT = "int"
FIELD_TYPE_TIME = "time"
FIELD_TYPE_MUTEX = "mutex"
FIELD_TYPE_BOOL = "bool"

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"

_VALID_CACHE_TYPES = {CACHE_TYPE_RANKED, CACHE_TYPE_LRU, CACHE_TYPE_NONE}

def bit_depth_uint(v: int) -> int:
    """Bits to store unsigned v (reference bitDepth field.go:1663)."""
    for i in range(63):
        if v < (1 << i):
            return i
    return 63


def bit_depth_int(v: int) -> int:
    return bit_depth_uint(-v if v < 0 else v)


def bsi_base(min_: int, max_: int) -> int:
    """Reference bsiBase (field.go:1551)."""
    if min_ > 0:
        return min_
    if max_ < 0:
        return max_
    return 0


@dataclass
class FieldOptions:
    """Reference FieldOptions (field.go:1419)."""

    type: str = FIELD_TYPE_SET
    cache_type: str = CACHE_TYPE_RANKED
    cache_size: int = DEFAULT_CACHE_SIZE
    min: int = 0
    max: int = 0
    base: int = 0
    bit_depth: int = 0
    time_quantum: str = ""
    keys: bool = False
    no_standard_view: bool = False

    def to_json(self) -> dict:
        """Type-dependent shape (reference FieldOptions.MarshalJSON)."""
        if self.type == FIELD_TYPE_INT:
            return {"type": self.type, "base": self.base,
                    "bitDepth": self.bit_depth, "min": self.min,
                    "max": self.max, "keys": self.keys}
        if self.type == FIELD_TYPE_TIME:
            return {"type": self.type, "timeQuantum": self.time_quantum,
                    "keys": self.keys, "noStandardView": self.no_standard_view}
        if self.type == FIELD_TYPE_BOOL:
            return {"type": self.type}
        return {"type": self.type, "cacheType": self.cache_type,
                "cacheSize": self.cache_size, "keys": self.keys}

    @classmethod
    def from_json(cls, d: dict) -> "FieldOptions":
        return cls(
            type=d.get("type", FIELD_TYPE_SET),
            cache_type=d.get("cacheType", CACHE_TYPE_RANKED),
            cache_size=d.get("cacheSize", DEFAULT_CACHE_SIZE),
            min=d.get("min", 0), max=d.get("max", 0),
            base=d.get("base", 0), bit_depth=d.get("bitDepth", 0),
            time_quantum=d.get("timeQuantum", ""),
            keys=d.get("keys", False),
            no_standard_view=d.get("noStandardView", False),
        )


@dataclass
class BSIGroup:
    """Reference bsiGroup (field.go:1561)."""

    name: str
    min: int = 0
    max: int = 0
    base: int = 0
    bit_depth: int = 0

    def bit_depth_min(self) -> int:
        return self.base - (1 << self.bit_depth) + 1

    def bit_depth_max(self) -> int:
        return self.base + (1 << self.bit_depth) - 1

    def base_value(self, op: str, value: int) -> tuple[int, bool]:
        """(base-relative value, out_of_range) — reference baseValue
        (field.go:1583), including the GT/LT clamp quirks."""
        min_, max_ = self.bit_depth_min(), self.bit_depth_max()
        base_value = 0
        if op in (pql_ast.GT, pql_ast.GTE):
            if value > max_:
                return 0, True
            elif value > min_:
                base_value = value - self.base
        elif op in (pql_ast.LT, pql_ast.LTE):
            if value < min_:
                return 0, True
            elif value > max_:
                base_value = max_ - self.base
            else:
                base_value = value - self.base
        elif op in (pql_ast.EQ, pql_ast.NEQ):
            if value < min_ or value > max_:
                return 0, True
            base_value = value - self.base
        return base_value, False

    def base_value_between(self, lo: int, hi: int) -> tuple[int, int, bool]:
        min_, max_ = self.bit_depth_min(), self.bit_depth_max()
        if hi < min_ or lo > max_:
            return 0, 0, True
        lo = max(lo, min_)
        hi = min(hi, max_)
        return lo - self.base, hi - self.base, False


class Field:
    """Typed view container (reference Field field.go:65)."""

    def __init__(self, index: str, name: str, options: FieldOptions | None = None,
                 stats=None, row_attr_store: AttrStore | None = None,
                 translate_store: TranslateStore | None = None,
                 fragment_listener=None, op_writer_factory=None, epoch=None,
                 schema_epoch=None):
        # The internal existence field is the one reserved name allowed to
        # bypass validation (reference index.go:336 createFieldIfNotExists).
        if name != EXISTENCE_FIELD_NAME:
            validate_name(name)
        self.index = index
        self.name = name
        self.options = options or FieldOptions()
        self._validate_options()
        self.stats = stats
        #: index-level mutation epoch (core.index.Epoch), threaded down to
        #: fragments so any mutation invalidates epoch-stamped caches.
        self.epoch = epoch
        #: index-level STRUCTURE epoch: bumped when baked query-plan
        #: inputs change (here: BSI bit-depth growth).
        self.schema_epoch = schema_epoch
        self.row_attr_store = row_attr_store or AttrStore(epoch=epoch)
        self.translate_store = translate_store or TranslateStore(epoch=epoch)
        self.fragment_listener = fragment_listener
        self.op_writer_factory = op_writer_factory
        self.views: dict[str, View] = {}
        self._lock = threading.RLock()
        #: shards known to hold data anywhere in the cluster
        #: (reference remoteAvailableShards field.go:263).
        self.remote_available_shards: set[int] = set()

        self.bsi_group: BSIGroup | None = None
        if self.options.type == FIELD_TYPE_INT:
            base = self.options.base or bsi_base(self.options.min, self.options.max)
            self.options.base = base
            bd = self.options.bit_depth or max(
                bit_depth_int(self.options.min - base),
                bit_depth_int(self.options.max - base),
            )
            self.options.bit_depth = bd
            self.bsi_group = BSIGroup(name=self.name, min=self.options.min,
                                      max=self.options.max, base=base, bit_depth=bd)

    def _validate_options(self):
        o = self.options
        if o.type not in (FIELD_TYPE_SET, FIELD_TYPE_INT, FIELD_TYPE_TIME,
                          FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL):
            raise InvalidFieldTypeError(f"invalid field type: {o.type!r}")
        if o.cache_type not in _VALID_CACHE_TYPES:
            raise InvalidCacheTypeError(f"invalid cache type: {o.cache_type!r}")
        if o.type == FIELD_TYPE_INT and o.min > o.max:
            raise InvalidBSIGroupRangeError()
        if o.type == FIELD_TYPE_TIME:
            tq.validate_quantum(o.time_quantum)

    # -- type helpers ------------------------------------------------------

    @property
    def field_type(self) -> str:
        return self.options.type

    @property
    def keys(self) -> bool:
        return self.options.keys

    def uses_mutex(self) -> bool:
        return self.options.type in (FIELD_TYPE_MUTEX, FIELD_TYPE_BOOL)

    def time_quantum(self) -> str:
        return self.options.time_quantum

    # -- views -------------------------------------------------------------

    def view(self, name: str) -> View | None:
        return self.views.get(name)

    def view_names(self) -> list[str]:
        return sorted(self.views)

    def delete_view(self, name: str) -> list[int]:
        """Drop one view and its fragments (reference Field.deleteView,
        field.go:889; API.DeleteView api.go:779 — operator cleanup of
        e.g. stale time views). Returns the shards the view held so the
        caller can unlink their on-disk files; missing views are a
        no-op (views don't exist on every node under shard
        distribution, api.go:797)."""
        with self._lock:
            v = self.views.pop(name, None)
            if v is None:
                return []
            shards = sorted(v.fragments)
        if self.epoch is not None:
            self.epoch.bump()
        if self.schema_epoch is not None:
            self.schema_epoch.bump()
        return shards

    def create_view_if_not_exists(self, name: str) -> View:
        with self._lock:
            v = self.views.get(name)
            if v is None:
                v = View(self.index, self.name, name,
                         cache_type=self.options.cache_type,
                         cache_size=self.options.cache_size,
                         mutex=self.uses_mutex(), stats=self.stats,
                         fragment_listener=self.fragment_listener,
                         op_writer_factory=self.op_writer_factory,
                         epoch=self.epoch)
                self.views[name] = v
            return v

    def available_shards(self) -> set[int]:
        """Local fragments plus remote availability (field.go:263-358)."""
        out = set(self.remote_available_shards)
        for v in self.views.values():
            out |= v.available_shards()
        return out

    def add_remote_available_shards(self, shards: Iterable[int]) -> None:
        new = set(shards) - self.remote_available_shards
        if not new:
            return
        self.remote_available_shards |= new
        # The shard set is part of query routing (and memoized on the
        # index epoch): an advertisement must invalidate, or queries
        # keep running against the pre-advert shard list. notify=False:
        # this isn't a local write, so no dirty re-broadcast.
        if self.epoch is not None:
            self.epoch.bump_shards(new, notify=False)

    def remove_remote_available_shard(self, shard: int) -> None:
        """Forget a remotely-advertised shard (reference
        Field.RemoveAvailableShard, field.go:344 — DELETE
        /internal/.../remote-available-shards/{shard}): used when the
        cluster learns a remote shard no longer exists, so queries stop
        fanning out to it."""
        if int(shard) in self.remote_available_shards:
            self.remote_available_shards.discard(int(shard))
            if self.epoch is not None:
                self.epoch.bump(notify=False, shard=int(shard))

    # -- bit ops -----------------------------------------------------------

    def set_bit(self, row_id: int, column_id: int,
                timestamp: dt.datetime | None = None) -> bool:
        """Fan the bit to standard + time views (reference SetBit :927)."""
        changed = False
        if not self.options.no_standard_view:
            changed |= self.create_view_if_not_exists(VIEW_STANDARD).set_bit(
                row_id, column_id)
        if timestamp is not None:
            q = self.time_quantum()
            if not q:
                raise ValueError("timestamp set on field without time quantum")
            for name in tq.views_by_time(VIEW_STANDARD, timestamp, q):
                changed |= self.create_view_if_not_exists(name).set_bit(
                    row_id, column_id)
        return changed

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        """Clear from standard AND all time views (reference ClearBit
        :967-1009 walks every view of the field)."""
        changed = False
        for name, v in list(self.views.items()):
            if name == VIEW_STANDARD or is_time_view(name):
                changed |= v.clear_bit(row_id, column_id)
        return changed

    def row(self, row_id: int) -> Row:
        v = self.view(VIEW_STANDARD)
        return v.row(row_id) if v else Row()

    def row_time(self, row_id: int, t_from: dt.datetime | None,
                 t_to: dt.datetime | None) -> Row:
        """Union of time views covering [from, to) (executor Range/Row with
        from/to, executor.go:1490-1528)."""
        q = self.time_quantum()
        if not q:
            raise ValueError(f"field {self.name} has no time quantum")
        if t_from is None or t_to is None:
            # Open-ended bound: clamp to the data actually present so the
            # view walk stays O(existing views), not O(calendar).
            lo, hi = self._time_view_bounds()
            if lo is None:
                return Row()
            t_from = t_from or lo
            t_to = t_to or hi
        start, end = t_from, t_to
        out = Row()
        for name in tq.views_by_time_range(VIEW_STANDARD, start, end, q):
            v = self.view(name)
            if v is not None:
                out = out.union(v.row(row_id))
        return out

    def _time_view_bounds(self) -> tuple[dt.datetime | None, dt.datetime | None]:
        """(earliest start, latest end) covered by existing time views."""
        spans = []
        for name in self.views:
            if not is_time_view(name):
                continue
            stamp = name[len(VIEW_STANDARD) + 1:]
            fmt, step = {
                4: ("%Y", "y"), 6: ("%Y%m", "m"),
                8: ("%Y%m%d", "d"), 10: ("%Y%m%d%H", "h"),
            }.get(len(stamp), (None, None))
            if fmt is None:
                continue
            try:
                t0 = dt.datetime.strptime(stamp, fmt)
            except ValueError:
                continue
            if step == "y":
                t1 = t0.replace(year=t0.year + 1)
            elif step == "m":
                t1 = tq._add_month_norm(t0)
            elif step == "d":
                t1 = t0 + dt.timedelta(days=1)
            else:
                t1 = t0 + dt.timedelta(hours=1)
            spans.append((t0, t1))
        if not spans:
            return None, None
        return min(s for s, _ in spans), max(e for _, e in spans)

    # -- BSI value ops -----------------------------------------------------

    def _require_bsi(self) -> BSIGroup:
        if self.bsi_group is None:
            raise BSIGroupNotFoundError()
        return self.bsi_group

    def set_value(self, column_id: int, value: int) -> bool:
        """Reference SetValue (field.go:1075): validate range, grow bit
        depth, store base-relative sign-magnitude."""
        bsig = self._require_bsi()
        if value < bsig.min:
            raise BSIGroupValueTooLowError()
        if value > bsig.max:
            raise BSIGroupValueTooHighError()
        base_value = value - bsig.base
        required = bit_depth_int(base_value)
        if required > bsig.bit_depth:
            bsig.bit_depth = required
            self.options.bit_depth = required
            if self.schema_epoch is not None:  # plans bake the depth
                self.schema_epoch.bump()
        v = self.create_view_if_not_exists(view_bsi_name(self.name))
        return v.set_value(column_id, bsig.bit_depth, base_value)

    def value(self, column_id: int) -> tuple[int, bool]:
        bsig = self._require_bsi()
        v = self.view(view_bsi_name(self.name))
        if v is None:
            return 0, False
        val, exists = v.value(column_id, bsig.bit_depth)
        if not exists:
            return 0, False
        return val + bsig.base, True

    def sum(self, filter_row: Row | None = None) -> tuple[int, int]:
        """(sum, count) — base-adjusted (field.go:1121)."""
        bsig = self._require_bsi()
        v = self.view(view_bsi_name(self.name))
        if v is None:
            return 0, 0
        s, c = v.sum(filter_row, bsig.bit_depth)
        return s + c * bsig.base, c

    def min(self, filter_row: Row | None = None) -> tuple[int, int]:
        bsig = self._require_bsi()
        v = self.view(view_bsi_name(self.name))
        if v is None:
            return 0, 0
        m, c = v.min(filter_row, bsig.bit_depth)
        if c == 0:
            return 0, 0
        return m + bsig.base, c

    def max(self, filter_row: Row | None = None) -> tuple[int, int]:
        bsig = self._require_bsi()
        v = self.view(view_bsi_name(self.name))
        if v is None:
            return 0, 0
        m, c = v.max(filter_row, bsig.bit_depth)
        if c == 0:
            return 0, 0
        return m + bsig.base, c

    def range(self, op: str, predicate: int) -> Row:
        """Comparison query over values (reference Field.Range :1178)."""
        bsig = self._require_bsi()
        if predicate < bsig.min or predicate > bsig.max:
            # Out of configured range: reference returns nil row.
            return Row()
        v = self.view(view_bsi_name(self.name))
        if v is None:
            return Row()
        base_value, out_of_range = bsig.base_value(op, predicate)
        if out_of_range:
            return Row()
        return v.range_op(_op_name(op), bsig.bit_depth, base_value)

    def range_between(self, pmin: int, pmax: int) -> Row:
        bsig = self._require_bsi()
        v = self.view(view_bsi_name(self.name))
        if v is None:
            return Row()
        lo, hi, out_of_range = bsig.base_value_between(pmin, pmax)
        if out_of_range:
            return Row()
        return v.range_between(bsig.bit_depth, lo, hi)

    def not_null(self) -> Row:
        """Columns with any value set (reference notNull via rangeOp)."""
        v = self.view(view_bsi_name(self.name))
        if v is None:
            return Row()
        out = Row()
        for frag in v.fragments.values():
            out = out.union(frag.not_null())
        return out

    # -- bulk import -------------------------------------------------------

    def import_bits(self, row_ids, column_ids, timestamps=None,
                    clear: bool = False) -> None:
        """Reference Field.Import (field.go:1204): group bits by view and
        shard, then bulk-import per fragment. The by-shard split is a
        vectorized sort (argsort + boundary search), not a per-bit Python
        loop — 100M-bit imports group in seconds."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if timestamps is None:
            self._import_view_bits(
                VIEW_STANDARD if not self.options.no_standard_view else None,
                row_ids, column_ids, clear)
            return
        data_by_view: dict[str, tuple[list, list]] = {}
        q = self.time_quantum()
        for rid, cid, ts in zip(row_ids.tolist(), column_ids.tolist(), timestamps):
            names = []
            if not self.options.no_standard_view:
                names.append(VIEW_STANDARD)
            if ts is not None:
                if not q:
                    raise ValueError("timestamps require a time quantum")
                names.extend(tq.views_by_time(VIEW_STANDARD, ts, q))
            for name in names:
                rows, cols = data_by_view.setdefault(name, ([], []))
                rows.append(rid)
                cols.append(cid)
        for name, (rows, cols) in data_by_view.items():
            self._import_view_bits(name, np.asarray(rows, dtype=np.uint64),
                                   np.asarray(cols, dtype=np.uint64), clear)

    def _import_view_bits(self, view_name: str | None, row_ids: np.ndarray,
                          column_ids: np.ndarray, clear: bool) -> None:
        """Vectorized by-shard scatter of one view's bit batch.

        Throughput notes (this is the 100M-bit bulk path, reference
        bulkImport fragment.go:1997): all math runs in int64/int32 —
        numpy's uint64 divide/compare are scalar-loop slow — the shard
        split is ONE stable integer argsort (radix for int keys), and
        each shard slice is handed pre-sorted to the fragment so no
        downstream re-sort or re-unique happens."""
        if view_name is None or len(row_ids) == 0:
            return
        view = self.create_view_if_not_exists(view_name)
        if (not clear and not self.uses_mutex() and len(row_ids) >= 65536
                and self._scatter_import(view, row_ids, column_ids)):
            return
        cols = column_ids.astype(np.int64, copy=False)
        rows = row_ids.astype(np.int64, copy=False)
        exp = SHARD_WIDTH.bit_length() - 1
        shards = (cols >> exp).astype(np.int32)
        local = (cols & (SHARD_WIDTH - 1)).astype(np.uint32)
        order = np.argsort(shards, kind="stable")  # radix on int32
        shards = shards[order]
        rows = rows[order]
        local = local[order]
        cut = np.flatnonzero(shards[1:] != shards[:-1]) + 1
        bounds = np.concatenate(([0], cut, [len(shards)]))
        for i in range(len(bounds) - 1):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            frag = view.create_fragment_if_not_exists(int(shards[lo]))
            seg_rows, seg_local = rows[lo:hi], local[lo:hi]
            if self.uses_mutex() and not clear:
                # Mutex semantics are last-write-per-column: keep BATCH
                # order (the stable shard sort preserved it) — sorting
                # here would silently rewrite which row wins.
                base = np.uint64(int(shards[lo]) * SHARD_WIDTH)
                frag.bulk_import_mutex(
                    seg_rows.tolist(),
                    (seg_local.astype(np.uint64) + base).tolist())
            else:
                # (row, pos) sort of the small per-shard slice.
                sub = np.lexsort((seg_local, seg_rows))
                frag.bulk_import_sorted_local(seg_rows[sub], seg_local[sub],
                                              clear=clear)

    #: heavy-row scatter import applies when the batch has at most this
    #: many distinct rows (each row costs one O(n) mask + one scatter).
    _SCATTER_MAX_ROWS = 8
    #: refuse to allocate more than this much dense block buffer per row.
    _SCATTER_MAX_BYTES = 1 << 30

    def _scatter_import(self, view, row_ids: np.ndarray,
                        column_ids: np.ndarray) -> bool:
        """Sort-free bulk import for batches dominated by few rows (the
        realistic bulk-load shape and the reference's import benchmarks):
        one native O(n) pass scatters each row's columns straight into
        dense per-shard blocks, which the fragments adopt or OR in.
        Returns False (untouched state) when the shape doesn't fit —
        many distinct rows, huge shard span, or no native lib."""
        from pilosa_tpu import native
        if not native.available():
            return False
        rows = row_ids
        distinct = np.unique(rows[:4096])
        if len(distinct) > self._SCATTER_MAX_ROWS:
            return False
        if len(distinct) == 1:
            # Single-row batch (the bulk-load common case): a min/max
            # scan proves coverage without materializing a 1-bit-per-
            # element mask array.
            rid = int(distinct[0])
            if int(rows.min()) != rid or int(rows.max()) != rid:
                return False
            masks: list = [None]
        else:
            masks = [rows == rid for rid in distinct.tolist()]
            covered = masks[0].sum()
            for m in masks[1:]:
                covered += m.sum()
            if int(covered) != len(rows):  # sample missed rows: bail
                return False
        exp = SHARD_WIDTH.bit_length() - 1
        n_shards = (int(column_ids.max()) >> exp) + 1
        if n_shards * WORDS_PER_SHARD * 4 > self._SCATTER_MAX_BYTES:
            return False
        merged_any = False
        touched_shards: set[int] = set()
        try:
            for rid, mask in zip(distinct.tolist(), masks):
                out = native.scatter_row_blocks(
                    column_ids[mask] if len(masks) > 1 else column_ids,
                    exp, n_shards, WORDS_PER_SHARD)
                if out is None:
                    return False
                blocks, touched, counts = out
                shards = np.flatnonzero(touched)
                # Dense batches use nearly the whole buffer: hand
                # fragments VIEWS into it (slices are disjoint, so
                # in-place fragment mutation stays correct) — copying
                # would double the memory traffic for no pinning
                # benefit. Sparse batches copy so a few live rows can't
                # pin a huge base array. The test is BYTES USED
                # (adopted rows keep the whole base alive).
                used = len(shards) * WORDS_PER_SHARD * 4
                adopt = used * 2 >= blocks.nbytes
                for shard in shards.tolist():
                    frag = view.create_fragment_if_not_exists(int(shard))
                    row = blocks[shard] if adopt else blocks[shard].copy()
                    frag.merge_row_words(int(rid), row,
                                         bit_count=int(counts[shard]),
                                         bump_epoch=False)
                    merged_any = True
                    touched_shards.add(int(shard))
        finally:
            # ONE shared-epoch bump for the whole batch, not one per
            # shard — including the partial-failure exit (a later row's
            # scatter failing after earlier rows merged), where stale
            # epoch-stamped caches would otherwise serve pre-import
            # counts for the merged rows.
            if merged_any:
                self.index_epoch_bump(touched_shards)
        return True

    def import_values(self, column_ids, values, clear: bool = False) -> None:
        """Reference importValue (field.go:1285): validates range, grows
        bit depth once for the batch."""
        bsig = self._require_bsi()
        values_arr = np.asarray(values, dtype=np.int64)
        if not clear and len(values_arr):
            lo, hi = int(values_arr.min()), int(values_arr.max())
            if lo < bsig.min:
                raise BSIGroupValueTooLowError()
            if hi > bsig.max:
                raise BSIGroupValueTooHighError()
            required = max(bit_depth_int(lo - bsig.base),
                           bit_depth_int(hi - bsig.base))
            if required > bsig.bit_depth:
                bsig.bit_depth = required
                self.options.bit_depth = required
                if self.schema_epoch is not None:
                    self.schema_epoch.bump()
        view = self.create_view_if_not_exists(view_bsi_name(self.name))
        cols = np.asarray(column_ids)
        if len(cols) == 0:
            return
        # base==0 (any range spanning zero) needs no offset: reusing
        # values_arr skips a 8B/value allocation+copy on the hot path.
        vals = values_arr if bsig.base == 0 else values_arr - bsig.base
        if (not clear and len(cols) >= 65536
                and self._scatter_import_values(view, cols, vals, bsig)):
            return
        cols = cols.astype(np.int64, copy=False)
        exp = SHARD_WIDTH.bit_length() - 1
        shards = (cols >> exp).astype(np.int32)
        order = np.argsort(shards, kind="stable")  # radix on int32
        cols, vals, shards = cols[order], vals[order], shards[order]
        cut = np.flatnonzero(shards[1:] != shards[:-1]) + 1
        bounds = np.concatenate(([0], cut, [len(shards)]))
        for i in range(len(bounds) - 1):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            frag = view.create_fragment_if_not_exists(int(shards[lo]))
            frag.import_values(cols[lo:hi], vals[lo:hi], bsig.bit_depth,
                               clear=clear)

    def _scatter_import_values(self, view, cols: np.ndarray,
                               vals: np.ndarray, bsig) -> bool:
        """Sort-free BSI bulk import: one native pass decomposes
        (column, value) pairs into all bit-plane blocks at once. Only
        applies to a FRESH view (no existing values anywhere), where
        last-write-wins needs no plane clears — the bulk-load case. The
        exact overwrite path below handles everything else."""
        from pilosa_tpu import native
        from pilosa_tpu.core.fragment import BSI_OFFSET_BIT, BSI_SIGN_BIT
        if not native.available():
            return False
        if any(frag.rows for frag in view.fragments.values()):
            return False
        exp = SHARD_WIDTH.bit_length() - 1
        n_shards = (int(cols.max()) >> exp) + 1
        depth = bsig.bit_depth
        if n_shards * (depth + 2) * WORDS_PER_SHARD * 4 > (1 << 30):
            return False
        # Last-write-wins for duplicated columns happens inside the
        # native pass (the exists plane is the seen-set on a fresh view).
        out = native.scatter_bsi_blocks(
            np.ascontiguousarray(cols, dtype=np.uint64), vals,
            exp, depth, n_shards, WORDS_PER_SHARD)
        if out is None:
            return False
        blocks, touched, counts = out
        shards = np.flatnonzero(touched)
        # Bytes-used test (see _scatter_import): only NON-EMPTY planes
        # get adopted, so count them — a batch whose values light few
        # planes must copy rather than pin the whole plane buffer.
        used = int(np.count_nonzero(counts)) * WORDS_PER_SHARD * 4
        adopt = used * 2 >= blocks.nbytes
        from pilosa_tpu.config import DENSE_CUTOFF
        # Sparse plane rows skip the positions conversion when ANY row
        # of the batch stays dense: adopted rows are views of ONE shared
        # pool chunk, so as long as one dense view lives, the chunk is
        # pinned regardless and positions would cost a scan and free
        # nothing. Only an ALL-sparse batch converts everything, letting
        # the chunk be garbage-collected.
        pinned = adopt and int(counts.max()) > DENSE_CUTOFF // 2
        merged_any = False
        touched_shards: set[int] = set()
        try:
            for shard in shards.tolist():
                frag = view.create_fragment_if_not_exists(int(shard))
                for r in range(depth + 2):
                    n_bits = int(counts[shard][r])
                    if n_bits == 0:
                        continue  # empty plane: skip the copy + lock trip
                    # Per-shard plane order: exists, sign, magnitude
                    # planes (BSI row ids 0, 1, 2+i — fragment.go:87-93).
                    row_id = r if r < 2 else BSI_OFFSET_BIT + (r - 2)
                    assert BSI_SIGN_BIT == 1
                    row = (blocks[shard][r] if adopt
                           else blocks[shard][r].copy())
                    frag.merge_row_words(row_id, row, bit_count=n_bits,
                                         bump_epoch=False,
                                         prefer_dense=pinned)
                    merged_any = True
                    touched_shards.add(int(shard))
        finally:
            # ONE shared-epoch bump for the whole batch (cache
            # invalidation + dirty broadcast), not one per landed plane
            # row — including the partial-failure exit, where merged
            # rows would otherwise be served stale from epoch-stamped
            # caches.
            if merged_any:
                self.index_epoch_bump(touched_shards)
        return True

    def index_epoch_bump(self, shards: Iterable[int] | None = None) -> None:
        """One batched index-epoch bump (bulk importers defer per-row
        bumps here: one cache invalidation + dirty broadcast per batch).
        ``shards`` tags which shards the batch landed in so plans not
        touching them keep their cached results; None floor-bumps
        everything (caller couldn't track the touched set)."""
        if self.epoch is None:
            return
        if shards:
            self.epoch.bump_shards(shards)
        else:
            self.epoch.bump()

    def import_roaring(self, shard: int, data: bytes, view: str = VIEW_STANDARD,
                       clear: bool = False) -> int:
        """Reference Field.importRoaring (field.go:1374)."""
        v = self.create_view_if_not_exists(view)
        frag = v.create_fragment_if_not_exists(shard)
        return frag.import_roaring(data, clear=clear)

    # -- schema ------------------------------------------------------------

    def info(self) -> dict:
        return {"name": self.name, "options": self.options.to_json()}

    def __repr__(self):
        return f"Field({self.index}/{self.name} type={self.options.type})"


def _op_name(op: str) -> str:
    return {
        pql_ast.EQ: "eq", pql_ast.NEQ: "neq",
        pql_ast.LT: "lt", pql_ast.LTE: "lte",
        pql_ast.GT: "gt", pql_ast.GTE: "gte",
    }[op]
