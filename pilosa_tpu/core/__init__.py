"""Data model: Holder -> Index -> Field -> view -> Fragment, plus the Row
result algebra. Mirrors the reference's root package containment hierarchy
(holder.go:50, index.go:37, field.go:65, view.go:36, fragment.go:99,
row.go:27) rebuilt around sparse-at-rest host storage and dense-on-device
query math."""
