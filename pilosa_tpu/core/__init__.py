"""Data model: Holder -> Index -> Field -> view -> Fragment, plus the Row
result algebra. Mirrors the reference's root package containment hierarchy
(holder.go:50, index.go:37, field.go:65, view.go:36, fragment.go:99,
row.go:27) rebuilt around sparse-at-rest host storage and dense-on-device
query math."""

from pilosa_tpu.core.attrs import AttrStore
from pilosa_tpu.core.field import Field, FieldOptions
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.hostrow import HostRow
from pilosa_tpu.core.index import Index, IndexOptions
from pilosa_tpu.core.row import Row
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.core.view import View

__all__ = [
    "AttrStore", "Field", "FieldOptions", "Fragment", "Holder", "HostRow",
    "Index", "IndexOptions", "Row", "TranslateStore", "View",
]
