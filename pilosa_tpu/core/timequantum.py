"""Time quantum: multi-granularity time views.

Reference: time.go (TimeQuantum :28, viewsByTime :91, viewsByTimeRange
:104, addMonth :178, parseTime :219). A field with quantum "YMDH" writes
each timestamped bit into up to 4 extra views (standard_2017, _201701,
_20170102, _2017010203); a range query greedily covers [start, end) with
the fewest views.
"""

from __future__ import annotations

import datetime as dt

from pilosa_tpu.config import TIME_FORMAT
from pilosa_tpu.errors import InvalidTimeQuantumError

_VALID = {"Y", "YM", "YMD", "YMDH", "M", "MD", "MDH", "D", "DH", "H", ""}

_UNIT_FMT = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}


def validate_quantum(q: str) -> str:
    if q not in _VALID:
        raise InvalidTimeQuantumError(f"invalid time quantum: {q!r}")
    return q


def parse_time(t) -> dt.datetime:
    """str (reference TimeFormat) or unix seconds -> datetime."""
    if isinstance(t, str):
        try:
            return dt.datetime.strptime(t, TIME_FORMAT)
        except ValueError:
            raise ValueError("cannot parse string time") from None
    if isinstance(t, int):
        return dt.datetime.fromtimestamp(t, dt.timezone.utc).replace(tzinfo=None)
    raise ValueError(f"invalid time type {type(t)}")


def view_by_time_unit(name: str, t: dt.datetime, unit: str) -> str:
    fmt = _UNIT_FMT.get(unit)
    return f"{name}_{t.strftime(fmt)}" if fmt else ""


def views_by_time(name: str, t: dt.datetime, quantum: str) -> list[str]:
    """All views a timestamped bit lands in (reference viewsByTime)."""
    return [v for u in quantum if (v := view_by_time_unit(name, t, u))]


def _add_year(t: dt.datetime) -> dt.datetime:
    try:
        return t.replace(year=t.year + 1)
    except ValueError:  # Feb 29
        return t.replace(year=t.year + 1, day=28)


def _add_month_norm(t: dt.datetime) -> dt.datetime:
    """time.AddDate(0,1,0) semantics: overflow normalizes (Jan 31 -> Mar 3)."""
    y, m = divmod(t.month, 12)
    y, m = t.year + y, m + 1
    days_in = (dt.datetime(y + (m == 12), (m % 12) + 1, 1) - dt.datetime(y, m, 1)).days
    overflow = t.day - days_in
    if overflow > 0:
        base = dt.datetime(y, m, days_in, t.hour, t.minute)
        return base + dt.timedelta(days=overflow)
    return t.replace(year=y, month=m)


def _add_month(t: dt.datetime) -> dt.datetime:
    """Reference addMonth (time.go:178): clamp day>28 to the 1st first so
    a YM walk can't skip a month."""
    if t.day > 28:
        t = dt.datetime(t.year, t.month, 1, t.hour, 0)
    return _add_month_norm(t)


def _next_year_gte(t: dt.datetime, end: dt.datetime) -> bool:
    nxt = _add_year(t)
    return nxt.year == end.year or end > nxt


def _next_month_gte(t: dt.datetime, end: dt.datetime) -> bool:
    nxt = _add_month_norm(t)
    return (nxt.year, nxt.month) == (end.year, end.month) or end > nxt


def _next_day_gte(t: dt.datetime, end: dt.datetime) -> bool:
    nxt = t + dt.timedelta(days=1)
    return nxt.date() == end.date() or end > nxt


def views_by_time_range(name: str, start: dt.datetime, end: dt.datetime,
                        quantum: str) -> list[str]:
    """Minimal view cover of [start, end) (reference viewsByTimeRange
    time.go:104): walk up small→large units to a coarse boundary, then back
    down large→small."""
    validate_quantum(quantum)
    has_y, has_m = "Y" in quantum, "M" in quantum
    has_d, has_h = "D" in quantum, "H" in quantum
    t = start
    results: list[str] = []

    # Walk up from smallest units to largest.
    if has_h or has_d or has_m:
        while t < end:
            if has_h:
                if not _next_day_gte(t, end):
                    break
                elif t.hour != 0:
                    results.append(view_by_time_unit(name, t, "H"))
                    t += dt.timedelta(hours=1)
                    continue
            if has_d:
                if not _next_month_gte(t, end):
                    break
                elif t.day != 1:
                    results.append(view_by_time_unit(name, t, "D"))
                    t += dt.timedelta(days=1)
                    continue
            if has_m:
                if not _next_year_gte(t, end):
                    break
                elif t.month != 1:
                    results.append(view_by_time_unit(name, t, "M"))
                    t = _add_month(t)
                    continue
            break

    # Walk back down from largest to smallest.
    while t < end:
        if has_y and _next_year_gte(t, end):
            results.append(view_by_time_unit(name, t, "Y"))
            t = _add_year(t)
        elif has_m and _next_month_gte(t, end):
            results.append(view_by_time_unit(name, t, "M"))
            t = _add_month(t)
        elif has_d and _next_day_gte(t, end):
            results.append(view_by_time_unit(name, t, "D"))
            t += dt.timedelta(days=1)
        elif has_h:
            results.append(view_by_time_unit(name, t, "H"))
            t += dt.timedelta(hours=1)
        else:
            break

    return results
