"""Host-side row storage: sparse positions at rest, dense words when hot.

This replaces roaring's array/bitmap/run container adaptivity
(roaring/container_stash.go:39, conversions roaring.go:2599-2878) with a
two-state scheme chosen for the TPU split-brain design: rows live on the
host as sorted uint64 position arrays (cheap mutation, tiny for sparse
rows) and flip to dense uint32 word blocks past DENSE_CUTOFF — the dense
block being exactly the HBM layout the device kernels consume, so upload
is a straight copy, no re-encode.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.config import DENSE_CUTOFF, SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.ops import bitops


#: Single-bit adds buffer in a Python set and merge into the sorted array
#: in batches, so a tight Set() loop costs O(1) amortized per bit instead
#: of one O(n) np.insert each (the reference bounds its array containers
#: at 4096; ours reach DENSE_CUTOFF, where per-bit memmove would sting).
_PENDING_FLUSH = 256


class HostRow:
    """One bitmap row (2^20 columns) of one fragment, host resident."""

    __slots__ = ("positions", "dense", "n", "_pending")

    def __init__(self):
        self.positions: np.ndarray | None = np.empty(0, dtype=np.uint64)
        self.dense: np.ndarray | None = None
        self.n: int = 0  # set-bit count, maintained incrementally
        self._pending: set[int] = set()  # adds not yet merged into positions

    # -- state ------------------------------------------------------------

    @property
    def is_dense(self) -> bool:
        return self.dense is not None

    def _maybe_densify(self) -> None:
        if self.positions is not None and len(self.positions) > DENSE_CUTOFF:
            self.dense = bitops.positions_to_words(self.positions)
            self.positions = None

    def _flush(self) -> None:
        """Merge buffered single-bit adds into the sorted position array.

        Only called with the owning fragment's lock held (all mutators and
        flushing readers take it). Ordering matters for LOCKLESS readers
        (Fragment.contains / rows_list peek at ``positions``/``_pending``
        without the lock): the merged array is published before the
        pending set is cleared, so a concurrent reader sees every bit in
        at least one of the two."""
        if not self._pending:
            return
        fresh = np.fromiter(self._pending, dtype=np.uint64,
                            count=len(self._pending))
        self.positions = np.sort(np.concatenate((self.positions, fresh)))
        self._pending.clear()
        self._maybe_densify()

    # -- mutation ---------------------------------------------------------

    def add(self, pos: int) -> bool:
        """Set one bit; True if changed. pos is shard-relative."""
        if self.dense is not None:
            if bitops.np_set_bit(self.dense, pos):
                self.n += 1
                return True
            return False
        if pos in self._pending:
            return False
        i = np.searchsorted(self.positions, pos)
        if i < len(self.positions) and self.positions[i] == pos:
            return False
        self._pending.add(int(pos))
        self.n += 1
        if len(self._pending) >= _PENDING_FLUSH or self.n > DENSE_CUTOFF:
            self._flush()
        return True

    def remove(self, pos: int) -> bool:
        if self.dense is not None:
            if bitops.np_clear_bit(self.dense, pos):
                self.n -= 1
                return True
            return False
        if pos in self._pending:
            self._pending.discard(int(pos))
            self.n -= 1
            return True
        i = np.searchsorted(self.positions, pos)
        if i < len(self.positions) and self.positions[i] == pos:
            self.positions = np.delete(self.positions, i)
            self.n -= 1
            return True
        return False

    def add_many(self, positions: np.ndarray) -> int:
        """Bulk-or of sorted-or-not positions; returns number of new bits.
        The reference analog is bulkImport's importPositions
        (fragment.go:2053, roaring AddN)."""
        self._flush()
        positions = np.unique(np.asarray(positions, dtype=np.uint64))
        if len(positions) == 0:
            return 0
        if self.dense is None and len(positions) + len(self.positions) > DENSE_CUTOFF:
            self.dense = bitops.positions_to_words(self.positions)
            self.positions = None
        if self.dense is not None:
            before = self.n
            word_idx = (positions >> np.uint64(5)).astype(np.int64)
            bit = np.left_shift(np.uint32(1), (positions & np.uint64(31)).astype(np.uint32))
            np.bitwise_or.at(self.dense, word_idx, bit)
            self.n = bitops.np_count(self.dense)
            return self.n - before
        merged = np.union1d(self.positions, positions)
        changed = len(merged) - len(self.positions)
        self.positions = merged
        self.n = len(merged)
        self._maybe_densify()
        return changed

    def add_many_sorted_unique(self, positions: np.ndarray) -> int:
        """add_many for input already sorted and deduplicated (the bulk
        import path): skips the O(n log n) re-unique, takes a direct
        assignment when the row is empty, and counts changed bits from
        touched words only instead of re-popcounting the whole block."""
        self._flush()
        n_new = len(positions)
        if n_new == 0:
            return 0
        pos64 = positions.astype(np.uint64)
        if self.dense is None:
            if self.n == 0:
                self.positions = pos64
                self.n = n_new
                self._maybe_densify()
                return n_new
            if n_new + len(self.positions) <= DENSE_CUTOFF:
                merged = np.union1d(self.positions, pos64)
                changed = len(merged) - len(self.positions)
                self.positions = merged
                self.n = len(merged)
                return changed
            self.dense = bitops.positions_to_words(self.positions)
            self.positions = None
        word_idx = (pos64 >> np.uint64(5)).astype(np.int64)
        bit = np.left_shift(np.uint32(1),
                            (pos64 & np.uint64(31)).astype(np.uint32))
        touched = np.unique(word_idx)  # sorted input -> cheap
        before = bitops.np_count(self.dense[touched])
        np.bitwise_or.at(self.dense, word_idx, bit)
        after = bitops.np_count(self.dense[touched])
        self.n += after - before
        return after - before

    def remove_many_sorted_unique(self, positions: np.ndarray) -> int:
        """remove_many for sorted-unique input; same savings as the add
        twin."""
        self._flush()
        if len(positions) == 0:
            return 0
        pos64 = positions.astype(np.uint64)
        if self.dense is not None:
            word_idx = (pos64 >> np.uint64(5)).astype(np.int64)
            bit = np.left_shift(np.uint32(1),
                                (pos64 & np.uint64(31)).astype(np.uint32))
            touched = np.unique(word_idx)
            before = bitops.np_count(self.dense[touched])
            np.bitwise_and.at(self.dense, word_idx, ~bit)
            after = bitops.np_count(self.dense[touched])
            self.n += after - before
            return before - after
        kept = np.setdiff1d(self.positions, pos64, assume_unique=True)
        removed = len(self.positions) - len(kept)
        self.positions = kept
        self.n = len(kept)
        return removed

    def remove_many(self, positions: np.ndarray) -> int:
        self._flush()
        positions = np.unique(np.asarray(positions, dtype=np.uint64))
        if len(positions) == 0:
            return 0
        if self.dense is not None:
            before = self.n
            word_idx = (positions >> np.uint64(5)).astype(np.int64)
            bit = np.left_shift(np.uint32(1), (positions & np.uint64(31)).astype(np.uint32))
            np.bitwise_and.at(self.dense, word_idx, ~bit)
            self.n = bitops.np_count(self.dense)
            return before - self.n
        kept = np.setdiff1d(self.positions, positions, assume_unique=True)
        removed = len(self.positions) - len(kept)
        self.positions = kept
        self.n = len(kept)
        return removed

    # -- reads ------------------------------------------------------------

    def contains(self, pos: int) -> bool:
        if self.dense is not None:
            return bitops.np_get_bit(self.dense, pos)
        if pos in self._pending:
            return True
        i = np.searchsorted(self.positions, pos)
        return i < len(self.positions) and self.positions[i] == pos

    def count(self) -> int:
        return self.n

    def count_range(self, start: int, stop: int) -> int:
        """Set bits in [start, stop) — reference CountRange (roaring.go:438)."""
        if self.dense is not None:
            mask = bitops.np_range_mask(start, stop)
            return bitops.np_count(self.dense & mask)
        self._flush()
        lo = np.searchsorted(self.positions, start)
        hi = np.searchsorted(self.positions, stop)
        return int(hi - lo)

    def to_words(self) -> np.ndarray:
        """Dense uint32[W] block (the device upload format). Copy-safe."""
        if self.dense is not None:
            return self.dense.copy()
        self._flush()
        return bitops.positions_to_words(self.positions)

    def to_positions(self) -> np.ndarray:
        if self.dense is not None:
            return bitops.words_to_positions(self.dense)
        self._flush()
        return self.positions.copy()

    @classmethod
    def from_positions(cls, positions: np.ndarray) -> "HostRow":
        r = cls()
        positions = np.unique(np.asarray(positions, dtype=np.uint64))
        if len(positions) > DENSE_CUTOFF:
            r.dense = bitops.positions_to_words(positions)
            r.positions = None
        else:
            r.positions = positions
        r.n = len(positions)
        return r

    def merge_words(self, words: np.ndarray) -> int:
        """OR a dense word block into this row; returns bits added. The
        scatter-import path's merge step (its blocks arrive unsorted and
        whole, so position-level merging would just re-derive this)."""
        from pilosa_tpu import native
        self._flush()
        base = self.dense if self.dense is not None \
            else bitops.positions_to_words(self.positions)
        merged = np.bitwise_or(base, words)
        n = native.popcount_words(merged)
        # The bulk paths keep moderately-sparse rows dense (half the
        # usual cutoff): below DENSE_CUTOFF the position form saves
        # little memory and the conversion walk dominates import time.
        if n > DENSE_CUTOFF // 2:
            self.dense = merged
            self.positions = None
        else:
            self.positions = native.words_to_positions(merged)
            self.dense = None
        added = n - self.n
        self.n = n
        return added

    @classmethod
    def adopt_words(cls, words: np.ndarray, n: int | None = None,
                    prefer_dense: bool = False) -> "HostRow":
        """Build a row AROUND a freshly-scattered dense block (caller
        relinquishes ownership — no copy for dense rows). prefer_dense
        skips the sparse conversion even for near-empty rows — right
        when ``words`` is a view whose backing chunk stays pinned by
        sibling rows regardless, so positions would cost a scan and
        save nothing."""
        from pilosa_tpu import native
        r = cls()
        if n is None:
            n = native.popcount_words(words)
        if prefer_dense or n > DENSE_CUTOFF // 2:  # see merge_words
            r.dense = words
            r.positions = None
        else:
            r.positions = native.words_to_positions(words)
        r.n = n
        return r

    @classmethod
    def from_words(cls, words: np.ndarray) -> "HostRow":
        r = cls()
        n = bitops.np_count(words)
        if n > DENSE_CUTOFF:
            r.dense = np.array(words, dtype=np.uint32)
            r.positions = None
        else:
            r.positions = bitops.words_to_positions(words)
        r.n = n
        return r
