"""View — one slice of a field, holding fragments keyed by shard.

Reference: view.go (names :36-44, CreateFragmentIfNotExists :263, setBit
:367, setValue/sum/min/max/rangeOp :380-473). View names: ``standard``,
``standard_YYYY[MM[DD[HH]]]`` time views, ``bsig_<field>`` BSI views.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

from pilosa_tpu.config import DEFAULT_CACHE_SIZE, SHARD_WIDTH
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.row import Row

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsig_"


def view_bsi_name(field: str) -> str:
    return VIEW_BSI_PREFIX + field


def is_time_view(name: str) -> bool:
    return name.startswith(VIEW_STANDARD + "_")


class View:
    """Container of per-shard fragments for one layout of one field."""

    def __init__(self, index: str, field: str, name: str,
                 cache_type: str = "ranked", cache_size: int = DEFAULT_CACHE_SIZE,
                 mutex: bool = False, stats=None,
                 fragment_listener: Callable | None = None,
                 op_writer_factory: Callable | None = None, epoch=None):
        self.index = index
        self.field = field
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.mutex = mutex
        self.stats = stats
        self.epoch = epoch
        #: called with (index, field, view, shard) when a fragment appears —
        #: the hook the reference uses to broadcast CreateShardMessage
        #: (view.go:263-304).
        self.fragment_listener = fragment_listener
        #: factory(index, field, view, shard) -> op_writer for WAL wiring.
        self.op_writer_factory = op_writer_factory
        self.fragments: dict[int, Fragment] = {}
        self._lock = threading.RLock()

    # -- fragments ---------------------------------------------------------

    def fragment(self, shard: int) -> Fragment | None:
        return self.fragments.get(shard)

    def create_fragment_if_not_exists(self, shard: int) -> Fragment:
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None:
                op_writer = (self.op_writer_factory(self.index, self.field,
                                                    self.name, shard)
                             if self.op_writer_factory else None)
                frag = Fragment(self.index, self.field, self.name, shard,
                                cache_type=self.cache_type,
                                cache_size=self.cache_size,
                                stats=self.stats, op_writer=op_writer,
                                mutex=self.mutex, epoch=self.epoch)
                self.fragments[shard] = frag
                # Registration changes the shard set even with zero bits
                # (an empty roaring import still creates the fragment):
                # the index-level available_shards() memo keys on the
                # epoch, so it must see this. notify=False — not a data
                # write.
                if self.epoch is not None:
                    self.epoch.bump(notify=False, shard=shard)
                if self.fragment_listener:
                    self.fragment_listener(self.index, self.field, self.name, shard)
            return frag

    def available_shards(self) -> set[int]:
        return set(self.fragments)

    def delete_fragment(self, shard: int) -> bool:
        """Drop a fragment this node no longer owns (holderCleaner,
        holder.go:1126). In-flight queries holding the object finish on
        the orphan; new lookups miss."""
        with self._lock:
            gone = self.fragments.pop(shard, None) is not None
        if gone and self.epoch is not None:
            # shard-set memo must see it
            self.epoch.bump(notify=False, shard=shard)
        return gone

    # -- bit ops -----------------------------------------------------------

    def set_bit(self, row_id: int, column_id: int) -> bool:
        frag = self.create_fragment_if_not_exists(column_id // SHARD_WIDTH)
        return frag.set_bit(row_id, column_id)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        frag = self.fragment(column_id // SHARD_WIDTH)
        return frag.clear_bit(row_id, column_id) if frag else False

    def row(self, row_id: int, shards: Iterable[int] | None = None) -> Row:
        """Cross-shard row for this view (used by the executor per shard
        in the mapReduce path; whole-view reads for tests/tools)."""
        wanted = set(shards) if shards is not None else None
        segs = {}
        for shard, frag in sorted(self.fragments.items()):
            if wanted is not None and shard not in wanted:
                continue
            segs[shard] = frag.device_row(row_id)
        return Row(segs)

    # -- BSI ---------------------------------------------------------------

    def set_value(self, column_id: int, bit_depth: int, value: int) -> bool:
        frag = self.create_fragment_if_not_exists(column_id // SHARD_WIDTH)
        return frag.set_value(column_id, bit_depth, value)

    def value(self, column_id: int, bit_depth: int) -> tuple[int, bool]:
        frag = self.fragment(column_id // SHARD_WIDTH)
        if frag is None:
            return 0, False
        return frag.value(column_id, bit_depth)

    def sum(self, filter_row: Row | None, bit_depth: int) -> tuple[int, int]:
        total = cnt = 0
        for frag in self.fragments.values():
            s, c = frag.sum(filter_row, bit_depth)
            total += s
            cnt += c
        return total, cnt

    def min(self, filter_row: Row | None, bit_depth: int) -> tuple[int, int]:
        best = None
        cnt = 0
        for frag in self.fragments.values():
            v, c = frag.min(filter_row, bit_depth)
            if c == 0:
                continue
            if best is None or v < best:
                best, cnt = v, c
            elif v == best:
                cnt += c
        return (best, cnt) if best is not None else (0, 0)

    def max(self, filter_row: Row | None, bit_depth: int) -> tuple[int, int]:
        best = None
        cnt = 0
        for frag in self.fragments.values():
            v, c = frag.max(filter_row, bit_depth)
            if c == 0:
                continue
            if best is None or v > best:
                best, cnt = v, c
            elif v == best:
                cnt += c
        return (best, cnt) if best is not None else (0, 0)

    def range_op(self, op: str, bit_depth: int, predicate: int) -> Row:
        out = Row()
        for frag in self.fragments.values():
            out = out.union(frag.range_op(op, bit_depth, predicate))
        return out

    def range_between(self, bit_depth: int, pmin: int, pmax: int) -> Row:
        out = Row()
        for frag in self.fragments.values():
            out = out.union(frag.range_between(bit_depth, pmin, pmax))
        return out

    def __repr__(self):
        return f"View({self.index}/{self.field}/{self.name} shards={sorted(self.fragments)})"
