"""Row — the cross-shard query result algebra.

Reference: row.go (Row :27, rowSegment :332, Union k-way merge :153,
Intersect :107, Difference :198, Xor :133, Shift :217). A Row is a sorted
list of per-shard segments; here each segment is one dense uint32[W] block,
typically a device (jax) array so chained set algebra stays on-device and
only Columns()/Count() materialization syncs to host.

Segments are immutable (functional ops return new Rows) — the reference's
copy-on-write ``Freeze``/``ensureWritable`` (row.go:479) machinery
disappears because jax arrays are immutable by construction.
"""

from __future__ import annotations

from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.config import SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.ops import bitops


def _as_device(words) -> jax.Array:
    if isinstance(words, jax.Array):
        return words
    return jnp.asarray(words)


class Row:
    """Set of column IDs spanning shards, plus result attrs/keys."""

    __slots__ = ("segments", "attrs", "keys")

    def __init__(self, segments: dict[int, object] | None = None, attrs=None, keys=None):
        #: shard -> uint32[W] block (jax array or numpy; converted lazily)
        self.segments: dict[int, object] = dict(segments or {})
        self.attrs = attrs or {}
        self.keys = keys or []

    @classmethod
    def from_columns(cls, columns: Iterable[int]) -> "Row":
        cols = np.asarray(sorted(set(int(c) for c in columns)), dtype=np.uint64)
        shards = (cols // SHARD_WIDTH).astype(np.int64)
        segs = {}
        for shard in np.unique(shards):
            local = cols[shards == shard] % SHARD_WIDTH
            segs[int(shard)] = bitops.positions_to_words(local)
        return cls(segs)

    def segment(self, shard: int):
        return self.segments.get(shard)

    def shards(self) -> list[int]:
        return sorted(self.segments)

    # -- algebra ----------------------------------------------------------

    def _binary(self, other: "Row", op: Callable, keep: str) -> "Row":
        """keep: which side's unmatched shards survive ('both'|'left'|'none')."""
        out = {}
        a, b = self.segments, other.segments
        for shard in set(a) | set(b):
            sa, sb = a.get(shard), b.get(shard)
            if sa is not None and sb is not None:
                out[shard] = op(_as_device(sa), _as_device(sb))
            elif sa is not None and keep in ("both", "left"):
                out[shard] = sa
            elif sb is not None and keep == "both":
                out[shard] = sb
        return Row(out)

    def intersect(self, other: "Row") -> "Row":
        out = {}
        for shard in set(self.segments) & set(other.segments):
            out[shard] = bitops.b_and(
                _as_device(self.segments[shard]), _as_device(other.segments[shard])
            )
        return Row(out)

    def union(self, *others: "Row") -> "Row":
        """k-way union (reference row.go:153 merges segment lists by shard)."""
        rows = (self,) + others
        by_shard: dict[int, list] = {}
        for r in rows:
            for shard, seg in r.segments.items():
                by_shard.setdefault(shard, []).append(seg)
        out = {}
        for shard, segs in by_shard.items():
            if len(segs) == 1:
                out[shard] = segs[0]
            else:
                acc = _as_device(segs[0])
                for s in segs[1:]:
                    acc = bitops.b_or(acc, _as_device(s))
                out[shard] = acc
        return Row(out)

    def difference(self, *others: "Row") -> "Row":
        out = dict(self.segments)
        for other in others:
            for shard, seg in other.segments.items():
                if shard in out:
                    out[shard] = bitops.b_andnot(_as_device(out[shard]), _as_device(seg))
        return Row(out)

    def xor(self, other: "Row") -> "Row":
        return self._binary(other, bitops.b_xor, keep="both")

    def shift(self, n: int = 1) -> "Row":
        """Per-shard shift; bits do NOT carry across shard boundaries
        (reference executeShiftShard semantics)."""
        return Row({s: bitops.jit_shift(_as_device(seg), n) for s, seg in self.segments.items()})

    # -- reductions --------------------------------------------------------

    def count(self) -> int:
        total = 0
        for seg in self.segments.values():
            if isinstance(seg, np.ndarray):
                total += bitops.np_count(seg)
            else:
                total += int(bitops.jit_count(seg))
        return total

    def intersection_count(self, other: "Row") -> int:
        total = 0
        for shard in set(self.segments) & set(other.segments):
            total += int(
                bitops.jit_intersection_count(
                    _as_device(self.segments[shard]), _as_device(other.segments[shard])
                )
            )
        return total

    def any(self) -> bool:
        return any(
            (bitops.np_count(seg) if isinstance(seg, np.ndarray) else int(bitops.jit_count(seg))) > 0
            for seg in self.segments.values()
        )

    def columns(self) -> np.ndarray:
        """Materialize sorted absolute column IDs (host sync point)."""
        parts = []
        for shard in self.shards():
            seg = np.asarray(self.segments[shard])
            parts.append(bitops.columns_of(seg, base=shard * SHARD_WIDTH))
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    def is_empty(self) -> bool:
        return not self.any()

    def __eq__(self, other) -> bool:
        if not isinstance(other, Row):
            return NotImplemented
        return np.array_equal(self.columns(), other.columns())

    def __repr__(self) -> str:
        cols = self.columns()
        head = ", ".join(str(c) for c in cols[:8])
        more = "..." if len(cols) > 8 else ""
        return f"Row([{head}{more}] n={len(cols)})"

    def to_json(self) -> dict:
        """Reference Row.MarshalJSON shape (row.go:302): attrs + columns."""
        out = {"attrs": self.attrs, "columns": [int(c) for c in self.columns()]}
        if self.keys:
            out["keys"] = self.keys
        return out
