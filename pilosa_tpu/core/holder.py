"""Holder — the root registry of all indexes on a node.

Reference: holder.go (struct :50, Open :137, Schema/applySchema :284/:327,
fragment accessor :496). Persistence (the data-dir walk, WAL, snapshots)
lives in pilosa_tpu/storage/; the holder exposes hooks for it.
"""

from __future__ import annotations

import threading

from pilosa_tpu.core.field import Field, FieldOptions
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.index import Index, IndexOptions
from pilosa_tpu.errors import IndexExistsError, IndexNotFoundError


class Holder:
    """Reference Holder (holder.go:50)."""

    def __init__(self, stats=None, fragment_listener=None,
                 op_writer_factory=None, index_listener=None):
        self.indexes: dict[str, Index] = {}
        self.stats = stats
        self.fragment_listener = fragment_listener
        #: called with each newly created Index (cluster mode wires the
        #: cross-node dirty broadcaster to its epoch here).
        self.index_listener = index_listener
        self.op_writer_factory = op_writer_factory
        self._lock = threading.RLock()

    # -- indexes -----------------------------------------------------------

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def index_or_raise(self, name: str) -> Index:
        idx = self.indexes.get(name)
        if idx is None:
            raise IndexNotFoundError(f"index not found: {name!r}")
        return idx

    def create_index(self, name: str, options: IndexOptions | None = None) -> Index:
        with self._lock:
            if name in self.indexes:
                raise IndexExistsError()
            idx = Index(name, options, stats=self.stats,
                        fragment_listener=self.fragment_listener,
                        op_writer_factory=self.op_writer_factory)
            self.indexes[name] = idx
            if self.index_listener is not None:
                self.index_listener(idx)
            return idx

    def create_index_if_not_exists(self, name: str,
                                   options: IndexOptions | None = None) -> Index:
        with self._lock:
            return self.indexes.get(name) or self.create_index(name, options)

    def delete_index(self, name: str) -> None:
        with self._lock:
            if name not in self.indexes:
                raise IndexNotFoundError()
            del self.indexes[name]

    # -- accessors (reference holder.go:496 fragment(i,f,v,shard)) ---------

    def field(self, index: str, field: str) -> Field | None:
        idx = self.index(index)
        return idx.field(field) if idx else None

    def fragment(self, index: str, field: str, view: str, shard: int) -> Fragment | None:
        f = self.field(index, field)
        if f is None:
            return None
        v = f.view(view)
        return v.fragment(shard) if v else None

    # -- schema (reference holder.go:284 Schema, :327 applySchema) ---------

    def schema(self) -> list[dict]:
        return [idx.info() for _, idx in sorted(self.indexes.items())]

    def apply_schema(self, schema: list[dict]) -> None:
        """Create any missing indexes/fields described by a schema dump."""
        for idx_info in schema:
            idx = self.create_index_if_not_exists(
                idx_info["name"], IndexOptions.from_json(idx_info.get("options", {})))
            for f_info in idx_info.get("fields", []):
                idx.create_field_if_not_exists(
                    f_info["name"], FieldOptions.from_json(f_info.get("options", {})))

    def index_names(self) -> list[str]:
        return sorted(self.indexes)

    def __repr__(self):
        return f"Holder(indexes={sorted(self.indexes)})"
