"""Open-loop arrival schedule generation.

The whole schedule is drawn up front from a seeded generator, BEFORE
the first request fires. That is what makes the harness open-loop: an
arrival's time depends only on (seed, rate, process), never on how
long earlier requests took, so a saturated server shows up as queue
delay in the latency distribution instead of silently throttling the
offered load the way closed-loop ("fire the next request when the
last one answers") drivers do.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

#: supported inter-arrival processes
PROCESSES = ("poisson", "gamma", "uniform")


@dataclass(frozen=True)
class OpenLoopArrivals:
    """Seeded arrival-schedule generator at a target rate.

    - ``poisson``: exponential inter-arrivals (cv = 1) — memoryless
      open traffic, the M/G/k default.
    - ``gamma``: gamma inter-arrivals with coefficient of variation
      ``cv`` (> 1 burstier than Poisson, < 1 smoother) at the same
      mean rate.
    - ``uniform``: constant spacing — a pure-pacing control leg.
    """

    rate: float              # target arrivals per second
    duration_s: float        # schedule horizon
    process: str = "poisson"
    cv: float = 1.0          # gamma only: std/mean of inter-arrivals
    seed: int = 0

    def __post_init__(self):
        if self.rate <= 0:
            raise ValueError(f"rate must be > 0, got {self.rate}")
        if self.duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {self.duration_s}")
        if self.process not in PROCESSES:
            raise ValueError(f"unknown process {self.process!r} "
                             f"(want one of {PROCESSES})")
        if self.process == "gamma" and self.cv <= 0:
            raise ValueError(f"cv must be > 0, got {self.cv}")

    def schedule(self) -> np.ndarray:
        """Absolute arrival offsets (seconds from run start), sorted,
        all < duration_s. Same seed → bit-identical schedule."""
        rng = np.random.default_rng(self.seed)
        mean = 1.0 / self.rate
        # Draw in chunks until the horizon is covered; the draw count
        # per chunk is deterministic, so the schedule is too.
        n_chunk = max(16, int(self.rate * self.duration_s * 1.2) + 8)
        gaps = []
        total = 0.0
        while total < self.duration_s:
            if self.process == "poisson":
                g = rng.exponential(mean, n_chunk)
            elif self.process == "gamma":
                shape = 1.0 / (self.cv ** 2)
                g = rng.gamma(shape, mean / shape, n_chunk)
            else:  # uniform
                g = np.full(n_chunk, mean)
            gaps.append(g)
            total += float(g.sum())
        offsets = np.cumsum(np.concatenate(gaps))
        return offsets[offsets < self.duration_s]
