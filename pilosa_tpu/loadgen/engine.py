"""Scenario engine: schedule → live HTTP traffic → SLO report.

The run is deterministic up to the wire: the arrival schedule AND the
full op sequence (leg, tenant, query text per arrival) are drawn from
the scenario seed before the first request fires (``build_ops``).
Execution never feeds back into arrivals — a worker-pool submission
happens at the scheduled offset whether or not earlier ops finished,
and latency is measured FROM THE SCHEDULED ARRIVAL, so server queue
buildup and driver lag both land in the tail where an SLO can see
them.

Latencies accumulate in a MemoryStats registry (bounded LogHistograms
with trace-id exemplars), never in private lists; the report reads
them back through ``timing_quantile`` and resolves tail exemplars
into full cost profiles via ``/debug/queries/<trace-id>``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.loadgen.arrival import OpenLoopArrivals
from pilosa_tpu.loadgen.mix import WorkloadMix, ZipfPicker
from pilosa_tpu.loadgen.report import (SCHEMA_VERSION, PromHistogram,
                                       parse_prom_histograms, tail_exemplars,
                                       validate_report)
from pilosa_tpu.loadgen.scenario import Scenario
from pilosa_tpu.loadgen.target import ManagedTarget
from pilosa_tpu.obs import tracing

#: the index every scenario drives (plus INDEX_KEYED for keyed legs)
INDEX = "mix"
INDEX_KEYED = "mixk"

#: service-latency histogram each node exports per QoS class
_SERVER_HIST = "pilosa_qos_service_seconds"


@dataclass(frozen=True)
class Op:
    """One precomputed request: everything but the wire."""

    offset: float      # seconds from run start
    leg: str
    kind: str
    qos_class: str
    tenant: int
    index: str
    pql: str
    no_cache: bool


def _leg_query(leg, rank: int, rng: np.random.Generator,
               sc: Scenario) -> tuple[str, str]:
    """(index, pql) for one sampled op. ``rank`` is the zipf-picked
    member of the leg's query population; extra randomness (the ad-hoc
    second operand) comes from the shared op rng so the sequence stays
    seed-deterministic."""
    n_rows = sc.rows
    if leg.kind == "dashboard":
        return INDEX, f"Count(Row(f={rank % n_rows}))"
    if leg.kind == "adhoc":
        a = rank % n_rows
        b = int(rng.integers(0, n_rows))
        return INDEX, f"Count(Intersect(Row(f={a}), Row(f={b})))"
    if leg.kind == "bsi":
        span = 100_000
        lo = -span + (2 * span * (rank % leg.population)) // leg.population
        return INDEX, f"Sum(Row(v > {lo}), field=v)"
    if leg.kind == "topn":
        if rank % 2:
            return INDEX, f"TopN(f, Row(f={rank % n_rows}), n=10)"
        return INDEX, "TopN(f, n=10)"
    if leg.kind == "distinct":
        if rank % 2:
            return INDEX, (f"Count(Distinct(Row(f={rank % n_rows}), "
                           "field=v))")
        return INDEX, "Count(Distinct(field=v))"
    if leg.kind == "similar":
        return INDEX, f"SimilarTopN(f, Row(f={rank % n_rows}), n=10)"
    # keyed
    return INDEX_KEYED, f'Count(Row(kf="k{rank % leg.population}"))'


def build_ops(sc: Scenario) -> list[Op]:
    """The full deterministic op sequence for a scenario: same
    scenario dict + same seed → identical list, computed without
    touching any target (the open-loop contract, testable offline)."""
    schedule = OpenLoopArrivals(rate=sc.rate, duration_s=sc.duration_s,
                                process=sc.process, cv=sc.cv,
                                seed=sc.seed).schedule()
    mix = WorkloadMix([(leg.name, leg.weight) for leg in sc.legs],
                      n_tenants=sc.tenants, tenant_s=sc.tenant_s)
    pickers = {leg.name: ZipfPicker(leg.population, leg.zipf_s)
               for leg in sc.legs}
    legs = {leg.name: leg for leg in sc.legs}
    rng = np.random.default_rng(sc.seed ^ 0x5EED)
    ops = []
    for off in schedule:
        name, tenant = mix.sample(rng)
        leg = legs[name]
        index, pql = _leg_query(leg, pickers[name].pick(rng), rng, sc)
        ops.append(Op(offset=float(off), leg=name, kind=leg.kind,
                      qos_class=leg.qos_class, tenant=tenant,
                      index=index, pql=pql, no_cache=leg.no_cache))
    return ops


# -- dataset -------------------------------------------------------------


def _bsi_reqs(sc: Scenario, field: str, shards: int, per_shard: int,
              rng: np.random.Generator,
              lo: int = -100_000, hi: int = 100_000) -> list[dict]:
    reqs = []
    for s in range(shards):
        cols = (s * SHARD_WIDTH
                + rng.choice(SHARD_WIDTH, per_shard,
                             replace=False).astype(np.uint64))
        vals = rng.integers(lo, hi, per_shard)
        reqs.append({"kind": "field", "index": INDEX, "field": field,
                     "shard": s, "rowIDs": None, "columnIDs": cols,
                     "values": vals, "clear": False})
    return reqs


def setup_dataset(sc: Scenario, target) -> None:
    """Create schema + seed data. Deterministic from the scenario seed
    (setup rng is independent of the op-sequence rng)."""
    rng = np.random.default_rng(sc.seed ^ 0xDA7A)
    target.create_index(INDEX)
    target.create_field(INDEX, "f")
    target.create_field(INDEX, "v", {"type": "int",
                                     "min": -100_000, "max": 100_000})
    per_shard = max(64, int(sc.density * SHARD_WIDTH))
    for s in range(sc.shards):
        cols = (s * SHARD_WIDTH
                + rng.choice(SHARD_WIDTH, per_shard,
                             replace=False).astype(np.uint64))
        # zipf-ish row popularity so TopN and dashboards see real skew
        rows = (np.abs(rng.standard_cauchy(per_shard)) * 4).astype(
            np.uint64) % sc.rows
        target.import_bits(INDEX, "f", rows, cols)
    target.import_stream(_bsi_reqs(sc, "v", sc.shards,
                                   min(per_shard, 20_000), rng))
    if any(leg.kind == "keyed" for leg in sc.legs):
        target.create_index(INDEX_KEYED, {"keys": True})
        target.create_field(INDEX_KEYED, "kf", {"keys": True})
        pop = max(leg.population for leg in sc.legs if leg.kind == "keyed")
        sets = [f'Set("c{int(rng.integers(0, 512))}", kf="k{k}")'
                for k in range(pop) for _ in range(4)]
        for i in range(0, len(sets), 64):
            target.query(INDEX_KEYED, "".join(sets[i:i + 64]),
                         qos_class="batch")
    if sc.ingest is not None:
        for t in (0, 1):
            target.create_field(INDEX, f"bg{t}",
                                {"type": "int",
                                 "min": sc.ingest.value_min,
                                 "max": sc.ingest.value_max})


# -- background legs -----------------------------------------------------


def _ingest_loop(sc: Scenario, target, stop: threading.Event,
                 totals: dict) -> None:
    """Stream PTS1 batches at the configured duty cycle."""
    leg = sc.ingest
    rng = np.random.default_rng(sc.seed ^ 0x16e5)
    reqs = _bsi_reqs(sc, "bg0", leg.shards, leg.per_shard, rng,
                     leg.value_min, leg.value_max)
    t = 0
    while not stop.is_set():
        batch = [dict(r, field=f"bg{t % 2}") for r in reqs]
        t0 = time.perf_counter()
        try:
            target.import_stream(batch)
        except Exception:
            totals["errors"] += 1
            if stop.wait(0.2):
                break
            continue
        dt = time.perf_counter() - t0
        totals["vals"] += leg.shards * leg.per_shard
        totals["seconds"] += dt
        totals["batches"] += 1
        t += 1
        if leg.duty < 1.0 and dt > 0:
            stop.wait(dt * (1.0 - leg.duty) / leg.duty)


def _chaos_loop(sc: Scenario, target, stop: threading.Event,
                t0: float, applied: list) -> None:
    for act in sorted(sc.chaos, key=lambda a: a.at_s):
        while not stop.is_set():
            delay = act.at_s - (time.perf_counter() - t0)
            if delay <= 0:
                break
            if stop.wait(min(delay, 0.1)):
                return
        if stop.is_set():
            return
        if act.action == "slow_peer":
            ok = target.slow_peer(act.node, act.value)
        elif act.action == "heal_peer":
            ok = target.heal_peer(act.node)
        elif act.action == "add_node":
            ok = target.add_node()
        elif act.action == "dr_backup":
            ok = target.dr_backup()
        elif act.action == "dr_destroy_data":
            ok = target.dr_destroy_data(act.node)
        elif act.action == "partition":
            ok = target.partition(act.group, act.mode, act.value)
        elif act.action == "heal_partition":
            ok = target.heal_partition()
        else:
            ok = target.remove_node(act.node)
        applied.append({"atS": act.at_s, "action": act.action,
                        "node": act.node, "value": act.value,
                        "group": list(act.group), "mode": act.mode,
                        "ok": ok})


# -- DR drill ------------------------------------------------------------


def _dr_setup(sc: Scenario) -> dict:
    """Boot the drill's fault-injected object store and derive the
    node opts that point every node's backup scheduler at it."""
    import tempfile

    from pilosa_tpu.backup.faults import FakeObjectServer
    cfg = dict(sc.dr or {})
    srv = FakeObjectServer(seed=sc.seed)
    srv.fail_rate = float(cfg.get("failRate", 0.15))
    srv.torn_next_put = int(cfg.get("tornUploads", 2))
    url = srv.url(bucket="drill")
    return {
        "srv": srv, "url": url, "cfg": cfg,
        "data_root": tempfile.mkdtemp(prefix="loadgen-dr-"),
        "node_opts": {
            "backup_interval": float(cfg.get("intervalS", 3.0)),
            "archive_url": url,
            "backup_full_every": int(cfg.get("fullEvery", 1)),
            "backup_keep_chains": int(cfg.get("keepChains", 1)),
        },
    }


def _dr_epilogue(sc: Scenario, target, env: dict) -> dict:
    """After the storm: final capture, restore into a fresh recovery
    cluster, prove bit-equivalence, and prove every backup retention
    left listed is still restorable. Returns the report's numeric
    ``dr`` section."""
    import shutil
    import tempfile

    from pilosa_tpu.backup import BackupError, open_archive, preflight_restore
    srv, url = env["srv"], env["url"]
    # One forced cycle captures the post-run state, so the recovery
    # cluster has an exact target to be measured against.
    dr: dict = {"finalBackupOk": 1 if target.dr_backup() else 0}

    names = ("backup.scheduler.runs", "backup.scheduler.failed",
             "backup.scheduler.skipped", "backup.retention.pruned",
             "archive.retries", "archive.bytesOut", "archive.bytesIn")
    sums = dict.fromkeys(names, 0.0)
    for i in range(len(target.base_urls)):
        try:
            dvars = target.debug_vars(i)
        except Exception:
            continue
        for n in names:
            sums[n] += _counter_sum(dvars, n)
    dr["backupRuns"] = int(sums["backup.scheduler.runs"])
    dr["backupFailed"] = int(sums["backup.scheduler.failed"])
    dr["backupSkipped"] = int(sums["backup.scheduler.skipped"])
    dr["retentionPruned"] = int(sums["backup.retention.pruned"])
    dr["archiveRetries"] = int(sums["archive.retries"])
    dr["archiveBytesOut"] = int(sums["archive.bytesOut"])
    dr["archiveBytesIn"] = int(sums["archive.bytesIn"])
    dr["faultsInjected"] = srv.injected
    dr["tornUploads"] = srv.torn

    live = target.fragment_digest()
    rec_root = tempfile.mkdtemp(prefix="loadgen-dr-rec-")
    rec = ManagedTarget(n_nodes=int(env["cfg"].get("recoveryNodes", 2)),
                        replica_n=sc.replica_n,
                        node_opts=dict(sc.node_opts), data_root=rec_root)
    try:
        rec._post(rec.base_urls[0] + "/restore",
                  json.dumps({"archive": url}))
        deadline = time.time() + 120
        st = {}
        while time.time() < deadline:
            try:
                st = json.loads(rec._get(rec.base_urls[0]
                                         + "/restore/status"))
            except Exception:
                st = {}
            if st.get("state") in ("done", "failed"):
                break
            time.sleep(0.2)
        dr["restoreDone"] = 1 if st.get("state") == "done" else 0
        recovered = rec.fragment_digest()
        dr["restoredFragments"] = len(recovered)
        # Bit-equivalence, key by key: every restored replica's digest
        # must be one the live cluster holds for that fragment (the
        # backup captured exactly one healthy replica's bytes), and no
        # fragment may appear on one side only.
        mismatched = 0
        for k in set(live) | set(recovered):
            lv, rv = live.get(k), recovered.get(k)
            if lv is None or rv is None or not rv <= lv:
                mismatched += 1
        dr["mismatchedFragments"] = mismatched
    finally:
        rec.close()
        shutil.rmtree(rec_root, ignore_errors=True)

    # Retention's standing invariant, re-proved from the outside: every
    # backup the archive still lists passes a restore preflight.
    arch = open_archive(url)
    try:
        ids = arch.list_backups()
        unrestorable = 0
        for bid in ids:
            try:
                preflight_restore(arch, arch.read_manifest(bid))
            except BackupError:
                unrestorable += 1
        dr["survivingBackups"] = len(ids)
        dr["unrestorableBackups"] = unrestorable
    finally:
        arch.close()
    return dr


# -- partition drill -----------------------------------------------------


def _partition_epilogue(sc: Scenario, target) -> dict:
    """After a split-brain drill: heal whatever is still cut, drive
    failure-detector sweeps until every node un-fences, force a repair
    pass, and prove the replicas converged bit-identically. Returns
    the report's numeric ``partition`` section."""
    healed = target.heal_partition()
    nodes = getattr(target, "nodes", None)   # managed mode only

    def sweep():
        if nodes is None:
            return
        from pilosa_tpu.cluster.resize import check_nodes
        for n in nodes:
            if n.cluster is None:
                continue
            try:
                check_nodes(n.cluster, n.cluster.client, retries=1,
                            discover=False)
            except Exception:
                pass

    still_fenced = len(target.base_urls)
    deadline = time.time() + 30.0
    while time.time() < deadline:
        sweep()
        still_fenced = 0
        for i in range(len(target.base_urls)):
            try:
                doc = json.loads(target._get(
                    target.base_urls[i] + "/debug/membership"))
            except Exception:
                still_fenced += 1
                continue
            if doc.get("fenced"):
                still_fenced += 1
        if still_fenced == 0:
            break
        time.sleep(0.3)

    out: dict = {"healedOk": 1 if healed else 0,
                 "stillFenced": still_fenced}

    names = ("cluster.fenced", "cluster.unfenced",
             "cluster.staleTokenRejected", "cluster.nodeDown",
             "cluster.nodeUp", "backup.scheduler.skippedFenced")
    sums = dict.fromkeys(names, 0.0)
    for i in range(len(target.base_urls)):
        try:
            dvars = target.debug_vars(i)
        except Exception:
            continue
        for n in names:
            sums[n] += _counter_sum(dvars, n)
    out["fencedTransitions"] = int(sums["cluster.fenced"])
    out["unfencedTransitions"] = int(sums["cluster.unfenced"])
    out["staleTokenRejected"] = int(sums["cluster.staleTokenRejected"])
    out["nodeDownEvents"] = int(sums["cluster.nodeDown"])
    out["nodeUpEvents"] = int(sums["cluster.nodeUp"])
    out["schedulerSkippedFenced"] = int(
        sums["backup.scheduler.skippedFenced"])

    # Convergence: after the repair passes every fragment's replicas
    # must hold bit-identical content — a healed partition that leaves
    # divergent replicas is the drill's core failure mode.
    if nodes is not None:
        for _ in range(2):
            for n in nodes:
                try:
                    n._sync_schema()
                    if n.syncer is not None:
                        n.syncer.sync_holder()
                except Exception:
                    pass
        digests = target.fragment_digest()
        out["fragments"] = len(digests)
        out["mismatchedFragments"] = sum(
            1 for d in digests.values() if len(d) > 1)
    return out


def _translate_epilogue(sc: Scenario, target) -> dict:
    """After a run with a keyed leg: per-node key-plane counters from
    ``/debug/translate`` plus the cross-node translation-agreement
    check (every node's store for the keyed index reports the same
    maxId once traffic stops — diverging ids is THE keyed-cluster
    failure mode). Returns the report's numeric ``translate`` section."""
    names = ("planes", "builds", "deviceBatches", "deviceKeys",
             "collisionHits", "staleServed", "rebuildsScheduled")
    sums = dict.fromkeys(names, 0)
    coord_max = 0
    replica_max: list[int] = []
    watermarks: list[int] = []
    nodes_seen = 0
    for i in range(len(target.base_urls)):
        try:
            doc = json.loads(target._get(
                target.base_urls[i] + "/debug/translate"))
        except Exception:
            continue
        nodes_seen += 1
        p = doc.get("planes") or {}
        for n in names:
            sums[n] += int(p.get(n, 0))
        ks = (doc.get("stores") or {}).get(f"{INDEX_KEYED}/kf")
        if ks is not None:
            mid = int(ks.get("maxId", 0))
            if doc.get("coordinator"):
                coord_max = max(coord_max, mid)
            else:
                replica_max.append(mid)
            watermarks.append(int(ks.get("watermark", 0)))
    out: dict = {"nodesReporting": nodes_seen}
    for n in names:
        out[n] = sums[n]
    # Replicas only hold the mappings their traffic touched, so maxId
    # may trail the coordinator — but no node may be AHEAD of it
    # (local allocation on a replica is how stores diverge).
    out["keyedMaxId"] = max([coord_max] + replica_max)
    out["replicaAheadOfCoordinator"] = (
        1 if coord_max and replica_max
        and max(replica_max) > coord_max else 0)
    out["keyedWatermarkMin"] = min(watermarks) if watermarks else 0
    return out


# -- counters ------------------------------------------------------------


def _counter_sum(dvars: dict, name: str) -> float:
    """Sum a counter across its tag expansions ('qos.shed' matches both
    "qos.shed" and "qos.shed['class:interactive']")."""
    return sum(v for k, v in dvars.get("counters", {}).items()
               if k == name or k.startswith(name + "["))


def _cluster_counters(target) -> dict:
    names = ("qos.shed", "qos.quotaRejected", "qos.deadlineMiss",
             "cluster.hedgeFired", "cluster.hedgeWon",
             "cluster.breakerOpen", "cache.hits", "cache.misses")
    out = dict.fromkeys(names, 0.0)
    for i in range(len(target.base_urls)):
        try:
            dvars = target.debug_vars(i)
        except Exception:
            continue
        for n in names:
            out[n] += _counter_sum(dvars, n)
    return out


def _server_class_hists(target) -> dict[str, PromHistogram]:
    """Per-QoS-class service-latency histograms merged across nodes."""
    merged: dict[str, PromHistogram] = {}
    for i in range(len(target.base_urls)):
        try:
            text = target.metrics_text(i)
        except Exception:
            continue
        for key, h in parse_prom_histograms(text, _SERVER_HIST).items():
            cls = dict(key).get("class", "")
            if not cls:
                continue
            m = merged.setdefault(cls, PromHistogram())
            if not m.buckets:
                m.buckets = list(h.buckets)
            else:
                m.buckets = [(le, c0 + c1) for (le, c0), (_, c1)
                             in zip(m.buckets, h.buckets)]
            m.exemplars.extend(h.exemplars)
    return merged


# -- the run -------------------------------------------------------------


def run_scenario(sc: Scenario, target=None, out: str | None = None,
                 verbose: bool = False) -> dict:
    """Drive one scenario and return (and optionally write) its SLO
    report. When ``target`` is None a ManagedTarget is booted from the
    scenario's cluster shape and torn down after."""
    from pilosa_tpu.obs.stats import MemoryStats

    owned = target is None
    dr_env = None
    has_partition = any(a.action in ("partition", "heal_partition")
                        for a in sc.chaos)
    part_root = None
    if sc.dr is not None:
        if not owned:
            raise ValueError("a DR drill scenario needs a managed "
                             "target (it owns the nodes it destroys)")
        dr_env = _dr_setup(sc)
    if owned:
        node_opts = dict(sc.node_opts)
        if dr_env is not None:
            node_opts.update(dr_env["node_opts"])
        elif has_partition:
            # Partition drills need durable nodes (the epilogue's
            # fragment-digest convergence check reads the stores) and,
            # when the scenario enables scheduled backups, a shared
            # directory archive for the coordinator to capture into.
            import tempfile
            part_root = tempfile.mkdtemp(prefix="loadgen-partition-")
            if float(node_opts.get("backup_interval", 0.0) or 0.0) > 0:
                node_opts.setdefault(
                    "archive_url", os.path.join(part_root, "archive"))
        target = ManagedTarget(
            n_nodes=sc.nodes, replica_n=sc.replica_n,
            node_opts=node_opts,
            data_root=(dr_env["data_root"] if dr_env else part_root))
    stats = MemoryStats()
    ops = build_ops(sc)
    try:
        setup_dataset(sc, target)

        # compile/cache warmup: one quiet pass over each leg's shape
        for op in ops[:sc.warmup_queries]:
            target.query(op.index, op.pql, qos_class=op.qos_class,
                         tenant=f"t{op.tenant}", no_cache=op.no_cache)

        before = _cluster_counters(target)
        stop = threading.Event()
        threads = []
        ingest_totals = {"vals": 0, "seconds": 0.0, "batches": 0,
                         "errors": 0}
        chaos_applied: list[dict] = []
        t0 = time.perf_counter()
        if sc.ingest is not None:
            threads.append(threading.Thread(
                target=_ingest_loop, args=(sc, target, stop, ingest_totals),
                name="loadgen-ingest", daemon=True))
        if sc.chaos:
            threads.append(threading.Thread(
                target=_chaos_loop, args=(sc, target, stop, t0, chaos_applied),
                name="loadgen-chaos", daemon=True))
        for t in threads:
            t.start()

        max_lag = 0.0

        def do_op(op: Op) -> None:
            tid = tracing.new_trace_id()
            out_ = target.query(op.index, op.pql, qos_class=op.qos_class,
                                tenant=f"t{op.tenant}", trace_id=tid,
                                no_cache=op.no_cache,
                                node=op.tenant % len(target.base_urls))
            # Latency from the SCHEDULED arrival: driver lag and server
            # queueing both count — that's the open-loop point.
            lat = (time.perf_counter() - t0) - op.offset
            tok = tracing.set_current_trace(tid)
            try:
                stats.with_tags(f"class:{op.qos_class}").timing(
                    "loadgen.latencySeconds", lat)
                stats.with_tags(f"leg:{op.leg}").timing(
                    "loadgen.legSeconds", lat)
            finally:
                tracing.reset_current_trace(tok)
            stats.with_tags(f"class:{op.qos_class}").count(
                f"loadgen.{out_.status}")
            stats.with_tags(f"leg:{op.leg}").count(
                f"loadgen.leg.{out_.status}")

        dispatched = 0
        with ThreadPoolExecutor(max_workers=sc.max_workers) as pool:
            futs = []
            for op in ops:
                delay = op.offset - (time.perf_counter() - t0)
                if delay > 0:
                    time.sleep(delay)
                else:
                    max_lag = max(max_lag, -delay)
                futs.append(pool.submit(do_op, op))
                dispatched += 1
            for f in futs:
                f.result()
        elapsed = time.perf_counter() - t0
        stop.set()
        for t in threads:
            t.join(timeout=30)
        after = _cluster_counters(target)

        part_section = (_partition_epilogue(sc, target)
                        if has_partition else None)
        dr_section = (_dr_epilogue(sc, target, dr_env)
                      if dr_env is not None else None)
        translate_section = (_translate_epilogue(sc, target)
                             if any(leg.kind == "keyed" for leg in sc.legs)
                             else None)
        report = _build_report(sc, target, stats, ops, elapsed, dispatched,
                               max_lag, before, after, ingest_totals,
                               chaos_applied, dr_section, part_section,
                               translate_section)
    finally:
        if owned:
            target.close()
        if dr_env is not None:
            import shutil
            dr_env["srv"].close()
            shutil.rmtree(dr_env["data_root"], ignore_errors=True)
        if part_root is not None:
            import shutil
            shutil.rmtree(part_root, ignore_errors=True)
    errs = validate_report(report)
    if errs:
        raise RuntimeError(f"SLO report failed its own schema: {errs}")
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    if verbose:
        print(json.dumps(report, indent=2, sort_keys=True))
    return report


def _build_report(sc: Scenario, target, stats, ops, elapsed, dispatched,
                  max_lag, before, after, ingest_totals, chaos_applied,
                  dr=None, partition=None, translate=None):
    delta = {k: after[k] - before[k] for k in after}
    server_hists = _server_class_hists(target)

    def ms(x: float) -> float:
        return round(x * 1000.0, 3)

    per_class: dict[str, dict] = {}
    for cls in sorted({op.qos_class for op in ops}):
        tag = f"class:{cls}"
        counts = {s: int(stats.counter_value(f"loadgen.{s}", tag))
                  for s in ("ok", "shed", "quota", "deadline", "error")}
        n = sum(counts.values())
        sh = server_hists.get(cls)
        per_class[cls] = {
            "client": {
                "count": stats.timing_count("loadgen.latencySeconds", tag),
                "p50Ms": ms(stats.timing_quantile(
                    "loadgen.latencySeconds", 0.50, tag)),
                "p99Ms": ms(stats.timing_quantile(
                    "loadgen.latencySeconds", 0.99, tag)),
                "p999Ms": ms(stats.timing_quantile(
                    "loadgen.latencySeconds", 0.999, tag)),
            },
            "server": None if sh is None else {
                "count": sh.count,
                "p50Ms": ms(sh.quantile(0.50)),
                "p99Ms": ms(sh.quantile(0.99)),
                "p999Ms": ms(sh.quantile(0.999)),
            },
            "counts": counts,
            "shedRate": round(counts["shed"] / n, 4) if n else 0.0,
            "errorRate": round(counts["error"] / n, 4) if n else 0.0,
        }

    legs: dict[str, dict] = {}
    for leg in sc.legs:
        tag = f"leg:{leg.name}"
        legs[leg.name] = {
            "count": stats.timing_count("loadgen.legSeconds", tag),
            "p50Ms": ms(stats.timing_quantile("loadgen.legSeconds",
                                              0.50, tag)),
            "p99Ms": ms(stats.timing_quantile("loadgen.legSeconds",
                                              0.99, tag)),
            "p999Ms": ms(stats.timing_quantile("loadgen.legSeconds",
                                               0.999, tag)),
            "errors": int(stats.counter_value("loadgen.leg.error", tag)),
        }

    hits, misses = delta["cache.hits"], delta["cache.misses"]
    looked = hits + misses

    # Exemplars: the engine's own p99+ tail first (client-observed
    # budget-blowers), then trace ids the servers exported on their
    # /metrics p99 buckets. Resolution goes through /debug/queries —
    # any node answers thanks to the cross-node fan-out. The ring's
    # slowest-retained entry is the fallback so a report always links
    # at least one profile.
    candidates: list[tuple[str, float, str]] = []
    for (name, tags), h in sorted(stats.timings.items()):
        if name != "loadgen.latencySeconds":
            continue
        for tid, val in tail_exemplars(h)[:3]:
            candidates.append((tid, val, f"client:{','.join(tags)}"))
    for cls, sh in sorted(server_hists.items()):
        for tid, val in sh.exemplars[-3:]:
            candidates.append((tid, val, f"server:class:{cls}"))
    exemplars, seen = [], set()
    for tid, val, source in candidates:
        if tid in seen or len(exemplars) >= 3:
            continue
        seen.add(tid)
        prof = target.resolve_profile(tid)
        if prof is not None:
            exemplars.append({"traceId": tid, "latencyMs": ms(val),
                              "source": source, "profile": prof})
    if not exemplars:
        try:
            import urllib.request
            listing = json.loads(urllib.request.urlopen(
                target.base_urls[0] + "/debug/queries", timeout=10).read())
            for doc in listing.get("queries", [])[:1]:
                exemplars.append({
                    "traceId": doc.get("traceId", ""),
                    "latencyMs": doc.get("timings", {}).get("totalMs", 0.0),
                    "source": "ring", "profile": doc})
        except Exception:
            pass

    return {
        "schemaVersion": SCHEMA_VERSION,
        "scenario": sc.to_dict(),
        "target": {"mode": target.mode, "nodes": len(target.base_urls)},
        "arrivals": {
            "process": sc.process,
            "rateTarget": sc.rate,
            "rateAchieved": round(dispatched / elapsed, 2) if elapsed else 0.0,
            "scheduled": len(ops),
            "dispatched": dispatched,
            "maxLagMs": ms(max_lag),
        },
        "perClass": per_class,
        "legs": legs,
        "rates": {
            "shed": delta["qos.shed"],
            "quota": delta["qos.quotaRejected"],
            "deadlineMiss": delta["qos.deadlineMiss"],
            "hedgeFired": delta["cluster.hedgeFired"],
            "hedgeWon": delta["cluster.hedgeWon"],
            "breakerOpens": delta["cluster.breakerOpen"],
        },
        "cache": {
            "hits": hits,
            "misses": misses,
            "hitRatio": round(hits / looked, 4) if looked else 0.0,
        },
        "ingest": None if sc.ingest is None else {
            "vals": ingest_totals["vals"],
            "seconds": round(ingest_totals["seconds"], 3),
            "batches": ingest_totals["batches"],
            "errors": ingest_totals["errors"],
            "mvalsPerS": round(
                ingest_totals["vals"] / ingest_totals["seconds"] / 1e6, 3)
                if ingest_totals["seconds"] else 0.0,
        },
        "chaos": chaos_applied,
        "dr": (None if dr is None else dict(
            dr, failedQueries=int(sum(per_class[c]["counts"]["error"]
                                      for c in per_class)))),
        "partition": (None if partition is None else dict(
            partition,
            failedQueries=int(sum(per_class[c]["counts"]["error"]
                                  for c in per_class)))),
        "translate": translate,
        "exemplars": exemplars,
    }
