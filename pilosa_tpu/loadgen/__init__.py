"""loadgen — the open-loop traffic harness.

A *scenario* describes production-shaped load: an open-loop arrival
process (arrivals fire on a precomputed schedule, never gated on
completions, so queue buildup is visible instead of hidden), a
zipf-skewed workload mix of query legs, a background PTS1 ingest leg,
and an optional chaos timeline. One run drives a live node or cluster
over HTTP and emits a machine-readable SLO report: per-QoS-class
p50/p99/p999, shed/quota/hedge/breaker rates, cache hit ratio, ingest
throughput, and p99 exemplar trace ids resolved through
``/debug/queries/<trace-id>`` into full cost profiles.

Run one with ``python -m pilosa_tpu.loadgen <scenario>`` (see
``scenarios.py`` for the built-ins) or from bench.py via
``BENCH_CONFIGS=overload``-style thin configs.
"""

from pilosa_tpu.loadgen.arrival import OpenLoopArrivals
from pilosa_tpu.loadgen.engine import run_scenario
from pilosa_tpu.loadgen.mix import WorkloadMix, ZipfPicker, zipf_weights
from pilosa_tpu.loadgen.report import validate_report
from pilosa_tpu.loadgen.scenario import (ChaosAction, IngestLeg, QueryLeg,
                                         Scenario)
from pilosa_tpu.loadgen.scenarios import SCENARIOS, get_scenario
from pilosa_tpu.loadgen.target import AttachedTarget, ManagedTarget

__all__ = [
    "OpenLoopArrivals", "WorkloadMix", "ZipfPicker", "zipf_weights",
    "Scenario", "QueryLeg", "IngestLeg", "ChaosAction",
    "run_scenario", "validate_report", "SCENARIOS", "get_scenario",
    "AttachedTarget", "ManagedTarget",
]
