"""Built-in scenarios.

Several of these re-express bench.py silos as legs of the one engine:
``dashboard_storm`` is the dispatch-storm + cache-churn pair,
``overload`` is the slow-peer breaker/hedge drill, ``ingest_under_query``
is the interactive-p99-under-PTS1-stream drill, and ``elastic`` is the
query-through-resize drill — each formerly its own hand-rolled
bench loop, now a scenario config on shared machinery.

``smoke``/``smoke3`` are the CI pair: short, seeded, deterministic
op sequences (see ``engine.build_ops``) sized to finish in ~30 s
total on a CPU-only runner.
"""

from __future__ import annotations

from pilosa_tpu.loadgen.scenario import (ChaosAction, IngestLeg, QueryLeg,
                                         Scenario)


def _mixed_legs(keyed: bool = True) -> list[QueryLeg]:
    legs = [
        QueryLeg(name="dashboard", weight=5.0, kind="dashboard",
                 qos_class="interactive", population=16, zipf_s=1.2),
        QueryLeg(name="adhoc", weight=2.0, kind="adhoc",
                 qos_class="batch", population=64, zipf_s=0.8,
                 no_cache=True),
        QueryLeg(name="bsi_agg", weight=2.0, kind="bsi",
                 qos_class="batch", population=16, zipf_s=1.0),
        QueryLeg(name="topn", weight=1.0, kind="topn",
                 qos_class="interactive", population=8, zipf_s=1.0),
        QueryLeg(name="distinct", weight=1.0, kind="distinct",
                 qos_class="batch", population=16, zipf_s=1.0),
        QueryLeg(name="similar", weight=1.0, kind="similar",
                 qos_class="interactive", population=8, zipf_s=1.0),
    ]
    if keyed:
        legs.append(QueryLeg(name="keyed", weight=1.0, kind="keyed",
                             qos_class="interactive", population=32,
                             zipf_s=1.1))
    return legs


def smoke() -> Scenario:
    """CI single-node leg: every query kind plus a trickle ingest."""
    return Scenario(
        name="smoke", seed=42, duration_s=8.0, rate=40.0,
        nodes=1, shards=4, rows=48, density=0.005,
        tenants=8, tenant_s=1.2,
        legs=_mixed_legs(keyed=True),
        ingest=IngestLeg(duty=0.3, shards=2, per_shard=10_000),
        node_opts={"qos_max_concurrent": 8},
    )


def smoke3() -> Scenario:
    """CI 3-node leg: mixed traffic over fan-out, one mid-run gray
    failure (slow peer) that heals — breakers and hedging must show
    up in the rates, and the p99 exemplar must resolve cross-node."""
    return Scenario(
        name="smoke3", seed=42, duration_s=8.0, rate=25.0,
        nodes=3, replica_n=2, shards=6, rows=48, density=0.004,
        tenants=8, tenant_s=1.2,
        legs=_mixed_legs(keyed=False),
        chaos=[ChaosAction(at_s=3.0, action="slow_peer", node=1, value=150.0),
               ChaosAction(at_s=5.5, action="heal_peer", node=1)],
        node_opts={"qos_max_concurrent": 8,
                   "breaker_threshold": 3, "breaker_cooldown": 1.0,
                   "hedge": True, "hedge_delay_ms": 60.0,
                   "hedge_budget_pct": 20.0},
    )


def mixed() -> Scenario:
    """The flagship: a minute of full mixed traffic on 3 nodes."""
    return Scenario(
        name="mixed", seed=7, duration_s=60.0, rate=120.0,
        nodes=3, replica_n=2, shards=8, rows=64, density=0.01,
        tenants=32, tenant_s=1.2,
        legs=_mixed_legs(keyed=True),
        ingest=IngestLeg(duty=0.5, shards=4, per_shard=50_000),
        node_opts={"qos_max_concurrent": 16, "qos_tenant_rate": 64.0,
                   "qos_tenant_burst": 128.0,
                   "breaker_threshold": 5, "hedge": True,
                   "hedge_delay_ms": 50.0},
        max_workers=128,
    )


def dashboard_storm() -> Scenario:
    """bench_dispatch + bench_cache re-expressed: a hot repeated
    dashboard panel (dispatch coalescing, result-cache hits) with a
    churn trickle invalidating shards underneath it."""
    return Scenario(
        name="dashboard_storm", seed=11, duration_s=20.0, rate=300.0,
        process="gamma", cv=2.0,   # bursty, the coalescer's diet
        nodes=1, shards=4, rows=32, density=0.01,
        tenants=4, tenant_s=1.5,
        legs=[QueryLeg(name="dashboard", weight=8.0, kind="dashboard",
                       qos_class="interactive", population=5, zipf_s=1.0),
              QueryLeg(name="topn", weight=1.0, kind="topn",
                       qos_class="interactive", population=4)],
        ingest=IngestLeg(duty=0.2, shards=1, per_shard=5_000),
        max_workers=128,
    )


def overload() -> Scenario:
    """bench_overload re-expressed: oversubscribed arrival rate into a
    3-node cluster with one gray-failing peer; admission, breakers,
    and hedging carry the run (shed is expected, errors are not)."""
    return Scenario(
        name="overload", seed=13, duration_s=20.0, rate=150.0,
        nodes=3, replica_n=2, shards=6, rows=48, density=0.008,
        tenants=16, tenant_s=1.1,
        legs=[QueryLeg(name="dashboard", weight=3.0, kind="dashboard",
                       qos_class="interactive", population=16),
              QueryLeg(name="adhoc", weight=2.0, kind="adhoc",
                       qos_class="batch", population=64, no_cache=True)],
        # slow > deadline: legs via node1 breach, feed its breaker, and
        # hedged replicas must win — mirrors the old bench's 0.6s slow
        # peer against a 0.5s deadline.
        chaos=[ChaosAction(at_s=5.0, action="slow_peer", node=1, value=600.0),
               ChaosAction(at_s=14.0, action="heal_peer", node=1)],
        node_opts={"qos_max_concurrent": 4, "qos_max_queue": 8,
                   "qos_default_deadline": 0.5,
                   "breaker_threshold": 3, "breaker_cooldown": 1.0,
                   "hedge": True, "hedge_delay_ms": 50.0,
                   "hedge_budget_pct": 20.0},
        max_workers=96,
    )


def ingest_under_query() -> Scenario:
    """bench_ingest's under-load half re-expressed: a near-saturating
    PTS1 stream (duty 0.9) with an interactive dashboard leg whose p99
    is the number that matters."""
    return Scenario(
        name="ingest_under_query", seed=23, duration_s=20.0, rate=50.0,
        nodes=1, shards=8, rows=32, density=0.005,
        tenants=8, tenant_s=1.1,
        legs=[QueryLeg(name="dashboard", weight=4.0, kind="dashboard",
                       qos_class="interactive", population=8),
              QueryLeg(name="bsi_agg", weight=1.0, kind="bsi",
                       qos_class="batch", population=8)],
        ingest=IngestLeg(duty=0.9, shards=8, per_shard=100_000),
        node_opts={"qos_max_concurrent": 8, "ingest_max_inflight_mb": 64},
    )


def elastic() -> Scenario:
    """bench_elastic re-expressed: steady mixed traffic while a node
    joins mid-run and another is removed later — queries must serve
    through both cutovers."""
    return Scenario(
        name="elastic", seed=31, duration_s=24.0, rate=40.0,
        nodes=2, replica_n=2, shards=6, rows=48, density=0.005,
        tenants=8, tenant_s=1.1,
        legs=[QueryLeg(name="dashboard", weight=3.0, kind="dashboard",
                       qos_class="interactive", population=16),
              QueryLeg(name="bsi_agg", weight=1.0, kind="bsi",
                       qos_class="batch", population=8)],
        chaos=[ChaosAction(at_s=6.0, action="add_node"),
               ChaosAction(at_s=16.0, action="remove_node", node=1)],
        node_opts={"qos_max_concurrent": 8},
    )


def dr_drill() -> Scenario:
    """Unattended disaster recovery under fire: mixed traffic with a
    trickle ingest while every node runs a backup scheduler against a
    fault-injected object store (≥10% of archive requests 503, plus
    torn uploads). Mid-run a gray failure comes and goes, a forced
    backup cycle lands, and then one member is resized out and its
    data dir destroyed. The run must keep zero failed queries; the
    engine's DR epilogue then restores the archive into a fresh
    recovery cluster, proves bit-equivalence fragment by fragment, and
    proves every backup retention left listed still restores."""
    return Scenario(
        name="dr_drill", seed=97, duration_s=16.0, rate=30.0,
        nodes=3, replica_n=2, shards=4, rows=32, density=0.004,
        tenants=8, tenant_s=1.2,
        legs=[QueryLeg(name="dashboard", weight=4.0, kind="dashboard",
                       qos_class="interactive", population=16),
              QueryLeg(name="adhoc", weight=2.0, kind="adhoc",
                       qos_class="batch", population=32, no_cache=True),
              QueryLeg(name="bsi_agg", weight=1.0, kind="bsi",
                       qos_class="batch", population=8)],
        ingest=IngestLeg(duty=0.25, shards=2, per_shard=8_000),
        chaos=[ChaosAction(at_s=2.5, action="slow_peer", node=1,
                           value=120.0),
               ChaosAction(at_s=5.0, action="heal_peer", node=1),
               ChaosAction(at_s=6.0, action="dr_backup"),
               ChaosAction(at_s=8.5, action="dr_destroy_data", node=2),
               ChaosAction(at_s=12.0, action="dr_backup")],
        dr={"failRate": 0.15, "intervalS": 4.0, "fullEvery": 1,
            "keepChains": 1, "recoveryNodes": 2, "tornUploads": 2},
        node_opts={"qos_max_concurrent": 8},
    )


def partition_drill() -> Scenario:
    """Split-brain under traffic: five nodes, replica 3, steady mixed
    load while the network is cut three ways in sequence — a 2-node
    minority island (the majority keeps serving, the minority fences
    and 503s), a cut that strands the COORDINATOR in the minority (its
    backup scheduler must suspend the duty: skipped-fenced, not a
    second capture racing the majority), and an asymmetric one-way
    link (the isolated node fences itself; nobody false-positives it
    DOWN because indirect probes still reach it). Each cut heals
    before the next. The engine's partition epilogue then proves every
    node un-fenced, forces a repair pass, and requires every
    fragment's replicas to be bit-identical — a healed split that
    leaves divergent replicas fails the drill."""
    return Scenario(
        name="partition_drill", seed=61, duration_s=18.0, rate=25.0,
        nodes=5, replica_n=3, shards=6, rows=32, density=0.004,
        tenants=10, tenant_s=1.2,
        legs=[QueryLeg(name="dashboard", weight=4.0, kind="dashboard",
                       qos_class="interactive", population=16),
              QueryLeg(name="adhoc", weight=2.0, kind="adhoc",
                       qos_class="batch", population=32, no_cache=True),
              QueryLeg(name="bsi_agg", weight=1.0, kind="bsi",
                       qos_class="batch", population=8)],
        chaos=[ChaosAction(at_s=2.5, action="partition", group=[3, 4]),
               ChaosAction(at_s=6.5, action="heal_partition"),
               ChaosAction(at_s=8.5, action="partition", group=[0, 1],
                           mode="timeout", value=150.0),
               ChaosAction(at_s=12.0, action="heal_partition"),
               ChaosAction(at_s=13.0, action="partition", group=[1],
                           mode="oneway"),
               ChaosAction(at_s=15.5, action="heal_partition")],
        # The failure detector must actually sweep (fencing hangs off
        # it); breakers + short deadlines keep majority-side legs into
        # the dead island from stalling the client pool; the 0.5s
        # backup cadence guarantees scheduler ticks land inside the
        # coordinator's fenced window even after detection latency
        # (the engine supplies a directory archive when backups are on
        # and the scenario has partitions).
        node_opts={"qos_max_concurrent": 8,
                   "check_nodes_interval": 0.5,
                   "anti_entropy_interval": 4.0,
                   "breaker_threshold": 3, "breaker_cooldown": 1.0,
                   "backup_interval": 0.5, "backup_full_every": 1,
                   "backup_keep_chains": 2},
    )


SCENARIOS = {
    "smoke": smoke,
    "smoke3": smoke3,
    "mixed": mixed,
    "dashboard_storm": dashboard_storm,
    "overload": overload,
    "ingest_under_query": ingest_under_query,
    "elastic": elastic,
    "dr_drill": dr_drill,
    "partition_drill": partition_drill,
}


def get_scenario(name: str) -> "Scenario":
    try:
        return SCENARIOS[name]()
    except KeyError:
        raise KeyError(f"unknown scenario {name!r} "
                       f"(have: {', '.join(sorted(SCENARIOS))})") from None
