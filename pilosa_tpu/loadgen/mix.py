"""Workload-mix model: zipf-skewed tenant and query populations.

Production traffic is never uniform — a few tenants and a few
dashboard panels dominate. A zipf(s) rank-frequency law over a finite
population captures that: P(rank r) ∝ 1/r^s. s≈1 is classic web
skew; s=0 degenerates to uniform (handy for control runs).
"""

from __future__ import annotations

import numpy as np


def zipf_weights(n: int, s: float) -> np.ndarray:
    """Normalized zipf pmf over ranks 1..n (rank 0 is the hottest)."""
    if n <= 0:
        raise ValueError(f"population must be > 0, got {n}")
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return w / w.sum()


class ZipfPicker:
    """Seedable categorical sampler over a zipf-weighted population.

    Sampling goes through a precomputed cdf + searchsorted — O(log n)
    per pick, bit-deterministic given the caller's rng state.
    """

    def __init__(self, n: int, s: float):
        self.n = n
        self.s = s
        self._cdf = np.cumsum(zipf_weights(n, s))

    def pick(self, rng: np.random.Generator) -> int:
        """One rank in [0, n) — 0 is the hottest."""
        return int(np.searchsorted(self._cdf, rng.random(), side="right"))


class WorkloadMix:
    """Weighted choice over named legs plus a shared tenant population.

    ``sample(rng)`` → (leg_name, tenant_rank). Leg weights are
    arbitrary positives (normalized internally); tenants follow
    zipf(tenant_s) so the hot-tenant cache/quota interactions show up.
    """

    def __init__(self, legs: list[tuple[str, float]],
                 n_tenants: int = 8, tenant_s: float = 1.1):
        if not legs:
            raise ValueError("mix needs at least one leg")
        names, weights = zip(*legs)
        w = np.asarray(weights, dtype=np.float64)
        if (w <= 0).any():
            raise ValueError(f"leg weights must be > 0, got {list(w)}")
        self.names = list(names)
        self._leg_cdf = np.cumsum(w / w.sum())
        self.tenants = ZipfPicker(n_tenants, tenant_s)

    def sample(self, rng: np.random.Generator) -> tuple[str, int]:
        leg = self.names[int(np.searchsorted(self._leg_cdf, rng.random(),
                                             side="right"))]
        return leg, self.tenants.pick(rng)
