"""CLI: ``python -m pilosa_tpu.loadgen <scenario> [options]``.

Runs one scenario — against a managed in-process cluster by default,
or a live deployment via ``--target`` — and writes its SLO report.

    python -m pilosa_tpu.loadgen smoke --out /tmp/slo.json
    python -m pilosa_tpu.loadgen mixed --target http://h1:10101,http://h2:10101
    python -m pilosa_tpu.loadgen --list
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    from pilosa_tpu.loadgen.engine import run_scenario
    from pilosa_tpu.loadgen.scenario import Scenario
    from pilosa_tpu.loadgen.scenarios import SCENARIOS, get_scenario
    from pilosa_tpu.loadgen.target import AttachedTarget

    ap = argparse.ArgumentParser(
        prog="python -m pilosa_tpu.loadgen",
        description="open-loop scenario harness: drive a live "
                    "node/cluster, emit an SLO report")
    ap.add_argument("scenario", nargs="?",
                    help="built-in scenario name, or a path to a "
                         "scenario JSON file")
    ap.add_argument("--list", action="store_true",
                    help="list built-in scenarios and exit")
    ap.add_argument("--target", default="",
                    help="comma-separated base URLs of a live cluster "
                         "(default: boot a managed in-process cluster)")
    ap.add_argument("--out", default="",
                    help="write the SLO report JSON here")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the scenario seed")
    ap.add_argument("--duration", type=float, default=None,
                    help="override duration_s")
    ap.add_argument("--rate", type=float, default=None,
                    help="override offered rate (arrivals/s)")
    ap.add_argument("--quiet", action="store_true",
                    help="don't print the report to stdout")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            print(f"{name:20s} {SCENARIOS[name].__doc__.splitlines()[0]}")
        return 0
    if not args.scenario:
        ap.error("scenario name required (or --list)")

    if args.scenario.endswith(".json"):
        with open(args.scenario) as f:
            sc = Scenario.from_dict(json.load(f))
    else:
        sc = get_scenario(args.scenario)
    if args.seed is not None:
        sc.seed = args.seed
    if args.duration is not None:
        sc.duration_s = args.duration
    if args.rate is not None:
        sc.rate = args.rate

    target = None
    if args.target:
        target = AttachedTarget(args.target.split(","))
    report = run_scenario(sc, target=target, out=args.out or None)
    if not args.quiet:
        print(json.dumps(report, indent=2, sort_keys=True))
    if args.out:
        print(f"# SLO report written to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
