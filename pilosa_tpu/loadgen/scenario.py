"""Scenario model: everything a run needs, as plain data.

A Scenario is JSON-serializable both ways (``to_dict``/``from_dict``)
so scenario configs can live in files, CI args, and SLO reports. The
engine never reads anything the Scenario doesn't carry — same dict +
same seed → same op sequence.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field

#: query-leg kinds the engine knows how to drive
LEG_KINDS = ("dashboard", "adhoc", "bsi", "topn", "keyed", "distinct",
             "similar")


@dataclass
class QueryLeg:
    """One slice of the query mix.

    - ``dashboard``: a small fixed panel of Count queries per tenant —
      repeat-heavy, the result cache's best case.
    - ``adhoc``: randomized Intersect/Difference over a wide row
      population — cache-miss exploratory traffic.
    - ``bsi``: Range→Sum aggregates over an int field.
    - ``topn``: TopN ranking, optionally filtered.
    - ``keyed``: string-keyed Count/Row queries (exercises key
      translation on the hot path).
    - ``distinct``: Count(Distinct(...)) over the int field — the HLL
      sketch planes (filtered and unfiltered spellings).
    - ``similar``: SimilarTopN row-similarity ranking over the set
      field.
    """

    name: str
    weight: float = 1.0
    kind: str = "dashboard"
    qos_class: str = "interactive"
    zipf_s: float = 1.1      # skew of the within-leg query population
    population: int = 32     # distinct queries the leg draws from
    no_cache: bool = False

    def __post_init__(self):
        if self.kind not in LEG_KINDS:
            raise ValueError(f"unknown leg kind {self.kind!r} "
                             f"(want one of {LEG_KINDS})")


@dataclass
class IngestLeg:
    """Background PTS1 ingest at a duty cycle: stream a batch, then
    sleep so streaming time ≈ ``duty`` of wall time. duty=1.0 hammers
    continuously (the bench_ingest silo); 0.2 is a trickle."""

    duty: float = 0.5
    shards: int = 4
    per_shard: int = 20_000
    value_min: int = -100_000
    value_max: int = 100_000


@dataclass
class ChaosAction:
    """One timeline entry. Actions: ``slow_peer`` (value = delay ms,
    via POST /internal/fault), ``heal_peer``, ``add_node`` (live
    resize grow), ``remove_node`` (live resize shrink), ``dr_backup``
    (force one scheduled-backup cycle now), ``dr_destroy_data``
    (resize a member out and destroy its data directory — the DR
    drill's disaster), ``partition`` (cut the network between
    ``group`` — node indices — and the rest of the ring; ``mode`` is
    ``drop``/``timeout`` for a symmetric cut or ``oneway`` for an
    asymmetric link where only the group's outbound traffic is lost),
    ``heal_partition`` (clear every injected partition fault)."""

    at_s: float
    action: str
    node: int = 1           # index into the target's node list
    value: float = 0.0
    group: list[int] = field(default_factory=list)  # partition side
    mode: str = "drop"      # partition flavor: drop | timeout | oneway

    def __post_init__(self):
        if self.action not in ("slow_peer", "heal_peer",
                               "add_node", "remove_node",
                               "dr_backup", "dr_destroy_data",
                               "partition", "heal_partition"):
            raise ValueError(f"unknown chaos action {self.action!r}")
        if self.mode not in ("drop", "timeout", "oneway"):
            raise ValueError(f"unknown partition mode {self.mode!r}")


@dataclass
class Scenario:
    """A full run description. ``rate`` is offered load (open-loop);
    the report records both target and achieved rates so a saturated
    driver is visible too."""

    name: str
    seed: int = 42
    duration_s: float = 10.0
    rate: float = 50.0
    process: str = "poisson"
    cv: float = 1.0

    # target shape (managed mode; ignored when attaching to live urls)
    nodes: int = 1
    replica_n: int = 1
    node_opts: dict = field(default_factory=dict)  # ServerNode kwargs

    # dataset
    shards: int = 4
    rows: int = 64
    density: float = 0.01    # fraction of each shard's columns set

    # mix
    tenants: int = 8
    tenant_s: float = 1.1
    legs: list[QueryLeg] = field(default_factory=list)
    ingest: IngestLeg | None = None
    chaos: list[ChaosAction] = field(default_factory=list)

    # disaster-recovery drill (managed mode only): when set, the engine
    # boots a fault-injected in-process object store, gives every node
    # a data dir plus an unattended backup scheduler pointed at it, and
    # after the run restores the archive into a fresh recovery cluster
    # and proves bit-equivalence. Keys: failRate (per-request 503
    # probability), intervalS (scheduler cadence), fullEvery,
    # keepChains, recoveryNodes, tornUploads.
    dr: dict | None = None

    # driver
    max_workers: int = 64
    warmup_queries: int = 8

    def __post_init__(self):
        if not self.legs:
            raise ValueError(f"scenario {self.name!r} has no query legs")

    def to_dict(self) -> dict:
        d = asdict(self)
        d["ingest"] = asdict(self.ingest) if self.ingest else None
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Scenario":
        d = dict(d)
        d["legs"] = [QueryLeg(**leg) for leg in d.get("legs", [])]
        ing = d.get("ingest")
        d["ingest"] = IngestLeg(**ing) if ing else None
        d["chaos"] = [ChaosAction(**c) for c in d.get("chaos", [])]
        return cls(**d)
