"""Scenario targets: the live cluster a run drives, over real HTTP.

Two flavors behind one surface:

- ``ManagedTarget`` boots N in-process ServerNodes on loopback ports
  (full stack: QoS gate, quotas, breakers, hedge, result cache,
  profile ring, /metrics) and owns their lifecycle — the CI/bench
  mode, and the only mode that can run the resize chaos actions.
- ``AttachedTarget`` points at already-running nodes by URL — the
  "drive a real deployment" mode. Chaos actions degrade gracefully:
  slow_peer needs the node started with chaos faults enabled;
  add/remove_node are refused.

Either way the engine talks production HTTP — the same admission,
cache, and profile paths a real client hits, not a bench backdoor.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import threading
import time
import urllib.error
import urllib.request


def _free_ports(n: int) -> list[int]:
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class QueryOutcome:
    """One request's classification, from the HTTP status line."""

    __slots__ = ("status", "code")

    def __init__(self, status: str, code: int):
        self.status = status   # ok | shed | quota | deadline | error
        self.code = code


_STATUS_BY_CODE = {503: "shed", 429: "quota", 504: "deadline"}


class _HTTPTargetBase:
    """Shared HTTP plumbing over a list of node base URLs."""

    def __init__(self, base_urls: list[str], timeout: float = 30.0):
        self.base_urls = list(base_urls)
        self.timeout = timeout

    # -- raw I/O ------------------------------------------------------

    def _post(self, url: str, body: str = "",
              headers: dict | None = None) -> bytes:
        req = urllib.request.Request(url, data=body.encode(),
                                     headers=headers or {}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def _get(self, url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return resp.read()

    # -- setup surface ------------------------------------------------

    def create_index(self, index: str, opts: dict | None = None) -> None:
        self._post(f"{self.base_urls[0]}/index/{index}",
                   json.dumps({"options": opts or {}}))

    def create_field(self, index: str, fld: str,
                     opts: dict | None = None) -> None:
        self._post(f"{self.base_urls[0]}/index/{index}/field/{fld}",
                   json.dumps({"options": opts or {}}))

    def import_bits(self, index: str, fld: str, rows, cols) -> None:
        self._post(f"{self.base_urls[0]}/index/{index}/field/{fld}/import",
                   json.dumps({"rowIDs": [int(r) for r in rows],
                               "columnIDs": [int(c) for c in cols]}))

    # -- query path ---------------------------------------------------

    def query(self, index: str, pql: str, *, qos_class: str = "",
              tenant: str = "", trace_id: str = "",
              no_cache: bool = False, node: int = 0) -> QueryOutcome:
        """One client query. Never retries — an open-loop driver
        records the rejection instead of hiding it behind a retry."""
        url = f"{self.base_urls[node % len(self.base_urls)]}" \
              f"/index/{index}/query"
        params = []
        if qos_class:
            params.append(f"qosClass={qos_class}")
        if no_cache:
            params.append("noCache=true")
        if params:
            url += "?" + "&".join(params)
        headers = {}
        if trace_id:
            headers["X-Pilosa-Trace-Id"] = trace_id
        if tenant:
            headers["X-API-Key"] = tenant
        try:
            self._post(url, pql, headers)
            return QueryOutcome("ok", 200)
        except urllib.error.HTTPError as e:
            e.read()
            return QueryOutcome(_STATUS_BY_CODE.get(e.code, "error"), e.code)
        except (urllib.error.URLError, ConnectionError, OSError, TimeoutError):
            return QueryOutcome("error", 0)

    # -- observability surface ---------------------------------------

    def metrics_text(self, node: int = 0) -> str:
        return self._get(f"{self.base_urls[node]}/metrics").decode()

    def debug_vars(self, node: int = 0) -> dict:
        return json.loads(self._get(f"{self.base_urls[node]}/debug/vars"))

    def resolve_profile(self, trace_id: str, node: int = 0) -> dict | None:
        """Full nested cost profile for a trace id, or None. Any node
        answers — a local ring miss fans out to the coordinator that
        retained the whole timeline."""
        try:
            return json.loads(self._get(
                f"{self.base_urls[node]}/debug/queries/{trace_id}"))
        except (urllib.error.URLError, OSError):
            return None

    # -- chaos surface ------------------------------------------------

    def slow_peer(self, node: int, delay_ms: float) -> bool:
        try:
            self._post(f"{self.base_urls[node]}/internal/fault",
                       json.dumps({"slowMs": delay_ms}))
            return True
        except (urllib.error.URLError, OSError):
            return False   # node without chaos faults mounted

    def heal_peer(self, node: int) -> bool:
        return self.slow_peer(node, 0.0)

    def _node_ids(self) -> list[str] | None:
        """Cluster node id per base URL (via /debug/membership), or
        None when any node can't answer — partition faults address
        peers by id, not by URL."""
        ids = []
        for u in self.base_urls:
            try:
                doc = json.loads(self._get(f"{u}/debug/membership"))
            except (urllib.error.URLError, OSError, ValueError):
                return None
            if not doc.get("localId"):
                return None
            ids.append(doc["localId"])
        return ids

    def partition(self, group: list[int], mode: str = "drop",
                  delay_ms: float = 0.0) -> bool:
        """Cut the network between ``group`` (node indices) and the
        rest. ``drop``/``timeout`` fault both directions; ``oneway``
        faults only the group's outbound links — the asymmetric case
        where A can't reach B but B still reaches A."""
        ids = self._node_ids()
        if ids is None:
            return False
        n = len(self.base_urls)
        side = {i % n for i in group}
        fault_mode = "drop" if mode == "oneway" else mode
        ok = True
        for i, url in enumerate(self.base_urls):
            if i in side:
                peers = [ids[j] for j in range(n) if j not in side]
            elif mode != "oneway":
                peers = [ids[j] for j in sorted(side)]
            else:
                continue
            if not peers:
                continue
            try:
                self._post(f"{url}/internal/fault",
                           json.dumps({"partition": {
                               "peers": peers, "mode": fault_mode,
                               "delayMs": delay_ms}}))
            except (urllib.error.URLError, OSError):
                ok = False
        return ok

    def heal_partition(self) -> bool:
        ok = True
        for url in self.base_urls:
            try:
                self._post(f"{url}/internal/fault",
                           json.dumps({"healPartition": True}))
            except (urllib.error.URLError, OSError):
                ok = False
        return ok

    def add_node(self) -> bool:
        return False

    def remove_node(self, node: int) -> bool:
        return False

    def dr_backup(self) -> bool:
        return False

    def dr_destroy_data(self, node: int) -> bool:
        return False

    def close(self) -> None:
        pass


class AttachedTarget(_HTTPTargetBase):
    """Drive an already-running node/cluster by URL."""

    def __init__(self, urls: list[str], timeout: float = 30.0):
        super().__init__([u.rstrip("/") for u in urls], timeout)
        self.mode = "attached"

    def import_stream(self, reqs: list[dict]) -> int:
        # Without a managed internal client, fall back to per-batch
        # JSON imports — slower, same bits.
        for r in reqs:
            self._post(
                f"{self.base_urls[0]}/index/{r['index']}"
                f"/field/{r['field']}/import",
                json.dumps({"columnIDs": [int(c) for c in r["columnIDs"]],
                            "values": [int(v) for v in r["values"]]}))
        return len(reqs)


class ManagedTarget(_HTTPTargetBase):
    """Boot and own N in-process ServerNodes for one run."""

    def __init__(self, n_nodes: int = 1, replica_n: int = 1,
                 node_opts: dict | None = None, timeout: float = 30.0,
                 data_root: str | None = None):
        from pilosa_tpu.server.node import ServerNode
        from pilosa_tpu.server.httpclient import HTTPInternalClient
        self.mode = "managed"
        # slow-log threshold stays high: the harness reads quantiles
        # and the profile ring, not a WARNING line per query.
        opts = {"use_planner": False, "anti_entropy_interval": 0.0,
                "check_nodes_interval": 0.0, "qos_slow_query_ms": 1000.0,
                "chaos_faults": True}
        opts.update(node_opts or {})
        # data_root gives each node its own durable data dir (the DR
        # drill needs real stores to back up and destroy); without it
        # the nodes stay memory-only as before.
        self._data_root = data_root
        self._dir_seq = n_nodes
        self._node_opts = opts
        addrs = [f"127.0.0.1:{p}" for p in _free_ports(n_nodes)]
        self.nodes = [ServerNode(bind=a, peers=addrs if n_nodes > 1 else None,
                                 replica_n=replica_n,
                                 **self._opts_for(i))
                      for i, a in enumerate(addrs)]
        self._replica_n = replica_n
        self._lock = threading.Lock()
        for n in self.nodes:
            n.open()
        super().__init__([n.address for n in self.nodes], timeout)
        self._client = HTTPInternalClient(timeout=timeout)

    def _opts_for(self, i: int) -> dict:
        opts = dict(self._node_opts)
        if self._data_root:
            opts["data_dir"] = os.path.join(self._data_root, f"n{i}")
        return opts

    def _peer(self, node: int = 0):
        from pilosa_tpu.cluster.node import URI, Node
        n = self.nodes[node]
        return Node(id=n.id, uri=URI(host=n.host, port=n.port))

    def import_stream(self, reqs: list[dict]) -> int:
        return self._client.send_import_stream(self._peer(0), reqs)

    def add_node(self) -> bool:
        from pilosa_tpu.server.node import ServerNode
        with self._lock:
            addr = f"127.0.0.1:{_free_ports(1)[0]}"
            opts = self._opts_for(self._dir_seq)
            self._dir_seq += 1
            joiner = ServerNode(bind=addr, join=self.nodes[0].id,
                                replica_n=self._replica_n, **opts)
            joiner.open()
            self.nodes.append(joiner)
            self.base_urls.append(joiner.address)
            return True

    def _coordinator(self):
        return next((n for n in self.nodes
                     if n.cluster.coordinator() is not None
                     and n.cluster.coordinator().id == n.id),
                    self.nodes[0])

    def _remove(self, node: int):
        """Resize a member out of the ring; returns the closed victim
        ServerNode, or None when removal isn't possible."""
        with self._lock:
            if node <= 0 or node >= len(self.nodes):
                return None   # never shoot node 0 (our setup anchor)
            # Removal is a coordinator-only request, and the coordinator
            # is elected by node-id order — not necessarily nodes[0]. If
            # the named victim IS the coordinator, shoot another member
            # instead: the scenario asks for "a member leaves", not for
            # a coordinator handoff.
            coord = self._coordinator()
            victim = self.nodes[node]
            if victim is coord:
                others = [i for i in range(1, len(self.nodes))
                          if self.nodes[i] is not coord]
                if not others:
                    return None
                node = others[-1]
                victim = self.nodes[node]
            try:
                self._post(f"{coord.address}/cluster/resize/remove-node",
                           json.dumps({"id": victim.id}))
            except (urllib.error.URLError, OSError):
                return None
            self.nodes.pop(node)
            self.base_urls.pop(node)
            victim.close()
            return victim

    def remove_node(self, node: int) -> bool:
        return self._remove(node) is not None

    # -- DR drill surface ---------------------------------------------

    def dr_backup(self) -> bool:
        """Force one scheduled-backup cycle on the coordinator NOW
        (drills and tests; the timer path stays untouched). Retries a
        few times — the drill's archive injects faults on purpose."""
        coord = self._coordinator()
        sched = getattr(coord, "backup_scheduler", None)
        if sched is None:
            return False
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if not coord._backup_gate.acquire(blocking=False):
                # a timer-driven run is mid-capture; wait it out
                time.sleep(0.1)
                continue
            try:
                st = sched.run_once(force=True)
            finally:
                coord._backup_gate.release()
            if st in ("ran", "skipped-unchanged"):
                return True
            time.sleep(0.2)
        return False

    def dr_destroy_data(self, node: int) -> bool:
        """The drill's disaster: resize the member out of the ring
        (serving continues on the survivors), then destroy its data
        directory beyond recovery — only the archive can bring those
        bytes back."""
        victim = self._remove(node)
        if victim is None:
            return False
        if victim.data_dir:
            shutil.rmtree(victim.data_dir, ignore_errors=True)
        return True

    def fragment_digest(self) -> dict[str, set[str]]:
        """Bit-level content fingerprint of every fragment this cluster
        owns: (index/field/view/shard) -> the set of per-replica block-
        checksum digests. Only placement owners contribute — a resize
        leaves restorable-but-stale bytes on former owners, and those
        are not the cluster's state. The DR drill's equivalence check:
        every restored fragment's digest must appear in the live set
        (a backup captures exactly one healthy replica's bytes)."""
        out: dict[str, set[str]] = {}
        for n in self.nodes:
            if n.store is None:
                continue
            for iname, fld, view, shard in n.store.all_fragment_keys():
                if n.cluster is not None and n.id not in {
                        m.id for m in n.cluster.shard_nodes(iname, shard)}:
                    continue
                blocks = n.api.fragment_blocks(iname, fld, view, shard)
                digest = ";".join(f"{b}:{cs.hex()}"
                                  for b, cs in sorted(blocks.items()))
                out.setdefault(f"{iname}/{fld}/{view}/{shard}",
                               set()).add(digest)
        return out

    def close(self) -> None:
        self._client.close()
        for n in self.nodes:
            try:
                n.close()
            except Exception:
                pass
