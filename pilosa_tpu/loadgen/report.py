"""SLO report: the machine-readable artifact one scenario run emits.

Numbers come from the observability substrate, never private lists:
client-side latencies live in the engine's MemoryStats LogHistograms
(read back through ``timing_quantile``), server-side per-class
latencies are parsed out of each node's ``/metrics`` histogram
buckets, and the p99 tail links to real queries via exemplar trace
ids resolved through ``/debug/queries/<trace-id>``.

``validate_report`` is the schema contract CI and ``slo_gate.py``
hold a report to; bump SCHEMA_VERSION when the shape changes.
"""

from __future__ import annotations

import re

SCHEMA_VERSION = 1

#: required document shape: path → type (dict/list checked by isinstance;
#: "num" accepts int|float). A path segment of "*" means every child.
_REQUIRED: list[tuple[str, type | str]] = [
    ("schemaVersion", int),
    ("scenario", dict),
    ("scenario.name", str),
    ("scenario.seed", int),
    ("target", dict),
    ("target.mode", str),
    ("target.nodes", int),
    ("arrivals", dict),
    ("arrivals.process", str),
    ("arrivals.rateTarget", "num"),
    ("arrivals.rateAchieved", "num"),
    ("arrivals.scheduled", int),
    ("arrivals.dispatched", int),
    ("arrivals.maxLagMs", "num"),
    ("perClass", dict),
    ("perClass.*", dict),
    ("perClass.*.client", dict),
    ("perClass.*.client.count", int),
    ("perClass.*.client.p50Ms", "num"),
    ("perClass.*.client.p99Ms", "num"),
    ("perClass.*.client.p999Ms", "num"),
    ("perClass.*.counts", dict),
    ("perClass.*.shedRate", "num"),
    ("perClass.*.errorRate", "num"),
    ("legs", dict),
    ("legs.*.count", int),
    ("legs.*.p50Ms", "num"),
    ("legs.*.p99Ms", "num"),
    ("rates", dict),
    ("rates.shed", "num"),
    ("rates.quota", "num"),
    ("rates.deadlineMiss", "num"),
    ("rates.hedgeFired", "num"),
    ("rates.hedgeWon", "num"),
    ("rates.breakerOpens", "num"),
    ("cache", dict),
    ("cache.hitRatio", "num"),
    ("exemplars", list),
]


def _walk(doc, segs):
    """Yield every value at ``segs`` (expanding '*')."""
    if not segs:
        yield doc
        return
    head, rest = segs[0], segs[1:]
    if not isinstance(doc, dict):
        return
    if head == "*":
        for v in doc.values():
            yield from _walk(v, rest)
    elif head in doc:
        yield from _walk(doc[head], rest)
    else:
        yield KeyError(head)


def validate_report(doc: dict) -> list[str]:
    """Schema errors, empty when the report is well-formed."""
    errors = []
    for path, want in _REQUIRED:
        segs = path.split(".")
        found = False
        for v in _walk(doc, segs):
            found = True
            if isinstance(v, KeyError):
                errors.append(f"missing: {path}")
            elif want == "num":
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    errors.append(f"{path}: want number, got {type(v).__name__}")
            elif not isinstance(v, want):
                errors.append(f"{path}: want {want.__name__}, "
                              f"got {type(v).__name__}")
        if not found and "*" not in segs:
            errors.append(f"missing: {path}")
    if doc.get("schemaVersion") != SCHEMA_VERSION:
        errors.append(f"schemaVersion: want {SCHEMA_VERSION}, "
                      f"got {doc.get('schemaVersion')}")
    return errors


# -- /metrics parsing ----------------------------------------------------

_BUCKET_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)_bucket\{(?P<labels>[^}]*)\}'
    r' (?P<cum>\d+)'
    r'(?: # \{trace_id="(?P<tid>[^"]+)"\} (?P<exval>[0-9.eE+-]+))?$')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


class PromHistogram:
    """One parsed exposition histogram series (fixed label set)."""

    def __init__(self):
        self.buckets: list[tuple[float, int]] = []   # (le, cumulative)
        self.exemplars: list[tuple[str, float]] = [] # (trace_id, seconds)

    @property
    def count(self) -> int:
        return self.buckets[-1][1] if self.buckets else 0

    def quantile(self, q: float) -> float:
        """histogram_quantile with linear interpolation inside the
        winning bucket (same estimate LogHistogram.quantile makes)."""
        total = self.count
        if total == 0:
            return 0.0
        rank = q * total
        lo, prev_cum = 0.0, 0
        for le, cum in self.buckets:
            if cum >= rank:
                if le == float("inf"):
                    return lo   # +Inf bucket: floor at last finite bound
                frac = ((rank - prev_cum) / (cum - prev_cum)
                        if cum > prev_cum else 1.0)
                return lo + frac * (le - lo)
            lo, prev_cum = le, cum
        return self.buckets[-1][0]


def parse_prom_histograms(text: str,
                          name: str) -> dict[tuple, PromHistogram]:
    """All series of histogram ``name`` (e.g. "pilosa_qos_service_seconds")
    keyed by their sorted non-``le`` label pairs. Bucket exemplars are
    collected in line order (the exporter only attaches them at p99+)."""
    out: dict[tuple, PromHistogram] = {}
    for line in text.splitlines():
        if not line.startswith(name + "_bucket"):
            continue
        m = _BUCKET_RE.match(line)
        if m is None or m.group("name") != name:
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels")))
        le = float(labels.pop("le"))
        key = tuple(sorted(labels.items()))
        h = out.setdefault(key, PromHistogram())
        h.buckets.append((le, int(m.group("cum"))))
        if m.group("tid"):
            h.exemplars.append((m.group("tid"), float(m.group("exval"))))
    for h in out.values():
        h.buckets.sort()
    return out


def tail_exemplars(hist) -> list[tuple[str, float]]:
    """(trace_id, seconds) exemplars at and above a LogHistogram's p99
    bucket — the budget-blowing queries worth resolving into profiles."""
    out = []
    p99 = hist.p99_bucket_index()
    for i in range(len(hist.counts)):
        if i < p99:
            continue
        ex = hist.exemplar(i)
        if ex is not None:
            val, tid = ex
            if tid:
                out.append((tid, val))
    out.sort(key=lambda e: -e[1])
    return out
