"""Device kernel variants for the ``hll`` representation class.

The resident forms (built host-side in sketch/store.py, uploaded and
cached by the planner like any other leaf stack):

* register stack — ``[S, 2^p]`` uint8, one HLL register file per
  shard. The unfiltered ``Count(Distinct(...))`` reduces it with a
  single register-max over the shard axis.
* packed plane — ``[S, SHARD_WIDTH]`` int32 of ``bucket | rho << 18``
  per column (0 = column absent). The FILTERED path needs per-column
  granularity: the filter tree evaluates to ``[S, W]`` word planes
  inside the same program, masks the rho entries, and a segment-max
  re-derives the registers of exactly the surviving columns — the
  "masked register gather" of the fused program, with no row set ever
  leaving the device.

All four kernels are pure traced jax so they can sit in the planner's
``KERNELS`` row for the class (the residency-pairing checker holds
every class to the full dense op set).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.sketch.hll import (BUCKET_MASK, RHO_SHIFT, _alpha,
                                   estimate_from_registers)


def hll_expand(packed, filt, p: int):
    """Masked register gather: ``[S, C]`` packed plane + ``[S, W]``
    filter words -> ``[S, 2^p]`` uint8 registers of the filtered
    columns. One segment-max over shard-offset buckets keeps the whole
    reduction a single XLA scatter-max."""
    s = packed.shape[0]
    m = 1 << p
    bits = (filt[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) \
        & jnp.uint32(1)
    mask = bits.reshape(s, -1).astype(jnp.int32)         # [S, C]
    rho = (packed >> RHO_SHIFT) * mask
    seg = ((packed & BUCKET_MASK)
           + jnp.arange(s, dtype=jnp.int32)[:, None] * m)
    regs = jax.ops.segment_max(rho.reshape(-1), seg.reshape(-1),
                               num_segments=s * m)
    # Empty segments come back as the dtype minimum; clamp to "no
    # observation" before narrowing to the uint8 register file.
    return jnp.maximum(regs, 0).astype(jnp.uint8).reshape(s, m)


def hll_reduce(regs):
    """[S, m] register stack -> [m] merged registers (register max)."""
    return jnp.max(regs, axis=0)


def hll_count(regs, p: int | None = None):
    """Device-side harmonic estimate of one register array (float32,
    with the linear-counting small-range correction traced as a
    select). The executor's host fold recomputes in float64; this
    variant exists so fully-fused consumers can keep the estimate on
    device."""
    regs = regs.astype(jnp.float32)
    m = regs.shape[-1]
    est = _alpha(m) * m * m / jnp.sum(jnp.exp2(-regs), axis=-1)
    zeros = jnp.sum((regs == 0).astype(jnp.float32), axis=-1)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    return jnp.where((est <= 2.5 * m) & (zeros > 0), linear, est)


def hll_and_count(a_regs, b_regs):
    """Estimated |A ∧ B| by inclusion-exclusion over register maxima:
    est(A) + est(B) - est(A ∪ B). Approximate (like everything HLL);
    exact-path consumers use the dense kernels instead."""
    union = jnp.maximum(a_regs, b_regs)
    return hll_count(a_regs) + hll_count(b_regs) - hll_count(union)


def hll_pair_count(a_regs, b_regs):
    """Same inclusion-exclusion estimate; registered under the
    ``pair_count`` op so the class carries the full dense op set."""
    return hll_and_count(a_regs, b_regs)


def similar_program(r: int):
    """The fused SimilarTopN program over a candidate row cube: one
    dispatch computes, for every candidate row, its overlap with the
    filter, its own cardinality, the filter cardinality, and the
    device top-k ranking of the overlap totals.

    ``cube``: [R, S, W] uint32 — every row of the field, id-ascending.
    ``filt``: [S, W] uint32 — the already-evaluated filter tree.
    Returns (order [R], inter [R], selfc [R], filtc []) — int32
    per-row totals summed over the shard axis inside the program (safe
    to ~2k full shards before int32 could saturate; the host fold
    re-widens to int64 before any cross-node addition)."""

    from pilosa_tpu.ops import bitops

    def program(cube, filt):
        inter = jnp.sum(bitops.popcount_words(cube & filt[None]),
                        axis=(1, 2))
        selfc = jnp.sum(bitops.popcount_words(cube), axis=(1, 2))
        filtc = jnp.sum(bitops.popcount_words(filt))
        _, order = jax.lax.top_k(inter, r)
        return order, inter, selfc, filtc

    return program


def np_uint8_stack(regs_list: list[np.ndarray], s_pad: int,
                   m: int) -> np.ndarray:
    """Host-side [S_pad, m] uint8 assembly with zero padding rows
    (zero registers merge as identity under register-max)."""
    mat = np.zeros((s_pad, m), dtype=np.uint8)
    for i, regs in enumerate(regs_list):
        if regs is not None:
            mat[i] = regs
    return mat
