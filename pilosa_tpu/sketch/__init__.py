"""Approximate analytics: HLL distinct-count planes + set-similarity.

Knobs follow the residency-mode pattern: a server-level setter
(``--sketch-precision`` / ``--sketch-exact-threshold`` in cli.py) with
a PILOSA_TPU_* env override that always wins — tests and operators can
flip a precision without rebuilding a server config."""

from __future__ import annotations

import os

from pilosa_tpu.sketch.hll import (BUCKET_MASK, MAX_PRECISION,  # noqa: F401
                                   MIN_PRECISION, RHO_SHIFT, DistinctValues,
                                   HLLSketch, SimPartial, error_bound,
                                   merge_all, sketch_values)

#: default HLL precision: 2^12 = 4096 registers, ~1.6% standard error,
#: 4 KiB per (shard, field) register file.
DEFAULT_PRECISION = 12

#: below this estimated cardinality the executor answers
#: Count(Distinct) EXACTLY (per-shard unique values + host union):
#: small sets are where relative HLL error is most visible and where
#: exact is cheapest.
DEFAULT_EXACT_THRESHOLD = 1024

#: default result size for SimilarTopN(...) without n=.
DEFAULT_SIMILAR_N = 10

_default_precision = DEFAULT_PRECISION
_default_exact_threshold = DEFAULT_EXACT_THRESHOLD


def _env_int(name: str) -> int | None:
    v = os.environ.get(name, "").strip()
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def validate_precision(p: int) -> int:
    if not (MIN_PRECISION <= p <= MAX_PRECISION):
        raise ValueError(
            f"sketch precision must be in [{MIN_PRECISION}, "
            f"{MAX_PRECISION}], got {p}")
    return int(p)


def set_precision(p: int) -> None:
    global _default_precision
    _default_precision = validate_precision(p)


def precision() -> int:
    env = _env_int("PILOSA_TPU_SKETCH_PRECISION")
    if env is not None and MIN_PRECISION <= env <= MAX_PRECISION:
        return env
    return _default_precision


def set_exact_threshold(n: int) -> None:
    global _default_exact_threshold
    if n < 0:
        raise ValueError("sketch exact threshold must be >= 0")
    _default_exact_threshold = int(n)


def exact_threshold() -> int:
    env = _env_int("PILOSA_TPU_SKETCH_EXACT_THRESHOLD")
    if env is not None and env >= 0:
        return env
    return _default_exact_threshold
