"""HyperLogLog host math: hashing, register planes, and the estimator.

Everything here is plain numpy — the device kernels (sketch/kernels.py)
trace the SAME arithmetic in jax, and the generative tests hold the two
to the published error bound together. The hash is splitmix64: cheap,
vectorizes to a handful of uint64 ops, and passes the avalanche tests
HLL's rho-statistics depend on (Flajolet et al. 2007 assume a uniform
hash; a weak one shows up as bias long before it shows up in unit
tests).

Register-plane packing: one int32 per column, ``bucket | rho << 18``.
rho fits 6 bits (1..33) and bucket fits 18 (precision is capped at 18),
so the packed word stays under 2^24 and a packed value of 0 reads
unambiguously as "column absent" — rho is never 0 for a present column.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

#: bit layout of a packed (bucket, rho) plane entry; precision <= 18
#: keeps bucket below the rho shift.
RHO_SHIFT = 18
BUCKET_MASK = (1 << RHO_SHIFT) - 1

MIN_PRECISION = 4
MAX_PRECISION = 18


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 arrays."""
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def bucket_rho(values_u64: np.ndarray, p: int) -> tuple[np.ndarray, np.ndarray]:
    """(bucket, rho) per value: bucket = top ``p`` hash bits, rho =
    1-based position of the first set bit in the next 32 (33 when the
    whole window is zero — with a 64-bit hash the window is wide enough
    that no large-range correction is needed)."""
    h = _splitmix64(np.asarray(values_u64, dtype=np.uint64))
    bucket = (h >> np.uint64(64 - p)).astype(np.int64)
    with np.errstate(over="ignore"):
        w32 = ((h << np.uint64(p)) >> np.uint64(32)).astype(np.uint32)
    # frexp exponent == bit length for positive ints, 0 for 0 — exact in
    # float64 for anything below 2^53, so for the whole uint32 range.
    bitlen = np.frexp(w32.astype(np.float64))[1]
    rho = (33 - bitlen).astype(np.int64)
    return bucket, rho


def pack_plane(bucket: np.ndarray, rho: np.ndarray) -> np.ndarray:
    """Pack per-column (bucket, rho) into int32 plane entries."""
    return (bucket | (rho << RHO_SHIFT)).astype(np.int32)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def error_bound(p: int) -> float:
    """Theoretical relative standard error of an HLL with 2^p registers."""
    return 1.04 / float(np.sqrt(1 << p))


def estimate_from_registers(regs: np.ndarray) -> float:
    """Harmonic-mean estimate with the small-range linear-counting
    correction (Flajolet et al. 2007, fig. 3). ``regs`` is the uint8
    register array; its length must be a power of two."""
    regs = np.asarray(regs, dtype=np.float64)
    m = regs.shape[-1]
    est = _alpha(m) * m * m / np.sum(np.exp2(-regs))
    if est <= 2.5 * m:
        zeros = int(np.count_nonzero(regs == 0))
        if zeros:
            return m * float(np.log(m / zeros))
    return float(est)


@dataclass
class HLLSketch:
    """One distinct-count partial: precision + register array. The merge
    is register-wise max — associative, commutative, idempotent — which
    is what lets partials ride the cluster aggregate wire in any fold
    order."""

    p: int
    regs: np.ndarray

    @classmethod
    def empty(cls, p: int) -> "HLLSketch":
        return cls(p=p, regs=np.zeros(1 << p, dtype=np.uint8))

    def merge(self, other: "HLLSketch") -> "HLLSketch":
        if other.p != self.p:
            raise ValueError(
                f"cannot merge HLL sketches of precision {self.p} and "
                f"{other.p}")
        return HLLSketch(p=self.p, regs=np.maximum(self.regs, other.regs))

    def estimate(self) -> float:
        return estimate_from_registers(self.regs)


def merge_all(sketches) -> HLLSketch:
    """Fold any number of same-precision sketches in one vectorized max."""
    sketches = list(sketches)
    if not sketches:
        raise ValueError("merge_all() of no sketches")
    p = sketches[0].p
    for s in sketches[1:]:
        if s.p != p:
            raise ValueError(
                f"cannot merge HLL sketches of precision {p} and {s.p}")
    regs = np.max(np.stack([s.regs for s in sketches], axis=0), axis=0)
    return HLLSketch(p=p, regs=regs.astype(np.uint8))


def sketch_values(values: np.ndarray, p: int) -> HLLSketch:
    """Host oracle: sketch an int64 value array directly (two's-
    complement reinterpretation, matching the plane builder)."""
    u = np.asarray(values, dtype=np.int64).astype(np.uint64)
    bucket, rho = bucket_rho(u, p)
    regs = np.zeros(1 << p, dtype=np.uint8)
    np.maximum.at(regs, bucket, rho.astype(np.uint8))
    return HLLSketch(p=p, regs=regs)


def registers_from_plane(packed: np.ndarray, p: int) -> np.ndarray:
    """Fold a packed (bucket|rho<<18) column plane into registers.
    Zero entries are absent columns (rho >= 1 for present ones)."""
    nz = packed[packed != 0].astype(np.int64)
    regs = np.zeros(1 << p, dtype=np.uint8)
    if len(nz):
        np.maximum.at(regs, nz & BUCKET_MASK,
                      (nz >> RHO_SHIFT).astype(np.uint8))
    return regs


@dataclass
class DistinctValues:
    """Exact-fallback partial: the sorted unique values seen by one
    node (absolute, base-adjusted). Only flows when the estimate is
    under the exact threshold, so the payload is bounded by it."""

    values: np.ndarray                 # int64, sorted unique

    @classmethod
    def empty(cls) -> "DistinctValues":
        return cls(values=np.empty(0, dtype=np.int64))

    def merge(self, other: "DistinctValues") -> "DistinctValues":
        return DistinctValues(values=np.union1d(self.values, other.values))


# ---------------------------------------------------------------------------
# set-similarity partials
# ---------------------------------------------------------------------------


@dataclass
class SimPartial:
    """One node's SimilarTopN partial: per candidate row, the overlap
    with the filter and the row's own cardinality, plus the filter's
    cardinality — everything the Jaccard/overlap scores need, and all
    of it additive across disjoint shard sets."""

    ids: np.ndarray                    # uint64 [R] candidate row ids
    overlap: np.ndarray                # int64 [R] |row ∧ filter|
    selfcnt: np.ndarray                # int64 [R] |row|
    filtcnt: int                       # |filter| over this partial's shards
    order: np.ndarray | None = field(default=None)  # device top-k, local only

    @classmethod
    def empty(cls) -> "SimPartial":
        return cls(ids=np.zeros(0, dtype=np.uint64),
                   overlap=np.zeros(0, dtype=np.int64),
                   selfcnt=np.zeros(0, dtype=np.int64), filtcnt=0)

    def merge(self, other: "SimPartial") -> "SimPartial":
        """Align by row id and sum counts; shard sets are disjoint, so
        plain addition is exact. The device top-k ordering does not
        survive a merge — the final ranking re-sorts merged totals."""
        ids = np.union1d(self.ids, other.ids)
        overlap = np.zeros(len(ids), dtype=np.int64)
        selfcnt = np.zeros(len(ids), dtype=np.int64)
        for part in (self, other):
            if len(part.ids):
                at = np.searchsorted(ids, part.ids)
                overlap[at] += part.overlap
                selfcnt[at] += part.selfcnt
        return SimPartial(ids=ids, overlap=overlap, selfcnt=selfcnt,
                          filtcnt=self.filtcnt + other.filtcnt)

    def top_pairs(self, n: int, metric: str = "jaccard"):
        """(row_id, overlap, score) triples, best-first. Ties break to
        the lower row id — the same order ``jax.lax.top_k`` produces
        over an id-ascending candidate stack, so the single-node device
        ranking and this host ranking agree bit-for-bit."""
        keep = self.overlap > 0
        ids = self.ids[keep]
        overlap = self.overlap[keep]
        selfcnt = self.selfcnt[keep]
        if metric == "jaccard":
            denom = selfcnt + self.filtcnt - overlap
            score = np.where(denom > 0, overlap / np.maximum(denom, 1), 0.0)
        elif metric == "overlap":
            score = overlap.astype(np.float64)
        else:
            raise ValueError(f"unknown similarity metric {metric!r}")
        order = np.lexsort((ids, -overlap, -score))[:n]
        return [(int(ids[i]), int(overlap[i]), float(score[i]))
                for i in order]
