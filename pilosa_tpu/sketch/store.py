"""Per-fragment HLL plane/register store.

A fragment's sketch state is DERIVED data: a packed ``bucket|rho<<18``
int32 plane over the shard's columns (built from the BSI value planes)
and the uint8 register file folded from it. Both cache on the fragment
keyed by ``(bit_depth, precision)`` and stamped with the fragment
generation, so correctness NEVER depends on the incremental hooks —
a generation mismatch rebuilds from the authoritative bit planes.

The hooks (``observe_values``, called from ``Fragment.set_value`` /
``import_values`` after the bit writes land) keep the plane current
across ingest without rebuilds: a value write is a point overwrite of
the packed plane, which is exact. The derived register file is dropped
instead of updated — registers are a running max, and an overwrite can
LOWER a column's contribution, which a max can't express.

Hook racing is resolved by generation fencing: an in-place update only
applies when the cached entry still carries the generation the
mutation started from; any interleaved writer drops the entry and the
next read rebuilds."""

from __future__ import annotations

import numpy as np

from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.core.fragment import BSI_EXISTS_BIT
from pilosa_tpu.ops import bitops
from pilosa_tpu.sketch import hll

_PLANES_ATTR = "_hll_planes"
_REGS_ATTR = "_hll_regs"


def _cache(frag, attr: str) -> dict:
    d = getattr(frag, attr, None)
    if d is None:
        d = {}
        setattr(frag, attr, d)
    return d


def _decode_stored(mat: np.ndarray, pos: np.ndarray,
                   depth: int) -> tuple[np.ndarray, np.ndarray]:
    """(u64 two's-complement, signed int64) stored values at ``pos``
    from a ``[depth+1, W]`` sign-row-first value-plane stack."""
    wi = (pos >> 5).astype(np.int64)
    sh = (pos & 31).astype(np.uint32)
    words = mat[:depth + 1][:, wi]                       # [depth+1, n]
    on = ((words >> sh) & np.uint32(1)).astype(np.uint64)
    weights = np.uint64(1) << np.arange(depth, dtype=np.uint64)
    mag = (on[1:].T * weights).sum(axis=1, dtype=np.uint64)
    sign = on[0].astype(bool)
    with np.errstate(over="ignore"):
        u = np.where(sign, (~mag) + np.uint64(1), mag)
    signed = np.where(sign, -mag.astype(np.int64), mag.astype(np.int64))
    return u, signed


def _exists_positions(frag) -> np.ndarray:
    return bitops.words_to_positions(frag.row_words(BSI_EXISTS_BIT))


def plane(frag, depth: int, p: int) -> np.ndarray:
    """Packed ``bucket | rho << 18`` int32 plane over the shard's
    columns (0 = no value); generation-cached on the fragment."""
    planes = _cache(frag, _PLANES_ATTR)
    ent = planes.get((depth, p))
    if ent is not None and ent[0] == frag.generation:
        return ent[1]
    vs = frag._build_value_stack(depth)
    gen, mat = vs[0], vs[2]
    pos = _exists_positions(frag)
    packed = np.zeros(SHARD_WIDTH, dtype=np.int32)
    if len(pos):
        u, _ = _decode_stored(mat, pos, depth)
        bucket, rho = hll.bucket_rho(u, p)
        packed[pos] = hll.pack_plane(bucket, rho)
    planes[(depth, p)] = (gen, packed)
    return packed


def registers(frag, depth: int, p: int) -> np.ndarray:
    """uint8[2^p] register file of the whole shard, derived from the
    packed plane and generation-cached separately (the unfiltered
    distinct path uploads these directly)."""
    regs_cache = _cache(frag, _REGS_ATTR)
    ent = regs_cache.get((depth, p))
    if ent is not None and ent[0] == frag.generation:
        return ent[1]
    gen = frag.generation
    regs = hll.registers_from_plane(plane(frag, depth, p), p)
    regs_cache[(depth, p)] = (gen, regs)
    return regs


def _filter_mask(packed: np.ndarray, filt_words: np.ndarray) -> np.ndarray:
    pos = np.arange(SHARD_WIDTH, dtype=np.int64)
    bits = (filt_words[pos >> 5] >> (pos & 31).astype(np.uint32)) \
        & np.uint32(1)
    return packed * bits.astype(np.int32)


def shard_sketch(frag, depth: int, p: int,
                 filt_words: np.ndarray | None = None) -> hll.HLLSketch:
    """Host oracle / remote map half: one shard's HLL sketch, optionally
    masked by a ``[W]`` uint32 filter word plane."""
    pk = plane(frag, depth, p)
    if filt_words is not None:
        pk = _filter_mask(pk, np.asarray(filt_words, dtype=np.uint32))
        regs = hll.registers_from_plane(pk, p)
    else:
        regs = registers(frag, depth, p)
    return hll.HLLSketch(p=p, regs=regs.copy())


def shard_distinct(frag, depth: int,
                   filt_words: np.ndarray | None = None) -> np.ndarray:
    """Exact fallback map half: the shard's sorted unique STORED
    (base-relative, signed) values; the executor adds the BSI base."""
    pos = _exists_positions(frag)
    if filt_words is not None and len(pos):
        fw = np.asarray(filt_words, dtype=np.uint32)
        keep = ((fw[pos >> 5] >> (pos & 31).astype(np.uint32))
                & np.uint32(1)).astype(bool)
        pos = pos[keep]
    if not len(pos):
        return np.empty(0, dtype=np.int64)
    vs = frag._build_value_stack(depth)
    _, signed = _decode_stored(vs[2], pos, depth)
    return np.unique(signed)


def observe_values(frag, local_pos: np.ndarray, values: np.ndarray,
                   gen_before: int, gen_after: int) -> None:
    """Incremental ingest hook: point-overwrite every cached plane at
    the written columns and drop the derived register files. Fenced by
    generation — entries another writer got to first are dropped, not
    updated (see module docstring)."""
    planes = getattr(frag, _PLANES_ATTR, None)
    if planes:
        vals = np.asarray(values, dtype=np.int64)
        pos = np.asarray(local_pos, dtype=np.int64)
        u = vals.astype(np.uint64)
        for (depth, p), (gen, packed) in list(planes.items()):
            if gen != gen_before:
                planes.pop((depth, p), None)
                continue
            bucket, rho = hll.bucket_rho(u, p)
            packed[pos] = hll.pack_plane(bucket, rho)
            planes[(depth, p)] = (gen_after, packed)
    regs_cache = getattr(frag, _REGS_ATTR, None)
    if regs_cache:
        regs_cache.clear()


def invalidate(frag) -> None:
    """Drop all sketch state (bulk clears, anything not expressible as
    a point overwrite)."""
    for attr in (_PLANES_ATTR, _REGS_ATTR):
        d = getattr(frag, attr, None)
        if d:
            d.clear()
