"""Build-time-ish constants.

The reference selects its shard width with build tags
(``shardwidth/20.go:19`` picks 2^20 among 2^16..2^32). We take it from the
environment once at import time — the shard width shapes every compiled
kernel, so it must be fixed for the life of the process, exactly like a
build tag.
"""

import os

# Reference: fragment.go:51-53 (ShardWidth = 1 << shardWidthExponent),
# shardwidth/20.go:19 (default exponent 20).
_DEFAULT_EXPONENT = 20

# Capped at 30 (reference goes to 32): count kernels accumulate per-row in
# int32, which holds up to 2^31-1 set bits — one 2^30-bit row can never
# overflow it, 2^31+ could.
_exp = int(os.environ.get("PILOSA_TPU_SHARD_WIDTH_EXP",
                          str(_DEFAULT_EXPONENT)))
if not (16 <= _exp <= 30):
    raise ValueError("PILOSA_TPU_SHARD_WIDTH_EXP must be in [16, 30]")

#: Number of columns per shard. Reference: fragment.go:53.
SHARD_WIDTH = 1 << _exp

#: Bits per storage word. TPUs have no native uint64 lanes, so the dense
#: bitmap word is uint32 (2x u32 replaces the reference's uint64 containers,
#: roaring/roaring.go:55).
WORD_BITS = 32

#: uint32 words per shard row (the dense on-device row block).
WORDS_PER_SHARD = SHARD_WIDTH // WORD_BITS

#: Words per 2^16-bit "container span" — retained only for roaring
#: import/export compatibility (reference container width, roaring.go:55).
CONTAINER_BITS = 1 << 16
WORDS_PER_CONTAINER = CONTAINER_BITS // WORD_BITS

#: A host-side row representation flips from sorted-positions ("sparse") to
#: dense words once the position array (uint64 per entry) would outweigh the
#: dense block (4*WORDS_PER_SHARD bytes): at WORDS_PER_SHARD/2 entries.
DENSE_CUTOFF = WORDS_PER_SHARD // 2

#: Snapshot the fragment once this many WAL ops accumulate.
#: Reference: MaxOpN = 10,000 (fragment.go:84).
MAX_OP_N = 10_000

#: Default TopN cache size kept for API compatibility (field.go:48). Our
#: TopN is exact (device top_k over the row-popcount vector) so this only
#: bounds reported candidates, never accuracy.
DEFAULT_CACHE_SIZE = 50_000

#: Cluster hash partitions. Reference: defaultPartitionN (cluster.go:44).
DEFAULT_PARTITION_N = 256

#: Rows per checksum block for anti-entropy. Reference: HashBlockSize
#: (fragment.go:81).
HASH_BLOCK_SIZE = 100

#: Reference time format (pilosa.go TimeFormat "2006-01-02T15:04").
TIME_FORMAT = "%Y-%m-%dT%H:%M"

#: Existence-tracking field name. Reference: existenceFieldName (holder.go:46).
EXISTENCE_FIELD_NAME = "_exists"


def shard_width_exponent() -> int:
    return SHARD_WIDTH.bit_length() - 1
