"""Transport-level partition fault injection.

A :class:`PartitionFaults` table hangs off an internal client
(``HTTPInternalClient.faults`` / ``LocalClient.pair_faults``) and is
consulted before any request leaves this node for a peer. Two modes:

- ``drop``: the link is cut — the call fails immediately with
  ``ConnectionError``, exactly like a refused TCP connect.
- ``timeout``: the link is black-holed — the call blocks for
  ``delay_s`` (bounded; default one probe timeout) and then fails
  with ``ConnectionError``, like a SYN that never answers.

Faults are *outbound and per-direction*: blocking A→B says nothing
about B→A, which is what makes asymmetric-partition drills possible.
A symmetric split is just both sides configured (the harness and the
chaos driver do that for you).

Chaos-gated ``POST /internal/fault`` drives the HTTP table; the
``LocalCluster`` harness drives the in-process pair table directly.
"""

from __future__ import annotations

import threading
import time

#: ceiling for the ``timeout`` mode's sleep so a fat-fingered delayMs
#: can never wedge a server thread for minutes.
MAX_TIMEOUT_DELAY_S = 10.0


class PartitionFaults:
    """Thread-safe {peer_id: (mode, delay_s)} outbound fault table."""

    MODES = ("drop", "timeout")

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: dict[str, tuple[str, float]] = {}

    def set_fault(self, peer_id: str, mode: str = "drop",
                  delay_s: float = 0.0) -> None:
        if mode not in self.MODES:
            raise ValueError(f"unknown partition fault mode {mode!r} "
                             f"(want one of {self.MODES})")
        delay_s = min(max(0.0, float(delay_s)), MAX_TIMEOUT_DELAY_S)
        with self._lock:
            self._faults[peer_id] = (mode, delay_s)

    def clear(self, peer_id: str | None = None) -> None:
        """Heal one link (``peer_id``) or every link (``None``)."""
        with self._lock:
            if peer_id is None:
                self._faults.clear()
            else:
                self._faults.pop(peer_id, None)

    def blocked(self, peer_id: str) -> bool:
        with self._lock:
            return peer_id in self._faults

    def check(self, peer_id: str) -> None:
        """Raise ``ConnectionError`` if the link to ``peer_id`` is
        faulted, honoring the mode's delay first."""
        with self._lock:
            fault = self._faults.get(peer_id)
        if fault is None:
            return
        mode, delay_s = fault
        if mode == "timeout" and delay_s > 0.0:
            time.sleep(delay_s)
        raise ConnectionError(
            f"partition fault ({mode}): link to {peer_id} is down")

    def snapshot(self) -> dict:
        with self._lock:
            return {peer: {"mode": mode, "delayS": delay_s}
                    for peer, (mode, delay_s) in self._faults.items()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._faults)
