"""Node-to-node transport interface.

Reference: client.go (InternalClient interface :46-74) with the HTTP impl
in http/client.go:37. Three implementations here:

- ``NopClient`` — standalone nodes (reference nopInternalClient);
- ``LocalClient`` — in-process registry of peer servers, the transport of
  the multi-node test harness (analog of test.MustRunCluster's real-HTTP
  in-process nodes, test/pilosa.go:343 — we cross a serialization
  boundary by shipping PQL strings + JSON-able payloads, no sockets);
- the HTTP impl lives in pilosa_tpu.server (once the REST layer exists).
"""

from __future__ import annotations

from typing import Any, Protocol

from pilosa_tpu.cluster.node import Node


class InternalClient(Protocol):
    """What the executor/cluster need from a peer (client.go:46)."""

    def query_node(self, node: Node, index: str, query: str,
                   shards: list[int] | None, remote: bool) -> list[Any]:
        """Execute PQL on a peer (http: POST /index/{i}/query?remote=true)."""
        ...

    def query_node_meta(self, node: Node, index: str, query: str,
                        shards: list[int] | None,
                        remote: bool) -> tuple[list[Any], dict]:
        """query_node plus the peer's shard-epoch vector (read on the
        peer BEFORE its leg executed) — the coordinator result cache's
        cross-node freshness stamp."""
        ...

    def fragment_blocks(self, node: Node, index: str, field: str, view: str,
                        shard: int) -> dict[int, bytes]:
        """Checksum blocks of a peer fragment (anti-entropy)."""
        ...

    def fragment_block_data(self, node: Node, index: str, field: str,
                            view: str, shard: int, block: int):
        """(row_ids, column_ids) of one block on a peer."""
        ...

    def import_bits(self, node: Node, index: str, field: str, view: str,
                    shard: int, rows: list[int], cols: list[int],
                    clear: bool) -> None:
        """Push bits into one specific fragment of a peer (the diff-push
        half of anti-entropy, fragment.go:2986)."""
        ...

    def translate_keys(self, node: Node, index: str, field: str | None,
                       keys: list[str]) -> list[int]:
        """Allocate/look up keys on a peer — the coordinator-primary RPC
        (reference http/translator.go)."""
        ...

    def translate_entries(self, node: Node, index: str, field: str | None,
                          after_id: int) -> list[tuple[int, str]]:
        """Entry stream for replica catch-up (translate.go:93)."""
        ...

    def post_schema(self, node: Node, schema: list[dict]) -> None:
        """Push a whole schema to a peer (ApplySchema fan-out,
        api.go:747)."""
        ...


class NopClient:
    """Standalone stub: remote calls are errors (clusters of one never
    issue them)."""

    def query_node(self, node, index, query, shards, remote):
        raise RuntimeError("nop client cannot query remote nodes")

    def query_node_meta(self, node, index, query, shards, remote):
        raise RuntimeError("nop client cannot query remote nodes")

    def fragment_blocks(self, node, index, field, view, shard):
        raise RuntimeError("nop client cannot reach remote nodes")

    def fragment_block_data(self, node, index, field, view, shard, block):
        raise RuntimeError("nop client cannot reach remote nodes")

    def import_bits(self, node, index, field, view, shard, rows, cols, clear):
        raise RuntimeError("nop client cannot reach remote nodes")

    def translate_keys(self, node, index, field, keys):
        raise RuntimeError("nop client cannot reach remote nodes")

    def translate_entries(self, node, index, field, after_id):
        raise RuntimeError("nop client cannot reach remote nodes")


class LocalClient:
    """In-process peer registry: node id -> server-like object exposing
    ``handle_query`` / ``handle_fragment_*`` (pilosa_tpu.cluster.harness
    wires these to real executors)."""

    def __init__(self):
        self.peers: dict[str, Any] = {}
        #: node ids currently "down" (fault injection — the pumba pause
        #: analog, internal/clustertests/cluster_test.go:69).
        self.down: set[str] = set()
        #: node id -> injected per-query latency in seconds (the
        #: slow-peer / gray-failure fault: alive, just sick).
        self.slow: dict[str, float] = {}
        #: optional BreakerRegistry, same contract as the HTTP client's.
        self.breakers = None
        #: directed partition faults: (src_id, dst_id) -> mode ("drop" |
        #: "timeout"). Unlike ``down`` (a node dead for EVERYONE), a
        #: pair fault cuts one link in one direction — the asymmetric-
        #: partition fault the SWIM indirect probes exist for. Enforced
        #: by the per-node bound views ``bind()`` hands out; the shared
        #: unbound client has no source identity and bypasses it.
        self.pair_faults: dict[tuple[str, str], str] = {}

    def bind(self, src_id: str) -> "BoundLocalClient":
        """A view of this client with a source identity, so outbound
        calls can honor (src, dst) pair faults."""
        return BoundLocalClient(self, src_id)

    def set_pair_fault(self, src_id: str, dst_id: str,
                       mode: str = "drop") -> None:
        if mode not in ("drop", "timeout"):
            raise ValueError(f"unknown pair fault mode {mode!r}")
        self.pair_faults[(src_id, dst_id)] = mode

    def clear_pair_faults(self) -> None:
        self.pair_faults.clear()

    def check_pair(self, src_id: str, dst_id: str) -> None:
        """Raise ConnectionError when the src->dst link is faulted.
        In-process "timeout" doesn't sleep (tests stay fast) — both
        modes surface as the ConnectionError a blown socket would."""
        mode = self.pair_faults.get((src_id, dst_id))
        if mode is not None:
            raise ConnectionError(
                f"partition fault ({mode}): link {src_id}->{dst_id} is down")

    def register(self, node_id: str, server: Any) -> None:
        self.peers[node_id] = server

    def _peer(self, node: Node):
        if node.id in self.down:
            raise ConnectionError(f"node {node.id} is down")
        peer = self.peers.get(node.id)
        if peer is None:
            raise ConnectionError(f"unknown node {node.id}")
        return peer

    def query_node(self, node, index, query, shards, remote=True):
        return self.query_node_meta(node, index, query, shards, remote)[0]

    def query_node_meta(self, node, index, query, shards, remote=True):
        if self.breakers is None:
            return self._query_node(node, index, query, shards, remote)
        self.breakers.check(node.id)
        # Mirror the HTTP client's bookkeeping exactly: EVERY outcome
        # resolves the breaker (a claimed half-open probe left
        # unresolved would fast-fail the peer forever). ConnectionError
        # (down peer, slow peer that blew the deadline) is a failure;
        # our own deadline expiring before/while dispatching proves
        # nothing, so it releases the probe without an outcome; any
        # other exception is an ALIVE peer answering with an
        # application error (query RuntimeError, ShardCorruptError,
        # QueryShedError) — a success, same as the HTTP path's 503.
        from pilosa_tpu.qos.deadline import DeadlineExceededError
        try:
            result = self._query_node(node, index, query, shards, remote)
        except ConnectionError:
            self.breakers.record_failure(node.id)
            raise
        except DeadlineExceededError:
            self.breakers.abort(node.id)
            raise
        except BaseException:
            self.breakers.record_success(node.id)
            raise
        self.breakers.record_success(node.id)
        return result

    def _query_node(self, node, index, query, shards, remote=True):
        """Returns (results, shard-epoch vector) — the serialization
        boundary carries the peer's epochs like the HTTP wire does."""
        peer = self._peer(node)
        handle = getattr(peer, "handle_query_meta", None)
        if handle is None:  # bare test double: no epoch reporting
            handle = lambda *a: (peer.handle_query(*a), {})  # noqa: E731
        # Cross the serialization boundary the way the HTTP transport
        # does (X-Deadline, server/httpclient.py): don't dispatch an
        # already-expired query, and hand the peer a RE-DERIVED token
        # (absolute expiry only — the coordinator's local cancel flag
        # doesn't travel over the wire either).
        from pilosa_tpu.qos import deadline as qos_deadline
        dl = qos_deadline.current_deadline()
        delay = self.slow.get(node.id, 0.0)
        if delay > 0.0:
            # The sick-peer fault: the request "takes" this long. With
            # a deadline in force this turns into the same timeout the
            # HTTP transport surfaces (ConnectionError), exercising the
            # breaker/hedge path; without one it's just slow.
            import time as _time
            if dl is not None:
                rem = dl.remaining()
                if rem is not None and rem <= delay:
                    _time.sleep(max(0.0, rem))
                    raise ConnectionError(
                        f"node {node.id} timed out (slow-peer fault)")
            _time.sleep(delay)
        if dl is None:
            return handle(index, query, shards, remote)
        dl.check()
        token = qos_deadline.set_current_deadline(dl.rederive())
        try:
            return handle(index, query, shards, remote)
        finally:
            qos_deadline.reset_current_deadline(token)

    def fragment_blocks(self, node, index, field, view, shard):
        return self._peer(node).handle_fragment_blocks(index, field, view, shard)

    def fragment_block_data(self, node, index, field, view, shard, block):
        return self._peer(node).handle_fragment_block_data(
            index, field, view, shard, block)

    def import_bits(self, node, index, field, view, shard, rows, cols,
                    clear=False):
        return self._peer(node).handle_import(index, field, view, shard,
                                              rows, cols, clear)

    def send_message(self, node, message: dict):
        """Control-plane broadcast (reference /internal/cluster/message,
        broadcast.go:55-72)."""
        return self._peer(node).handle_message(message)

    def send_import_roaring(self, node, index, field, shard, data: bytes,
                            clear=False):
        return self._peer(node).handle_import_roaring(index, field, shard,
                                                      data, clear)

    def send_import_stream(self, node, reqs, chunked=False, qos_class=None):
        """PTS1 bulk-import stream to a peer — the one wire for large
        data movement (user bulk loads AND resize fragment migration,
        which rides it with qos_class="internal"). Returns the number of
        applied requests (the applied prefix, for resume)."""
        return self._peer(node).handle_import_stream(list(reqs))

    def probe(self, node) -> None:
        """Liveness probe (the /version check of confirmNodeDown)."""
        self._peer(node)

    def indirect_probe(self, via, target) -> bool:
        """SWIM indirect confirmation: ask intermediary ``via`` whether
        IT can reach ``target``. Models the two hops the HTTP path
        takes: us->via (via must be up), then via->target (via's own
        link faults and target's liveness apply)."""
        try:
            self._peer(via)
            self.check_pair(via.id, target.id)
            self._peer(target)
        except ConnectionError:
            return False
        return True

    def send_import(self, node, index, field, shard, rows=None, cols=None,
                    values=None, timestamps=None, clear=False):
        """Field-level import routed to an owning node (api.go:967)."""
        return self._peer(node).handle_import_request(
            index, field, rows=rows, cols=cols, values=values,
            timestamps=timestamps, clear=clear)

    def translate_keys(self, node, index, field, keys):
        return self._peer(node).handle_translate_keys(index, field, keys)

    def translate_entries(self, node, index, field, after_id):
        return self._peer(node).handle_translate_entries(index, field,
                                                         after_id)

    def schema(self, node) -> list[dict]:
        return self._peer(node).handle_schema()

    def post_schema(self, node, schema: list[dict]) -> None:
        self._peer(node).apply_schema(schema)

    def nodes(self, node) -> list[dict]:
        return self._peer(node).handle_nodes()

    def backup_keys(self, node) -> list:
        """Fragment keys a peer holds durable files for (backup
        coordinator enumeration)."""
        return self._peer(node).handle_backup_keys()

    def backup_fragment(self, node, index, field, view, shard) -> dict:
        """One fragment's verified (snap, wal) pair from a peer; raises
        ShardCorruptError when that copy is unhealthy."""
        return self._peer(node).handle_backup_fragment(index, field, view,
                                                       shard)

    def attr_blocks(self, node, index, field):
        return self._peer(node).handle_attr_blocks(index, field)

    def attr_block_data(self, node, index, field, block):
        return self._peer(node).handle_attr_block_data(index, field, block)


class BoundLocalClient:
    """A LocalClient view carrying a source node identity. Every method
    whose first positional argument is a peer Node first checks the
    (src, dst) pair-fault table, then delegates — so the harness can
    cut individual links (symmetric or one-way) while the shared
    registry/down/slow state stays in one place.

    For ``indirect_probe(via, target)`` the checked link is src->via
    (reaching the INTERMEDIARY); the via->target hop is the base
    client's job — that is exactly what makes an asymmetric partition
    survivable: src can't see target, but via can."""

    def __init__(self, base: LocalClient, src_id: str):
        self._base = base
        self.src_id = src_id

    def __getattr__(self, name):
        attr = getattr(self._base, name)
        if not callable(attr):
            return attr

        def bound(*args, **kwargs):
            if args and isinstance(args[0], Node):
                self._base.check_pair(self.src_id, args[0].id)
            return attr(*args, **kwargs)

        return bound

    def __repr__(self):
        return f"BoundLocalClient({self.src_id!r})"
