"""Anti-entropy: replica repair by checksummed block diff + majority merge.

Reference: holder.go holderSyncer.SyncHolder (:911) → fragment syncer
(fragment.go:2861) → mergeBlock (fragment.go:1875-1993). Blocks are
100-row checksums (Fragment.checksum_blocks); differing blocks are merged
bit-by-bit with majority consensus (ties → set) and diffs pushed back to
replicas.

The k-way roaring iterators of the reference become numpy set ops over
position-encoded (row*SHARD_WIDTH + col) pair arrays — same consensus,
vectorized.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.config import HASH_BLOCK_SIZE, SHARD_WIDTH
from pilosa_tpu.core.holder import Holder


def merge_block(local_pairs: tuple[np.ndarray, np.ndarray],
                remote_pairs: list[tuple[np.ndarray, np.ndarray]],
                include_local: bool = True):
    """Consensus-merge one block. Pairs are (row_ids, ABSOLUTE column_ids).

    Returns (local_sets, local_clears, remote_diffs) where remote_diffs is
    a list of (sets, clears) per remote node; each sets/clears is a
    (rows, cols) pair. majorityN = (n+1)//2 over all participants — an
    even split keeps the bit (fragment.go:1917).

    ``include_local=False`` excludes the local copy from the vote (its
    diffs are still computed): the scrubber uses this to repair a
    fragment whose local data is quarantined-corrupt — evidence of
    corruption means the local bits must not outvote healthy replicas.
    """
    all_pairs = [local_pairs] + list(remote_pairs)
    n = len(all_pairs)
    majority_n = (n + 1) // 2

    # Structured (row, col) pairs — overflow-proof for the full uint64
    # row/column domain (no positional packing).
    pair_dt = np.dtype([("r", "<u8"), ("c", "<u8")])

    def encode(rows, cols):
        a = np.empty(len(rows), dtype=pair_dt)
        a["r"] = np.asarray(rows, dtype=np.uint64)
        a["c"] = np.asarray(cols, dtype=np.uint64)
        return np.unique(a)

    encoded = [encode(r, c) for r, c in all_pairs]
    if not any(len(e) for e in encoded):
        empty = (np.empty(0, np.uint64), np.empty(0, np.uint64))
        return (empty, empty), [(empty, empty) for _ in remote_pairs]
    universe = np.unique(np.concatenate(encoded))

    presence = np.zeros((n, len(universe)), dtype=np.int32)
    for i, e in enumerate(encoded):
        if len(e):
            idx = np.searchsorted(universe, e)
            presence[i, idx] = 1
    if include_local:
        keep = presence.sum(axis=0) >= majority_n
    else:
        majority_n = (len(remote_pairs) + 1) // 2
        keep = presence[1:].sum(axis=0) >= max(majority_n, 1)

    def decode(mask):
        sel = universe[mask]
        return (sel["r"].astype(np.uint64), sel["c"].astype(np.uint64))

    def diffs(i):
        has = presence[i].astype(bool)
        return decode(keep & ~has), decode(~keep & has)

    local_sets, local_clears = diffs(0)
    remote = [diffs(i + 1) for i in range(len(remote_pairs))]
    return (local_sets, local_clears), remote


class HolderSyncer:
    """Reference holderSyncer (holder.go:895): walk the schema, sync every
    owned fragment against its replicas."""

    def __init__(self, holder: Holder, cluster, client):
        self.holder = holder
        self.cluster = cluster
        self.client = client

    def sync_holder(self) -> int:
        """Returns the number of fragments + attr stores repaired."""
        repaired = 0
        for index_name in self.holder.index_names():
            idx = self.holder.index(index_name)
            # Attr stores first, like the reference's syncIndex/syncField
            # order (holder.go:975-1067): column attrs, then per-field
            # row attrs — attrs replicate everywhere, not per shard.
            if self._sync_attrs(index_name, None, idx.column_attr_store):
                repaired += 1
            for field_name, f in sorted(idx.fields.items()):
                if self._sync_attrs(index_name, field_name, f.row_attr_store):
                    repaired += 1
                for view_name, v in sorted(f.views.items()):
                    for shard in sorted(v.fragments):
                        if not self.cluster.owns_shard(
                                self.cluster.local_id, index_name, shard):
                            continue
                        if self._sync_fragment(index_name, field_name,
                                               view_name, shard):
                            repaired += 1
        return repaired

    def _sync_attrs(self, index_name: str, field_name: str | None,
                    store) -> bool:
        """Pull-repair one attr store against every live peer: blocks
        whose checksums differ are fetched and merged locally (reference
        syncIndex -> AttrStore.Blocks -> ColumnAttrDiff -> SetBulkAttrs,
        holder.go:975-1067). Each node repairs itself; mutual convergence
        comes from every node running its own syncer."""
        changed = False
        mine = store.blocks()
        for node in self.cluster.nodes:
            if node.id == self.cluster.local_id or node.state == "DOWN":
                continue
            try:
                theirs = self.client.attr_blocks(node, index_name, field_name)
            except (ConnectionError, LookupError):
                continue
            for b in store.diff_blocks(mine, theirs):
                try:
                    data = self.client.attr_block_data(node, index_name,
                                                       field_name, b)
                except (ConnectionError, LookupError):
                    continue
                if data:
                    store.set_bulk_attrs(data)
                    changed = True
            if changed:
                mine = store.blocks()
        return changed

    def _replicas(self, index_name: str, shard: int):
        return [n for n in self.cluster.shard_nodes(index_name, shard)
                if n.id != self.cluster.local_id and n.state != "DOWN"]

    def _sync_fragment(self, index_name, field_name, view_name, shard) -> bool:
        frag = self.holder.fragment(index_name, field_name, view_name, shard)
        if frag is None:
            return False
        replicas = self._replicas(index_name, shard)
        if not replicas:
            return False

        local_blocks = frag.checksum_blocks()
        peer_blocks = []
        live = []
        for node in replicas:
            try:
                peer_blocks.append(self.client.fragment_blocks(
                    node, index_name, field_name, view_name, shard))
                live.append(node)
            except LookupError:
                # Replica lacks the fragment entirely: empty block set —
                # every local block diffs and gets pushed.
                peer_blocks.append({})
                live.append(node)
            except ConnectionError:
                continue
        if not live:
            return False

        block_ids = set(local_blocks)
        for pb in peer_blocks:
            block_ids |= set(pb)
        idx = self.holder.index(index_name)
        epoch = idx.epoch if idx is not None else None
        changed = False
        for b in sorted(block_ids):
            if all(pb.get(b) == local_blocks.get(b) for pb in peer_blocks):
                continue
            # Read-merge-write guard: a write that lands between reading
            # this block and applying the merged plan would be UNDONE by
            # the plan (a freshly cleared bit still in the stale read
            # gets resurrected on every copy). Snapshot the index's
            # mutation epoch with the read; a bump during the merge
            # invalidates the plan for this block — next pass replans.
            e0 = epoch.value if epoch is not None else None
            local_pairs = frag.block_data(b)
            remote_pairs, reachable = [], []
            empty = (np.empty(0, np.uint64), np.empty(0, np.uint64))
            for node in live:
                try:
                    remote_pairs.append(self.client.fragment_block_data(
                        node, index_name, field_name, view_name, shard, b))
                    reachable.append(node)
                except LookupError:
                    remote_pairs.append(empty)
                    reachable.append(node)
                except ConnectionError:
                    continue  # peer died mid-sync: merge with the rest
            if not reachable:
                continue
            (lsets, lclears), remote_diffs = merge_block(local_pairs, remote_pairs)
            if e0 is not None and epoch.value != e0:
                continue  # a write raced this merge: stale plan, replan
            if len(lsets[0]):
                frag.bulk_import(lsets[0].tolist(), lsets[1].tolist())
                changed = True
            if len(lclears[0]):
                frag.bulk_import(lclears[0].tolist(), lclears[1].tolist(),
                                 clear=True)
                changed = True
            for node, (rsets, rclears) in zip(reachable, remote_diffs):
                try:
                    if len(rsets[0]):
                        self.client.import_bits(
                            node, index_name, field_name, view_name, shard,
                            rsets[0].tolist(), rsets[1].tolist(), False)
                        changed = True
                    if len(rclears[0]):
                        self.client.import_bits(
                            node, index_name, field_name, view_name, shard,
                            rclears[0].tolist(), rclears[1].tolist(), True)
                        changed = True
                except (ConnectionError, LookupError):
                    continue  # next sync pass retries this peer
        return changed
