"""Elastic resize: add/remove nodes while the ring keeps serving.

Reference: cluster.go resize machinery — `diff` (:745) computes
added/removed nodes, `fragSources` (:784-868) computes which node streams
which fragment to whom, `resizeJob` (:1447-1561) distributes
ResizeInstructions to nodes, `followResizeInstruction` (:1297-1411) makes
each node fetch its missing fragments from source nodes; one job at a
time; abortable (api.go:1250).

Unlike the reference (which closes the cluster behind a ring-wide
RESIZING state for the whole job), this resize SERVES THROUGHOUT:

- The old ring stays authoritative — ``Cluster.nodes`` doesn't change
  until the single commit broadcast at the end, so reads never route to
  a partial copy and any failure/abort needs no rollback at all.
- A ``resize-begin`` broadcast installs a MigrationTable
  (cluster/migration.py) on every member, after which writes dual-apply
  to each shard's future owners while fragments move.
- Fragments travel over the PTS1 import-stream wire (the same path as
  bulk ingest: chunked resume-from-applied-prefix, WAL group-commit,
  IngestGate byte budget, QoS internal class) — the coordinator's
  instruction still goes to the TARGET, which relays a synchronous
  ``resize-push`` to each source; the source streams.
- After the bulk copy, the target runs a per-shard directed catch-up
  sync against the source (block-checksum diff applying both sets and
  clears, guarded by the shard-epoch read-recheck loop), bumps the
  shard epoch, and announces the shard cut over — from then on the new
  owner is also an eligible READ leg (replica-aware read scaling).

Instructions travel as control-plane messages ("resize-instruction",
"resize-push", "resize-shard-cutover", …) so the same flow works over
the in-process LocalClient and real HTTP.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import asdict, dataclass

from pilosa_tpu.cluster.cluster import (
    STATE_NORMAL,
    STATE_RESIZING,
    STATE_STARTING,
    Cluster,
)
from pilosa_tpu.cluster.event import EVENT_UPDATE
from pilosa_tpu.cluster.node import URI, Node

#: active jobs by id, so completion ACKs arriving as control-plane
#: messages can find their job (reference: the coordinator's resizeJob
#: map, cluster.go:1413).
_JOBS: dict[str, "ResizeJob"] = {}
_JOBS_LOCK = threading.Lock()
_JOB_SEQ = itertools.count(1)


def deliver_completion(message: dict) -> None:
    """Route a resize-instruction-complete message to its job
    (reference ResizeInstructionComplete, cluster.go:1413-1438)."""
    with _JOBS_LOCK:
        job = _JOBS.get(message.get("job", ""))
    if job is not None:
        job.complete(message.get("node", ""), message.get("error"))


def deliver_dual_write_failed(message: dict) -> None:
    """A member could not apply a write to a migration target: the new
    copy just diverged, so the coordinator must fail that target — the
    commit would otherwise route reads to a copy missing writes. The
    old ring was never not-authoritative, so failing is free."""
    with _JOBS_LOCK:
        job = _JOBS.get(message.get("job", ""))
    if job is not None:
        job.fail_target(
            message.get("node", ""),
            f"dual write failed: {message.get('error', 'unknown')}")


def deliver_cutover(message: dict, cluster: Cluster | None = None) -> None:
    """A target finished catch-up for one shard: record it on the
    coordinator's job (for /debug/resize) and on the local migration
    table (the shard's new owner becomes an eligible read leg)."""
    with _JOBS_LOCK:
        job = _JOBS.get(message.get("job", ""))
    if job is not None:
        job.note_cutover(message.get("index", ""),
                         int(message.get("shard", -1)),
                         message.get("node", ""))
    if cluster is not None:
        mig = getattr(cluster, "migration", None)
        if mig is not None and mig.job_id == message.get("job"):
            mig.mark_cutover(message["index"], int(message["shard"]))


def apply_resize_begin(cluster: Cluster, message: dict) -> None:
    """Peer half of the serve-through handshake: install the migration
    table so every write fanned out by THIS member also lands on the
    shard's future owners. Replaces any stale table — one job at a time
    is enforced at the coordinator's resize gate, so a new begin means
    the previous job is dead. A begin carrying a STALE fencing token is
    from a coordinator deposed by a takeover/commit we already adopted:
    installing its table would dual-apply writes toward a ring that
    will never commit, so it is rejected outright."""
    if not cluster.check_fencing_token(message):
        return
    from pilosa_tpu.cluster.migration import MigrationTable
    cluster.migration = MigrationTable.from_message(cluster, message)


def apply_resize_end(cluster: Cluster, message: dict) -> None:
    """Drop the migration table for an aborted/failed job. Always safe:
    the old ring never stopped being authoritative, so partially
    migrated shards simply keep routing to their old owners."""
    mig = getattr(cluster, "migration", None)
    if mig is not None and mig.job_id == message.get("job"):
        cluster.migration = None


def handle_resize_instruction(holder, client, cluster: Cluster,
                              message: dict, local_id: str) -> None:
    """Target-side entry point. When the instruction carries a job id,
    apply it in the BACKGROUND and ACK the coordinator with an explicit
    resize-instruction-complete message — the dispatch RPC returns
    immediately, so a large fragment stream can take arbitrarily longer
    than any HTTP client timeout (reference followResizeInstruction runs
    in a goroutine and POSTs ResizeInstructionComplete back,
    cluster.go:1297-1315). Without a job id (direct/legacy callers) the
    apply stays synchronous."""
    job_id = message.get("job")
    if job_id is None:
        apply_resize_instruction(holder, client, cluster,
                                 message["sources"],
                                 schema=message.get("schema"),
                                 local_id=local_id)
        return
    coord = message.get("coordinator") or {}

    def work():
        err = None
        try:
            apply_resize_instruction(holder, client, cluster,
                                     message["sources"],
                                     schema=message.get("schema"),
                                     local_id=local_id, job_id=job_id,
                                     coordinator=coord)
        except Exception as e:  # noqa: BLE001 — every failure must ACK
            err = f"{type(e).__name__}: {e}"
        node = cluster.node_by_id(coord.get("id", ""))
        if node is None and coord.get("uri"):
            node = Node.from_json(coord)
        if node is None:
            return
        try:
            client.send_message(node, {"type": "resize-instruction-complete",
                                       "job": job_id, "node": local_id,
                                       "error": err})
        except (ConnectionError, RuntimeError):
            pass  # coordinator's ACK deadline treats us as failed

    threading.Thread(target=work, name="resize-apply", daemon=True).start()


@dataclass
class ResizeSource:
    """One fragment a node must fetch (reference ResizeSource).

    Carries the source's address (host/port) so a JOINING node — which
    has no topology yet — can fetch without resolving ids against a
    cluster it hasn't learned."""

    source_node: str
    index: str
    field: str
    view: str
    shard: int
    source_host: str = ""
    source_port: int = 0
    source_scheme: str = "http"


def fragment_sources(old: Cluster, new: Cluster, schema_fragments) -> dict[str, list[ResizeSource]]:
    """Pure placement diff: target node id -> fragments to fetch.

    A node in the NEW owner set that wasn't an OLD owner fetches from an
    old owner that SURVIVES into the new view (reference fragSources
    cluster.go:784-868 skips removed nodes at :823-826) — a node being
    removed is usually dead, so it must never be chosen as a source.
    Raises ValueError when a fragment has no surviving replica (the
    reference's "not enough data to perform resize")."""
    out: dict[str, list[ResizeSource]] = {}
    new_ids = {n.id for n in new.nodes}
    for index, field, view, shard in schema_fragments:
        old_owners = old.shard_nodes(index, shard)
        if not old_owners:
            continue
        old_ids = [n.id for n in old_owners]
        new_owners = [n.id for n in new.shard_nodes(index, shard)]
        surviving = [n for n in old_owners if n.id in new_ids]
        for target in new_owners:
            if target in old_ids:
                continue
            if not surviving:
                raise ValueError(
                    f"resize: fragment {index}/{field}/{view}/{shard} has "
                    f"no surviving replica to stream from (replication "
                    f"factor too low to remove its owners)")
            src = surviving[0]
            out.setdefault(target, []).append(ResizeSource(
                source_node=src.id, index=index, field=field,
                view=view, shard=shard,
                source_host=src.uri.host, source_port=src.uri.port,
                source_scheme=src.uri.scheme))
    return out


def _resolve_source(cluster: Cluster, src: "ResizeSource") -> Node:
    node = cluster.node_by_id(src.source_node)
    if node is None and src.source_host:
        node = Node.from_json({
            "id": src.source_node,
            "uri": {"scheme": src.source_scheme or "http",
                    "host": src.source_host, "port": src.source_port}})
    if node is None:
        raise ConnectionError(
            f"resize source {src.source_node!r} unknown")
    return node


def _fragment_stream_reqs(frag, src: "ResizeSource") -> list[dict]:
    """Chunk one fragment's bits into PTS1 import requests: kind=
    "fragment" payloads (absolute column ids), each bounded by
    Fragment.TRANSFER_CHUNK_BITS, so the target applies bounded batches
    as they arrive and a killed stream resumes from the applied prefix
    (sets are idempotent)."""
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core.fragment import Fragment
    base = int(src.shard) * SHARD_WIDTH
    limit = max(1, int(Fragment.TRANSFER_CHUNK_BITS))
    reqs: list[dict] = []
    rows_buf: list[int] = []
    cols_buf: list[int] = []

    def flush():
        if rows_buf:
            reqs.append({"kind": "fragment", "index": src.index,
                         "field": src.field, "view": src.view,
                         "shard": int(src.shard),
                         "rowIDs": list(rows_buf),
                         "columnIDs": list(cols_buf)})
            rows_buf.clear()
            cols_buf.clear()
    for rid, positions in frag.rows_snapshot():
        i, n = 0, len(positions)
        while i < n:
            take = min(n - i, limit - len(rows_buf))
            cols_buf.extend(int(p) + base for p in positions[i:i + take])
            rows_buf.extend([int(rid)] * take)
            i += take
            if len(rows_buf) >= limit:
                flush()
    flush()
    return reqs


def handle_resize_push(holder, client, cluster: Cluster,
                       message: dict) -> int:
    """SOURCE-side fragment export for serve-through resize: stream the
    requested fragments to the target over the PTS1 import wire (QoS
    internal class — migrations must never starve interactive traffic;
    the target's IngestGate byte budget backpressures us through the
    stream's 429/applied-prefix protocol). Handled synchronously: the
    target's send_message blocks until the push finished (or raises),
    so stream failures surface at the target and fail its ACK."""
    target = Node.from_json(message["target"])
    reqs: list[dict] = []
    for s in message["sources"]:
        src = ResizeSource(**s)
        frag = holder.fragment(src.index, src.field, src.view, src.shard)
        if frag is None:
            continue  # nothing here to stream; catch-up verifies parity
        reqs.extend(_fragment_stream_reqs(frag, src))
    if not reqs:
        return 0
    applied = client.send_import_stream(target, reqs, qos_class="internal")
    stats = getattr(cluster, "stats", None)
    if stats is not None:
        # Logical payload bytes (16 per bit pair on the PTI1 wire).
        stats.count("cluster.resize.bytesStreamed",
                    sum(16 * len(r["rowIDs"]) for r in reqs))
    return applied


#: read-diff-recheck passes a catch-up sync attempts before concluding
#: sustained write pressure is outrunning it and failing the target
#: (safe: the job fails, the old ring stays authoritative).
CATCH_UP_ATTEMPTS = 16


def _catch_up_fragment(holder, client, node: Node,
                       src: "ResizeSource") -> None:
    """Directed (source-authoritative) per-fragment sync: after the bulk
    PTS1 copy, diff block checksums against the source and apply both
    the missing SETS and the stale CLEARS, so a Clear that raced the
    bulk copy is never resurrected. NOT the anti-entropy majority merge
    — with one source and one target, "majority" degenerates to union,
    which can't clear anything.

    Epoch guard (the same read-merge-write discipline as
    cluster/sync.py): snapshot the local shard epoch, read both sides,
    and only apply if the epoch is unchanged — a dual-applied write
    landing mid-read bumps it and forces a re-read. The guard is sound
    against writes racing the APPLY too, because write_fanout applies
    old owners (the source) before dual targets (this node): a write
    whose source-side apply predates our source read is already in the
    snapshot, and one that postdates it reaches this node only after
    bumping our epoch — the next pass sees it. Convergence requires one
    full pass with a stable epoch and ZERO diff."""
    f = holder.field(src.index, src.field)
    if f is None:
        raise LookupError(
            f"resize target field missing: {src.index}/{src.field}")
    v = f.create_view_if_not_exists(src.view)
    frag = v.create_fragment_if_not_exists(src.shard)
    idx = holder.index(src.index)
    epoch = idx.epoch if idx is not None else None

    def shard_epoch():
        if epoch is None:
            return None
        return epoch.shard_vector([src.shard])[int(src.shard)]
    for _ in range(CATCH_UP_ATTEMPTS):
        e0 = shard_epoch()
        remote_sums = client.fragment_blocks(node, src.index, src.field,
                                             src.view, src.shard)
        local_sums = frag.checksum_blocks()
        diff = sorted(b for b in set(remote_sums) | set(local_sums)
                      if remote_sums.get(b) != local_sums.get(b))
        ops: list[tuple[list[tuple[int, int]], bool]] = []
        for block in diff:
            try:
                r_rows, r_cols = client.fragment_block_data(
                    node, src.index, src.field, src.view, src.shard, block)
                remote_pairs = set(zip((int(x) for x in r_rows),
                                       (int(x) for x in r_cols)))
            except LookupError:
                remote_pairs = set()  # source block vanished: all clears
            l_rows, l_cols = frag.block_data(block)
            local_pairs = set(zip((int(x) for x in l_rows),
                                  (int(x) for x in l_cols)))
            sets = sorted(remote_pairs - local_pairs)
            clears = sorted(local_pairs - remote_pairs)
            if sets:
                ops.append((sets, False))
            if clears:
                ops.append((clears, True))
        if e0 is not None and shard_epoch() != e0:
            continue  # a write raced the reads: stale snapshot, re-read
        if not ops:
            return  # converged: zero diff over a stable epoch window
        for pairs, clear in ops:
            frag.bulk_import([r for r, _ in pairs],
                             [c for _, c in pairs], clear=clear)
    raise RuntimeError(
        f"resize catch-up did not converge for {src.index}/{src.field}/"
        f"{src.view}/{src.shard} after {CATCH_UP_ATTEMPTS} passes "
        f"(sustained write pressure); target fails, old ring stays "
        f"authoritative")


def apply_resize_instruction(holder, client, cluster: Cluster,
                             sources: list[dict],
                             schema: list[dict] | None = None,
                             local_id: str | None = None,
                             job_id: str | None = None,
                             coordinator: dict | None = None) -> None:
    """followResizeInstruction (cluster.go:1297), serve-through edition:
    adopt the sender's schema (a joiner starts empty), then — grouped by
    SOURCE node — relay a synchronous resize-push so each source streams
    its fragments here over the PTS1 import wire, then run the per-shard
    directed catch-up sync, bump the shard epoch, and announce the shard
    cut over. Any failure RAISES so the coordinator's completion
    tracking sees this target as failed (reference
    ResizeInstructionComplete, cluster.go:1315)."""
    if schema:
        holder.apply_schema(schema)
    if not sources:
        return
    local_id = local_id or cluster.local_id
    target = cluster.node_by_id(local_id)
    if target is None:
        raise ConnectionError(
            f"resize target {local_id!r} has no membership entry")
    srcs = [ResizeSource(**s) for s in sources]
    by_source: dict[str, list[ResizeSource]] = {}
    for src in srcs:
        by_source.setdefault(src.source_node, []).append(src)
    t_json = target.to_json()
    for _, frags in sorted(by_source.items()):
        node = _resolve_source(cluster, frags[0])
        # Synchronous relay: LocalClient returns the handler's value;
        # the HTTP POST blocks until the source's handler returned.
        # Either way an error raises here and fails this target's ACK.
        client.send_message(node, {"type": "resize-push", "job": job_id,
                                   "target": t_json,
                                   "sources": [asdict(f) for f in frags]})
    by_shard: dict[tuple[str, int], list[ResizeSource]] = {}
    for src in srcs:
        by_shard.setdefault((src.index, int(src.shard)), []).append(src)
    stats = getattr(cluster, "stats", None)
    for (index, shard), frags in sorted(by_shard.items()):
        t0 = time.monotonic()
        for src in frags:
            _catch_up_fragment(holder, client,
                               _resolve_source(cluster, src), src)
        idx = holder.index(index)
        if idx is not None:
            # Cutover pairing invariant (analysis checker
            # resize_cutover): the shard-epoch bump must precede the
            # cutover mark/announce, so any result cached against the
            # pre-cutover epoch is invalid before the new owner can
            # serve a read leg.
            idx.epoch.bump(shard=shard)
        mig = getattr(cluster, "migration", None)
        if mig is not None and (job_id is None or mig.job_id == job_id):
            mig.mark_cutover(index, shard)
        if stats is not None:
            stats.timing("cluster.resize.cutover", time.monotonic() - t0)
            stats.count("cluster.resize.shardsMigrated")
        if job_id and coordinator:
            msg = {"type": "resize-shard-cutover", "job": job_id,
                   "index": index, "shard": shard, "node": local_id}
            if coordinator.get("id") == local_id:
                deliver_cutover(msg, cluster)
            else:
                coord = cluster.node_by_id(coordinator.get("id", ""))
                if coord is None and coordinator.get("uri"):
                    coord = Node.from_json(coordinator)
                if coord is not None:
                    try:  # best-effort: /debug + read-spread signal only
                        client.send_message(coord, msg)
                    except (ConnectionError, RuntimeError, LookupError):
                        pass


def apply_cluster_status(cluster: Cluster, nodes_json: list[dict],
                         holder=None, availability: dict | None = None,
                         replica_n: int | None = None,
                         partition_n: int | None = None,
                         version: int | None = None) -> None:
    """mergeClusterStatus (cluster.go:1943): adopt a broadcast topology
    and, like the reference's NodeStatus, the sender's per-field shard
    availability so new members can route queries for shards they don't
    hold locally. replica_n/partition_n ride along so a joiner booted
    with mismatched settings can't silently compute a different ring.

    The push path enforces the same strictly-newer version gate as the
    pull path (Cluster.merge_membership): a delayed or replayed
    broadcast carrying an OLDER committed topology must not roll the
    ring back — that would resurrect removed members, shift jump-hash
    placement, and let the holder GC delete live fragments. Unversioned
    statuses (version None) predate the version field and are adopted
    as before. Shard availability always merges: it is additive and
    harmless."""
    with cluster._lock:
        stale = (version is not None
                 and int(version) <= cluster.topology_version)
        if not stale:
            # Adopting a committed topology ends any in-flight
            # migration on this member: either this IS the resize's
            # commit (the new ring now owns every moved shard) or a
            # newer topology superseded the job. Clear before the
            # holder cleaner runs so commit-time GC isn't suppressed
            # by the mid-migration guard.
            cluster.migration = None
            if replica_n:
                cluster.replica_n = int(replica_n)
            if partition_n:
                cluster.partition_n = int(partition_n)
            cluster.nodes = sorted((Node.from_json(n) for n in nodes_json),
                                   key=lambda n: n.id)
            if version is not None:
                cluster.topology_version = int(version)
            if not any(n.id == cluster.local_id for n in cluster.nodes):
                # A committed topology that excludes THIS node is a
                # removal notice: enter the terminal REMOVED state so
                # the API gate stays closed — serving reads/writes under
                # a ring we are no longer part of would make them
                # invisible to the rest of the cluster (ADVICE r4 #1).
                from pilosa_tpu.cluster.cluster import STATE_REMOVED
                cluster.set_state(STATE_REMOVED)
            else:
                from pilosa_tpu.cluster.cluster import STATE_REMOVED
                if cluster.state in (STATE_RESIZING, STATE_REMOVED):
                    # The commit broadcast ends the resize on every
                    # peer: clear RESIZING so the recompute below can
                    # run (the _update_state guard defers to the resize
                    # owner). A REMOVED node that appears in a NEWER
                    # committed ring has been re-added by the operator —
                    # the terminal state ends with this commit, not with
                    # a process restart.
                    cluster.set_state(STATE_NORMAL)
                cluster._update_state()
    if not stale:
        cluster.notify_topology()
    if holder is not None and availability:
        for index, fields in availability.items():
            idx = holder.index(index)
            if idx is None:
                continue
            for field, shards in fields.items():
                f = idx.field(field)
                if f is not None:
                    f.add_remote_available_shards(shards)


def apply_cluster_state(cluster: Cluster, state: str) -> None:
    """Peer half of ResizeJob._broadcast_state: adopt a coordinator-
    announced state transition. Entering RESIZING closes this node's API
    gate; leaving it recomputes the steady state from node liveness."""
    from pilosa_tpu.cluster.cluster import STATE_REMOVED
    if cluster.state == STATE_REMOVED:
        return  # terminal: a stray steady-state broadcast (e.g. the
        # abort path's union fan-out) must not reopen a removed node.
    if state == STATE_RESIZING:
        cluster.set_state(STATE_RESIZING)
    else:
        if cluster.state == STATE_RESIZING:
            cluster.set_state(state)
        cluster._update_state()


def holder_availability(holder) -> dict:
    """{index: {field: [shards]}} from a holder's point of view."""
    out: dict = {}
    for iname in holder.index_names():
        idx = holder.index(iname)
        out[iname] = {fname: sorted(f.available_shards())
                      for fname, f in idx.fields.items()}
    return out


class ResizeJob:
    """Coordinator-driven resize. Known limitation for this round: the
    fragment inventory is the coordinator's view (schema + broadcast
    shard availability); remote-only time views are re-synced by
    anti-entropy after the resize."""

    #: how long the coordinator waits for every target's completion ACK.
    #: Generous by design: fragment streaming is bounded by data volume,
    #: not RPC timeouts, now that apply runs off the dispatch request.
    #: A DOWN event fails a pending target's ACK immediately; the
    #: deadline covers the blind spot where a target restarts so fast
    #: the failure detector never sees it down (its in-flight apply is
    #: simply gone, and the job must fail and release the gate rather
    #: than hold it — found by the chaos soak). Operators on flappy
    #: fleets tune it down via PILOSA_TPU_RESIZE_ACK_TIMEOUT.
    try:
        ACK_TIMEOUT = float(
            os.environ.get("PILOSA_TPU_RESIZE_ACK_TIMEOUT", "600"))
    except ValueError:  # malformed env must not make this module (and
        # with it the whole membership control plane) unimportable
        import sys as _sys
        print("PILOSA_TPU_RESIZE_ACK_TIMEOUT is not a number; "
              "using 600s", file=_sys.stderr)
        ACK_TIMEOUT = 600.0

    def __init__(self, cluster: Cluster, holder, client, store=None):
        self.cluster = cluster
        self.holder = holder
        self.client = client
        #: DiskStore (optional) so the commit-time holderCleaner can
        #: unlink the files of fragments it drops.
        self.store = store
        self.state = "RUNNING"
        self.job_id = f"resize-{next(_JOB_SEQ)}"
        self._cond = threading.Condition()
        self._pending: set[str] = set()
        self.completed: list[str] = []
        self.failed: list[str] = []
        self.started_at = time.monotonic()
        self._last_cutover = self.started_at
        #: (index, shard) -> "pending" | "migrated", for /debug/resize.
        self.shard_status: dict[tuple[str, int], str] = {}

    def abort(self) -> None:
        with self._cond:
            self.state = "ABORTED"
            self._cond.notify_all()

    def complete(self, node_id: str, error: str | None) -> None:
        """A target finished applying its instruction (ACK receiver)."""
        with self._cond:
            if node_id not in self._pending:
                return
            self._pending.discard(node_id)
            if error:
                self.failed.append(node_id)
            else:
                self.completed.append(node_id)
            self._cond.notify_all()

    def fail_target(self, node_id: str, error: str) -> None:
        """Force-fail a target even after it ACKed: a dual-write failure
        means its copy diverged, so a completed ACK no longer proves the
        copy is current and the commit must not happen."""
        with self._cond:
            if self.state != "RUNNING":
                return
            self._pending.discard(node_id)
            if node_id not in self.failed:
                self.failed.append(node_id)
            self._cond.notify_all()

    def note_cutover(self, index: str, shard: int, node_id: str) -> None:
        with self._cond:
            self.shard_status[(index, int(shard))] = "migrated"
            self._last_cutover = time.monotonic()

    def snapshot(self) -> dict:
        """Live job state for GET /debug/resize."""
        with self._cond:
            statuses = list(self.shard_status.values())
            migrated = sum(1 for s in statuses if s == "migrated")
            now = time.monotonic()
            return {
                "job": self.job_id,
                "state": self.state,
                "pending": sorted(self._pending),
                "completed": list(self.completed),
                "failed": list(self.failed),
                "shards": {"total": len(statuses),
                           "migrated": migrated,
                           "inFlight": len(statuses) - migrated},
                "runningSeconds": round(now - self.started_at, 3),
                "lastCutoverLagSeconds": round(now - self._last_cutover, 3),
            }

    def _schema_fragments(self):
        out = set()
        for iname in self.holder.index_names():
            idx = self.holder.index(iname)
            for fname, f in idx.fields.items():
                views = set(f.views)
                shards = f.available_shards()
                for vname in views or set():
                    for shard in shards:
                        out.add((iname, fname, vname, shard))
        return sorted(out)

    def run(self, new_nodes: list[Node]) -> str:
        # Coordinator duty gate: a fenced coordinator is (by its own
        # failure detector's evidence) on the minority side of a
        # partition — the majority may be electing a successor right
        # now, and a resize begun here would race its commits.
        if getattr(self.cluster, "fenced", False):
            self.state = "FAILED"
            return self.state
        old_view = Cluster("_old", [Node(id=n.id, uri=n.uri)
                                    for n in self.cluster.nodes],
                           replica_n=self.cluster.replica_n,
                           partition_n=self.cluster.partition_n)
        new_view = Cluster("_new", [Node(id=n.id, uri=n.uri)
                                    for n in new_nodes],
                           replica_n=self.cluster.replica_n,
                           partition_n=self.cluster.partition_n)
        local = self.cluster.node_by_id(self.cluster.local_id)
        coord_json = local.to_json() if local is not None else {
            "id": self.cluster.local_id}
        # Serve-through: NO ring-wide RESIZING gate. The ring keeps
        # serving under the old (authoritative) topology; a resize-begin
        # broadcast installs a MigrationTable on every member so writes
        # dual-apply to each shard's future owners while fragments move,
        # and the single cluster-status commit at the end flips
        # placement atomically. (The reference instead broadcast
        # ClusterStateResizing and closed every node's API for the whole
        # job, cluster.go:1470.)
        begin = {"type": "resize-begin", "job": self.job_id,
                 "coordinator": coord_json,
                 "nodes": [n.to_json() for n in new_nodes],
                 "replicaN": self.cluster.replica_n,
                 "partitionN": self.cluster.partition_n,
                 # Fencing token: peers reject this begin if they have
                 # already adopted a newer topology (deposed coordinator).
                 "fencingToken": self.cluster.fencing_token()}
        # Per-target completion tracking (reference
        # ResizeInstructionComplete + per-node map, cluster.go:1315,
        # :1413-1438): the new topology is committed ONLY after every
        # target acknowledged its instruction; any failure leaves the
        # old topology fully intact. Remote targets apply in the
        # background and ACK via an explicit resize-instruction-complete
        # message, so a long fragment stream never hits an RPC timeout.
        with _JOBS_LOCK:
            _JOBS[self.job_id] = self

        # A target that dies after accepting its dispatch would otherwise
        # stall the job for the full ACK deadline with the resize gate
        # held: let the failure detector's DOWN event fail its pending
        # ACK immediately (the reference aborts the job on node-failure
        # events, cluster.go:1754).
        def on_event(ev):
            if ev.state == "DOWN":
                self.complete(ev.node_id, "node down during resize")

        self.cluster.subscribe(on_event)
        try:
            if self.state == "ABORTED":
                return self.state
            apply_resize_begin(self.cluster, begin)
            # Every LIVE old-ring member must install the table before
            # any fragment moves: a member without it keeps single-
            # applying writes, silently diverging the new copies. A
            # member the failure detector already marked DOWN is skipped
            # (it serves nothing; if it resurrects mid-job its writes
            # are refused by peers' liveness view and it learns the
            # outcome from the commit/sweeps). Joiners are mandatory
            # too: without a table their API gate refuses the dual-write
            # legs about to be aimed at them.
            members = {n.id: n for v in (old_view, new_view)
                       for n in v.nodes}
            for node in members.values():
                if node.id == self.cluster.local_id:
                    continue
                known = self.cluster.node_by_id(node.id)
                if known is not None and known.state == "DOWN":
                    continue
                try:
                    self.client.send_message(node, begin)
                except (ConnectionError, RuntimeError, LookupError):
                    self.failed.append(node.id)
            if self.failed:
                self.state = "FAILED"
                return self.state
            schema = self.holder.schema()
            try:
                instructions = fragment_sources(old_view, new_view,
                                                self._schema_fragments())
            except ValueError:
                self.state = "FAILED"
                raise
            # Every ADDED node gets an instruction even with nothing to
            # fetch: the message carries the schema, which a fresh
            # joiner doesn't have yet.
            old_ids = {n.id for n in old_view.nodes}
            for n in new_view.nodes:
                if n.id not in old_ids:
                    instructions.setdefault(n.id, [])
            with self._cond:
                for sources in instructions.values():
                    for s in sources:
                        self.shard_status.setdefault(
                            (s.index, int(s.shard)), "pending")
            for target_id, sources in sorted(instructions.items()):
                if self.state == "ABORTED":
                    return self.state
                payload = [asdict(s) for s in sources]
                try:
                    if target_id == self.cluster.local_id:
                        apply_resize_instruction(
                            self.holder, self.client, self.cluster,
                            payload, local_id=self.cluster.local_id,
                            job_id=self.job_id, coordinator=coord_json)
                        self.completed.append(target_id)
                    else:
                        node = new_view.node_by_id(target_id)
                        with self._cond:
                            self._pending.add(target_id)
                        # Dispatch only: the target applies in the
                        # background and ACKs with
                        # resize-instruction-complete.
                        self.client.send_message(
                            node, {"type": "resize-instruction",
                                   "job": self.job_id,
                                   "coordinator": coord_json,
                                   "schema": schema,
                                   "sources": payload})
                except (ConnectionError, LookupError, RuntimeError):
                    with self._cond:
                        self._pending.discard(target_id)
                    self.failed.append(target_id)
            # Wait for every dispatched target's ACK (or abort/deadline).
            with self._cond:
                self._cond.wait_for(
                    lambda: not self._pending or self.state == "ABORTED",
                    timeout=self.ACK_TIMEOUT)
                if self.state == "ABORTED":
                    return self.state
                if self._pending:  # deadline: never-ACKed targets failed
                    self.failed.extend(sorted(self._pending))
                    self._pending.clear()
            if self.failed:
                # A target never confirmed its fragments: committing the
                # new topology would route reads to holes. Old topology
                # stays live; operator (or the next join attempt) retries.
                self.state = "FAILED"
                return self.state
            # Commit: broadcast the new topology + shard availability,
            # adopt it locally.
            status = {"type": "cluster-status",
                      "nodes": [n.to_json() for n in new_nodes],
                      "replicaN": self.cluster.replica_n,
                      "partitionN": self.cluster.partition_n,
                      "version": self.cluster.topology_version + 1,
                      "availability": holder_availability(self.holder)}
            # Removed nodes get the commit too (ADVICE r4: they are not
            # in new_nodes, so without this they sit in RESIZING until
            # _recover_stuck_resizing reopens their gate under the stale
            # pre-resize ring — a zombie accepting invisible writes).
            # Receiving a committed status that excludes them flips them
            # to the terminal REMOVED state (apply_cluster_status).
            new_ids = {node.id for node in new_nodes}
            removed = [n for n in self.cluster.nodes if n.id not in new_ids]
            for node in list(new_nodes) + removed:
                if node.id != self.cluster.local_id:
                    try:
                        self.client.send_message(node, status)
                    except (ConnectionError, RuntimeError):
                        pass
            apply_cluster_status(self.cluster, status["nodes"],
                                 version=status["version"])
            # Coordinator-side holderCleaner (holder.go:1126): peers GC
            # on receiving the status broadcast; the coordinator adopted
            # it directly, so GC here (disk half included when a store
            # was attached).
            from pilosa_tpu.cluster.cleaner import clean_holder
            clean_holder(self.holder, self.cluster, store=self.store)
            self.state = "DONE"
            return self.state
        finally:
            self.cluster.unsubscribe(on_event)
            with _JOBS_LOCK:
                _JOBS.pop(self.job_id, None)
            if self.state != "DONE":
                # Non-commit exit (FAILED/ABORTED/exception): drop the
                # migration tables everywhere. The old ring never
                # stopped being authoritative and no shard was ever
                # routed away from its old owner, so this IS the whole
                # rollback — partially migrated copies become garbage
                # the holder cleaner GCs after the next committed
                # topology. Best-effort: a peer that misses the end
                # message drops its table via the stale-migration sweep
                # (_recover_stale_migration) or the next begin/commit.
                end = {"type": "resize-end", "job": self.job_id}
                apply_resize_end(self.cluster, end)
                for node in {n.id: n for v in (old_view, new_view)
                             for n in v.nodes}.values():
                    if node.id == self.cluster.local_id:
                        continue
                    try:
                        self.client.send_message(node, end)
                    except (ConnectionError, RuntimeError, LookupError):
                        pass
            if self.cluster.state == STATE_RESIZING:
                # Non-commit exit (FAILED/ABORTED/exception): reopen the
                # gate everywhere. set_state first (clears RESIZING so
                # _update_state's guard disengages), then RECOMPUTE from
                # node liveness — a peer that died mid-job must yield
                # DEGRADED/STARTING here, not a blind NORMAL.
                self.cluster.set_state(STATE_NORMAL)
                self.cluster._update_state()
                # Union of surviving ring + attempted targets: a FAILED
                # join must reopen the joiner's gate too, even though it
                # never made it into the committed ring.
                self._broadcast_state(
                    STATE_NORMAL,
                    {n.id: n for n in
                     list(self.cluster.nodes) + list(new_nodes)}.values())

    def _broadcast_state(self, state: str, nodes) -> None:
        """Push a cluster-state transition to peers (best-effort: an
        unreachable peer is either dead — its gate is moot — or will
        learn the steady state from the commit broadcast / sweeps)."""
        msg = {"type": "cluster-state", "state": state}
        for node in nodes:
            if node.id == self.cluster.local_id:
                continue
            try:
                self.client.send_message(node, msg)
            except (ConnectionError, RuntimeError, LookupError):
                pass


#: intermediaries asked to confirm an unreachable peer before DOWN
#: (memberlist IndirectChecks analog).
INDIRECT_PROBES = 2


def check_nodes(cluster: Cluster, client, retries: int = 2,
                discover: bool = True) -> list[str]:
    """Failure detector sweep: probe every peer, confirm before marking
    down (reference confirmNodeDown cluster.go:1724-1751: /version probe
    with retry), and — SWIM-style (gossip/gossip.go:43-443) — ask up to
    INDIRECT_PROBES other live members to probe an unreachable peer
    before declaring it down, so an asymmetric partition between THIS
    node and one member doesn't false-positive into node-down repair
    churn. Returns ids whose state changed. ``discover`` adds the
    membership push/pull (one GET per live peer) — callers on a tight
    sweep cadence can run it every few sweeps."""
    changed = []
    reachable = 1  # self
    for node in list(cluster.nodes):
        if node.id == cluster.local_id:
            continue
        alive = False
        for _ in range(retries):
            try:
                client.probe(node)
                alive = True
                break
            except ConnectionError:
                continue
        direct_alive = alive
        indirect_verdicts: dict[str, bool] = {}
        # Indirect confirmation only for a SUSPECT transition (a peer
        # we thought was up going unreachable) — confirming an
        # already-DOWN corpse every sweep would put constant probe load
        # on the intermediaries (memberlist also scopes indirect checks
        # to suspicion).
        if (not alive and node.state != "DOWN"
                and hasattr(client, "indirect_probe")):
            import random
            intermediaries = [n for n in cluster.nodes
                              if n.id not in (cluster.local_id, node.id)
                              and n.state != "DOWN"]
            # Random sample (memberlist's k-random-members): fixed
            # ring-order picks would concentrate confirm load on two
            # nodes and correlate their failure with the suspect's.
            picked = random.sample(intermediaries,
                                   min(INDIRECT_PROBES, len(intermediaries)))
            if len(picked) > 1:
                # Concurrent confirms: serialized probes would add their
                # timeouts to the sweep and delay detecting OTHER
                # failures behind this suspect.
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(len(picked)) as pool:
                    def ask(via, node=node):
                        try:
                            return client.indirect_probe(via, node)
                        except (ConnectionError, OSError, RuntimeError):
                            return False
                    verdicts = list(pool.map(ask, picked))
                indirect_verdicts = {via.id: ok
                                     for via, ok in zip(picked, verdicts)}
                alive = any(verdicts)
            elif picked:
                ok = False
                try:
                    ok = bool(client.indirect_probe(picked[0], node))
                except (ConnectionError, OSError, RuntimeError):
                    pass
                indirect_verdicts = {picked[0].id: ok}
                alive = alive or ok
        # Membership push/pull only over a DIRECTLY-reachable link: a
        # peer alive only via indirect probe is unreachable from here,
        # and a full-timeout GET at it would stall the whole sweep.
        if direct_alive and discover:
            # Transitive membership exchange rides the liveness sweep
            # (memberlist's push/pull, gossip.go:295): a peer holding a
            # STRICTLY NEWER committed topology hands us the whole ring,
            # so discovery doesn't depend on reaching the coordinator —
            # and stale peers can't resurrect removed members.
            try:
                resp = client.nodes(node)
            except (ConnectionError, RuntimeError, LookupError,
                    AttributeError):
                resp = None
            if isinstance(resp, dict) and resp.get("nodes"):
                changed.extend(cluster.merge_membership(
                    resp["nodes"], int(resp.get("version", 0))))
        # A merge_membership above may have REPLACED cluster.nodes with
        # fresh Node objects — re-resolve by id so the liveness
        # transition lands on the live entry, not an orphan of the old
        # list (and skip nodes the merge removed outright).
        live = next((n for n in cluster.nodes if n.id == node.id), None)
        if live is None:
            continue
        if alive:
            reachable += 1
        # Per-peer observation record for GET /debug/membership: what
        # THIS node's detector last saw, not a consensus view.
        cluster.membership_log[live.id] = {
            "state": live.state,
            "lastProbeOk": alive,
            "lastProbeDirect": direct_alive,
            "lastProbeAt": time.time(),
            "indirect": indirect_verdicts,
        }
        if alive and live.state == "DOWN":
            live.state = "READY"
            changed.append(live.id)
            cluster.stats.count("cluster.nodeUp")
            cluster._emit(EVENT_UPDATE, live.id, "READY")
        elif not alive and live.state != "DOWN":
            live.state = "DOWN"
            changed.append(live.id)
            cluster.stats.count("cluster.nodeDown")
            cluster._emit(EVENT_UPDATE, live.id, "DOWN")
    if changed:
        cluster._update_state()
    # Quorum self-fence: this sweep IS our view of the ring — fence
    # when the reachable set (self + direct/indirect-alive peers) is
    # not a strict majority, un-fence when majority returns.
    cluster.observe_quorum(reachable, len(cluster.nodes))
    _recover_stuck_resizing(cluster, client)
    return changed


#: consecutive failure-detector sweeps a coordinator must stay DOWN
#: before a peer concludes a phantom RESIZING state died with it.
RESIZING_COORD_DOWN_SWEEPS = 3


def _recover_stale_migration(cluster: Cluster) -> None:
    """Drop a migration table whose coordinator died mid-job: the
    coordinator's crash killed the only thread that would have sent
    resize-end (or the commit), so without this sweep every member
    dual-applies writes forever against a job that no longer exists.
    Debounced over the same consecutive-DOWN-sweeps window as the
    RESIZING recovery — a coordinator GC pause must not drop tables
    while fragments still move. Dropping is always safe (the old ring
    stayed authoritative); worst case a resurrected coordinator's job
    fails its targets' catch-up and retries."""
    mig = getattr(cluster, "migration", None)
    if mig is None:
        cluster._migration_coord_down_sweeps = 0
        return
    coord_id = mig.coordinator.get("id", "")
    if coord_id == cluster.local_id:
        return  # the local ResizeJob owns this table's lifecycle
    coord = cluster.node_by_id(coord_id)
    if coord is None:
        if cluster.state == STATE_STARTING:
            # A joiner doesn't know the ring yet — the coordinator being
            # unresolvable is expected, not evidence of death.
            return
        down = True  # not in our committed ring: no authority exists
    else:
        down = coord.state == "DOWN"
    if not down:
        cluster._migration_coord_down_sweeps = 0
        return
    sweeps = getattr(cluster, "_migration_coord_down_sweeps", 0) + 1
    cluster._migration_coord_down_sweeps = sweeps
    if sweeps >= RESIZING_COORD_DOWN_SWEEPS:
        cluster._migration_coord_down_sweeps = 0
        cluster.migration = None


def _recover_stuck_resizing(cluster: Cluster, client) -> None:
    """A non-coordinator stuck in RESIZING self-heals here: a removed
    node never receives the commit broadcast (it isn't in the new
    ring), and a coordinator crash mid-job kills the only thread that
    would have restored the state. The coordinator's own view is
    authoritative: if it reports any steady state — or is dead — the
    resize no longer exists and the gate must reopen."""
    _recover_stale_migration(cluster)
    if cluster.state != STATE_RESIZING:
        # Not resizing: clear any debounce left by a PREVIOUS job so the
        # next resize starts its DOWN count from zero.
        cluster._resizing_coord_down_sweeps = 0
        return
    local = cluster.node_by_id(cluster.local_id)
    if local is not None and local.is_coordinator:
        return  # the local ResizeJob owns this state
    coord = next((n for n in cluster.nodes
                  if n.is_coordinator and n.id != cluster.local_id), None)
    over = False
    removed = False
    if coord is None:
        over = True  # no resize authority exists at all
    elif coord.state == "DOWN":
        # A single failed sweep is a weak proxy for "the job died" — a
        # GC pause or blip would reopen the gate while fragments still
        # move, and a write accepted then could be GC'd at commit.
        # Require several consecutive DOWN sweeps before concluding the
        # coordinator (and its job) are gone.
        down = getattr(cluster, "_resizing_coord_down_sweeps", 0) + 1
        cluster._resizing_coord_down_sweeps = down
        over = down >= RESIZING_COORD_DOWN_SWEEPS
    else:
        cluster._resizing_coord_down_sweeps = 0
        try:
            resp = client.nodes(coord)
            if isinstance(resp, dict):
                # Only an AFFIRMATIVE steady-state report clears the
                # gate; errors/old peers keep it closed.
                over = (resp.get("state") is not None
                        and resp["state"] != STATE_RESIZING)
                # A steady-state ring that no longer contains this node
                # means the commit (whose broadcast we evidently missed)
                # removed us: terminal REMOVED, not a reopened zombie
                # serving the stale pre-resize ring (ADVICE r4 #1).
                peer_nodes = resp.get("nodes")
                if over and isinstance(peer_nodes, list) and peer_nodes:
                    removed = not any(
                        isinstance(n, dict) and n.get("id") == cluster.local_id
                        for n in peer_nodes)
        except (ConnectionError, RuntimeError, LookupError,
                AttributeError):
            over = False
    if over:
        from pilosa_tpu.cluster.cluster import STATE_REMOVED
        cluster._resizing_coord_down_sweeps = 0
        if removed:
            cluster.set_state(STATE_REMOVED)
        else:
            cluster.set_state(STATE_NORMAL)
            cluster._update_state()
